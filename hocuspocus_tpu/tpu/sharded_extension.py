"""Doc-partitioned merge plane: N independent planes on one chip.

The integrate kernel's microbatch latency scales with the ARENA WIDTH
it sweeps — at the 100k-doc regime one monolithic plane pays a
full-population pass per flush (round-3 capture: 226 ms p99 vs the
50 ms budget). Documents never interact (SURVEY.md §2.2: doc axis is
the data-parallel dimension), so the product fix is the same move the
reference prescribes for scale-out — "split users by a document
identifier" (`docs/guides/scalability.md`) — applied INSIDE one
process: a router extension hashing each document onto one of N
`TpuMergeExtension` shards, each with its own plane, flush pipeline
and broadcast timers. A microbatch then sweeps one shard's arena
(population/N docs), pipelining naturally across shards because every
shard flushes on its own schedule.

This composes with everything the single-plane extension does (native
text lane, RLE arena, serving, recycling): the shard is a full
TpuMergeExtension; the router only dispatches hooks by name hash.

SCOPE: all N shards share ONE chip (and one `DeviceLane`) — this
router bounds arena-sweep width, not device count. For true data
parallelism across chips — one arena + lane + governor per device,
with load-aware placement and cross-cell migration — use the
multi-device cell plane (tpu/cells.py, `--tpu-devices`,
docs/guides/multi-device.md).
"""

from __future__ import annotations

import zlib
from typing import Optional

from ..server.types import Extension, Payload
from .merge_plane import TpuMergeExtension


class ShardedTpuMergeExtension(Extension):
    """Routes per-document hooks to one of N TpuMergeExtension shards.

    Scheduling (tpu/scheduler.py): all shards share ONE device-lane
    arbiter — they contend for the same chip, so their flushes,
    hydration batches and compaction sweeps must be ordered by priority
    class, not by whichever timer fires first. Each shard's flush and
    broadcast timers get a deterministic phase offset (i/N of the
    interval) so N shards stop tick-aligning their dispatches, and the
    shared warm registry makes shard 2..N skip grid shapes shard 1
    already compiled (the jitted steps are module-level — one XLA cache
    per process, not N)."""

    priority = 900

    def __init__(self, shards: int = 4, **extension_kwargs) -> None:
        if shards < 1:
            raise ValueError("shards must be >= 1")
        lane = extension_kwargs.pop("lane", None)
        if lane is None:
            from .scheduler import get_device_lane

            lane = get_device_lane()
        interval = float(extension_kwargs.get("flush_interval_ms", 5.0))
        extension_kwargs.pop("phase_offset_ms", None)
        self.shards = [
            TpuMergeExtension(
                lane=lane,
                phase_offset_ms=(
                    index * interval / shards if shards > 1 else None
                ),
                **extension_kwargs,
            )
            for index in range(shards)
        ]
        # False disables arbitration in every shard; mirror that here
        self.lane = self.shards[0].lane

    def shard_for(self, document_name: str) -> TpuMergeExtension:
        digest = zlib.crc32(document_name.encode("utf-8"))
        return self.shards[digest % len(self.shards)]

    # -- lifecycle hooks (broadcast) ---------------------------------------

    async def on_listen(self, data: Payload) -> None:
        for shard in self.shards:
            await shard.on_listen(data)

    async def on_destroy(self, data: Payload) -> None:
        for shard in self.shards:
            await shard.on_destroy(data)

    # -- per-document hooks (routed) ---------------------------------------

    async def after_load_document(self, data: Payload) -> None:
        await self.shard_for(data.document_name).after_load_document(data)

    async def on_change(self, data: Payload) -> None:
        await self.shard_for(data.document_name).on_change(data)

    async def after_unload_document(self, data: Payload) -> None:
        await self.shard_for(data.document_name).after_unload_document(data)

    # -- supervisor surface (tpu/supervisor.py) ----------------------------

    def planes(self) -> list:
        return [shard.plane for shard in self.shards]

    def servings(self) -> list:
        return [shard.serving for shard in self.shards if shard.serving is not None]

    def degrade_all(self) -> None:
        for shard in self.shards:
            shard.degrade_all()

    def cancel_timers(self) -> None:
        for shard in self.shards:
            shard.cancel_timers()

    async def reonboard(self, document, instance=None) -> None:
        await self.shard_for(document.name).reonboard(document, instance)

    # -- aggregate observability -------------------------------------------

    @property
    def counters(self) -> dict:
        total: dict = {}
        for shard in self.shards:
            for key, value in shard.plane.counters.items():
                total[key] = total.get(key, 0) + value
        return total

    def scheduler_snapshot(self) -> dict:
        """Lane + per-shard governor state for /debug/scheduler."""
        return {
            "lane": None if self.lane is None else self.lane.snapshot(),
            "governors": [
                None if shard.governor is None else shard.governor.snapshot()
                for shard in self.shards
            ],
            "phase_offsets_ms": [
                shard.phase_offset_ms for shard in self.shards
            ],
        }

    def served_docs(self) -> int:
        return sum(len(shard._docs) for shard in self.shards)

    def pending_ops(self) -> int:
        return sum(shard.plane.pending_ops() for shard in self.shards)

    def is_served(self, document_name: str) -> bool:
        return document_name in self.shard_for(document_name)._docs
