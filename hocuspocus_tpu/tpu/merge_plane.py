"""The TPU merge plane: cross-document update queue + batched integrate.

Replaces the reference's per-connection apply loop (SURVEY.md §3.3 hot
loop) with a micro-batched device step: updates from ALL documents are
lowered to dense ops, padded into (K slots, D docs) tensors, and
integrated by one jitted kernel call. Exposed as `TpuMergeExtension`
hooking the same onChange boundary the reference's extensions use, with
the CPU document remaining the authoritative fallback.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import numpy as np

from ..server.types import Extension, Payload
from .kernels import (
    DocState,
    KIND_INSERT,
    NONE_CLIENT,
    OpBatch,
    extract_live_mask,
    make_empty_state,
)
from .lowering import DenseOp, DocLowerer, units_to_text
from .pallas_kernels import integrate_op_slots_fast


class MergePlane:
    """Device-resident arenas for up to `num_docs` documents."""

    def __init__(self, num_docs: int = 256, capacity: int = 4096, max_slots_per_flush: int = 16) -> None:
        self.num_docs = num_docs
        self.capacity = capacity
        self.max_slots_per_flush = max_slots_per_flush
        self.state: DocState = make_empty_state(num_docs, capacity)
        self.slots: dict[str, int] = {}
        self.free: list[int] = list(range(num_docs - 1, -1, -1))
        self.lowerers: dict[int, DocLowerer] = {}
        self.queues: dict[int, list[DenseOp]] = {}
        # char payloads never touch the device: slot assignment in the
        # append-only arena is deterministic (arena slot = arrival
        # index), so shipped insert payloads land here, indexed by slot
        self.char_logs: dict[int, list[int]] = {}
        self.projected_len: dict[int, int] = {}
        self.total_integrated = 0

    # -- registry ----------------------------------------------------------

    def register(self, name: str) -> Optional[int]:
        if name in self.slots:
            return self.slots[name]
        if not self.free:
            return None
        slot = self.free.pop()
        self.slots[name] = slot
        self.lowerers[slot] = DocLowerer()
        self.queues[slot] = []
        self.char_logs[slot] = []
        self.projected_len[slot] = 0
        return slot

    def release(self, name: str) -> None:
        slot = self.slots.pop(name, None)
        if slot is None:
            return
        self.lowerers.pop(slot, None)
        self.queues.pop(slot, None)
        self.char_logs.pop(slot, None)
        self.projected_len.pop(slot, None)
        self._clear_slot(slot)
        self.free.append(slot)

    def _clear_slot(self, slot: int) -> None:
        empty = make_empty_state(1, self.capacity)
        self.state = DocState(
            *(
                field.at[slot].set(empty_field[0])
                for field, empty_field in zip(self.state, empty)
            )
        )

    def is_supported(self, name: str) -> bool:
        slot = self.slots.get(name)
        if slot is None:
            return False
        return not self.lowerers[slot].unsupported

    # -- queueing ----------------------------------------------------------

    def enqueue_update(self, name: str, update: bytes) -> None:
        slot = self.slots.get(name)
        if slot is None:
            slot = self.register(name)
            if slot is None:
                return
        lowerer = self.lowerers[slot]
        if lowerer.unsupported:
            return
        ops = lowerer.lower_update(update)
        # host-side mirror of the device capacity check: the lowerer
        # guarantees causal readiness, so inserts succeed until the
        # arena overflows — at which point the doc is CPU-only forever;
        # stop queueing (and logging payloads) instead of leaking
        projected = self.projected_len[slot] + sum(
            op.run_len for op in ops if op.kind == KIND_INSERT
        )
        if projected > self.capacity:
            lowerer.unsupported = True
            self.queues[slot].clear()
            return
        self.projected_len[slot] = projected
        self.queues[slot].extend(ops)

    def pending_ops(self) -> int:
        return sum(len(q) for q in self.queues.values())

    # -- device step -------------------------------------------------------

    def flush(self) -> int:
        """Integrate queued ops in (K, D) batches. Returns ops integrated."""
        from ..observability.tracing import get_tracer

        tracer = get_tracer()
        total = 0
        while self.pending_ops() > 0:
            needed = min(
                max(len(q) for q in self.queues.values()),
                self.max_slots_per_flush,
            )
            # round K up to a power of two to bound jit recompilations
            k = 1
            while k < needed:
                k *= 2
            ops = self._build_batch(k)
            # int(count) is a sound completion barrier: both integrate
            # paths data-depend the count on the output state via
            # lax.optimization_barrier (buffer *readiness* of aliased
            # Pallas outputs is not trustworthy — see bench.py sync())
            if tracer.enabled:
                with tracer.device_span("merge_plane.integrate", slots=k) as span:
                    self.state, count = integrate_op_slots_fast(self.state, ops)
                    count = int(count)
                    span.set("integrated", count)
            else:
                self.state, count = integrate_op_slots_fast(self.state, ops)
                count = int(count)
            total += count
        self.total_integrated += total
        return total

    def _build_batch(self, k: int) -> OpBatch:
        d = self.num_docs
        kind = np.zeros((k, d), np.int32)
        client = np.zeros((k, d), np.uint32)
        clock = np.zeros((k, d), np.int32)
        run_len = np.zeros((k, d), np.int32)
        left_client = np.full((k, d), NONE_CLIENT, np.uint32)
        left_clock = np.zeros((k, d), np.int32)
        right_client = np.full((k, d), NONE_CLIENT, np.uint32)
        right_clock = np.zeros((k, d), np.int32)
        for slot, queue in self.queues.items():
            take = queue[:k]
            del queue[:k]
            log = self.char_logs[slot]
            for i, op in enumerate(take):
                kind[i, slot] = op.kind
                client[i, slot] = op.client
                clock[i, slot] = op.clock
                run_len[i, slot] = op.run_len
                left_client[i, slot] = op.left_client
                left_clock[i, slot] = op.left_clock
                right_client[i, slot] = op.right_client
                right_clock[i, slot] = op.right_clock
                if op.kind == KIND_INSERT:  # payload goes to the host log
                    log.extend(op.chars)
        import jax.numpy as jnp

        return OpBatch(
            kind=jnp.asarray(kind),
            client=jnp.asarray(client),
            clock=jnp.asarray(clock),
            run_len=jnp.asarray(run_len),
            left_client=jnp.asarray(left_client),
            left_clock=jnp.asarray(left_clock),
            right_client=jnp.asarray(right_client),
            right_clock=jnp.asarray(right_clock),
        )

    # -- extraction --------------------------------------------------------

    def text(self, name: str) -> Optional[str]:
        """Decode a document's live text from device state.

        Surrogate-pair handling mirrors Yjs splice semantics: Yjs
        replaces both halves with U+FFFD whenever an item split lands
        inside a pair. The arena never splits (deletes are id-range
        tombstones), so a pair decodes as a real character only when its
        two units are id-consecutive from one client AND rank-adjacent
        (no tombstones between) — every split scenario breaks one of
        those, yielding the same U+FFFD output as the CPU path.
        """
        slot = self.slots.get(name)
        if slot is None:
            return None
        if self.lowerers[slot].unsupported:
            return None  # doc fell back to the CPU path (content/overflow)
        overflow = bool(np.asarray(self.state.overflow)[slot])
        if overflow:
            return None
        log = np.asarray(self.char_logs[slot], dtype=np.int64)
        if len(log) != int(np.asarray(self.state.length)[slot]):
            # host log and arena desynced (op rejected on device) — the
            # CPU document stays authoritative; retire the doc from the
            # plane so it stops consuming queue/log/kernel resources
            self.lowerers[slot].unsupported = True
            self.queues[slot].clear()
            self.char_logs[slot] = []
            return None
        live = np.asarray(extract_live_mask(self.state))[slot]
        occupied = np.nonzero(live)[0]
        ranks_all = np.asarray(self.state.rank)[slot][occupied]
        order = np.argsort(ranks_all)
        sel = occupied[order]
        ranks = ranks_all[order]
        chars = log[sel]
        clients = np.asarray(self.state.id_client)[slot][sel]
        clocks = np.asarray(self.state.id_clock)[slot][sel]
        out: list[int] = []
        i = 0
        count = len(chars)
        while i < count:
            c = int(chars[i])
            if 0xD800 <= c <= 0xDBFF:
                if (
                    i + 1 < count
                    and 0xDC00 <= int(chars[i + 1]) <= 0xDFFF
                    and clients[i + 1] == clients[i]
                    and clocks[i + 1] == clocks[i] + 1
                    and ranks[i + 1] == ranks[i] + 1
                ):
                    out.append(c)
                    out.append(int(chars[i + 1]))
                    i += 2
                    continue
                out.append(0xFFFD)
            elif 0xDC00 <= c <= 0xDFFF:
                out.append(0xFFFD)
            else:
                out.append(c)
            i += 1
        return units_to_text(out)


class TpuMergeExtension(Extension):
    """Mirrors live documents onto the TPU merge plane via onChange.

    The CPU document stays authoritative for serving in this round; the
    plane shadows every supported text document and is the substrate for
    batched merge serving (bench.py drives it directly).
    """

    priority = 900

    def __init__(
        self,
        num_docs: int = 256,
        capacity: int = 4096,
        flush_interval_ms: float = 5.0,
        plane: Optional[MergePlane] = None,
    ) -> None:
        self.plane = plane or MergePlane(num_docs=num_docs, capacity=capacity)
        self.flush_interval_ms = flush_interval_ms
        self._flush_handle: Optional[asyncio.TimerHandle] = None

    async def after_load_document(self, data: Payload) -> None:
        from ..crdt import encode_state_as_update

        self.plane.register(data.document_name)
        snapshot = encode_state_as_update(data.document)
        self.plane.enqueue_update(data.document_name, snapshot)
        self._schedule_flush()

    async def on_change(self, data: Payload) -> None:
        self.plane.enqueue_update(data.document_name, data.update)
        self._schedule_flush()

    async def after_unload_document(self, data: Payload) -> None:
        self.plane.release(data.document_name)

    async def on_destroy(self, data: Payload) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self.plane.flush()

    def _schedule_flush(self) -> None:
        if self._flush_handle is not None:
            return

        def run() -> None:
            self._flush_handle = None
            self.plane.flush()

        self._flush_handle = asyncio.get_event_loop().call_later(
            self.flush_interval_ms / 1000, run
        )
