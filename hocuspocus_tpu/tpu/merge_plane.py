"""The TPU merge plane: cross-document update queue + batched integrate.

Replaces the reference's per-connection apply loop (SURVEY.md §3.3 hot
loop) with a micro-batched device step: updates from ALL documents are
lowered to dense ops, padded into (K slots, D docs) tensors, and
integrated by one jitted kernel call. Exposed as `TpuMergeExtension`
hooking the same onChange boundary the reference's extensions use, with
the CPU document remaining the authoritative fallback.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import numpy as np

from ..server.types import Extension, Payload
from .kernels import (
    DocState,
    KIND_INSERT,
    NONE_CLIENT,
    OpBatch,
    extract_live_mask,
    make_empty_state,
)
from .lowering import DenseOp, DocLowerer, units_to_text
from .pallas_kernels import integrate_op_slots_fast


class MergePlane:
    """Device-resident arenas for up to `num_docs` documents."""

    def __init__(self, num_docs: int = 256, capacity: int = 4096, max_slots_per_flush: int = 16) -> None:
        self.num_docs = num_docs
        self.capacity = capacity
        self.max_slots_per_flush = max_slots_per_flush
        self.state: DocState = make_empty_state(num_docs, capacity)
        self.slots: dict[str, int] = {}
        self.free: list[int] = list(range(num_docs - 1, -1, -1))
        self.lowerers: dict[int, DocLowerer] = {}
        self.queues: dict[int, list[DenseOp]] = {}
        # char payloads never touch the device: slot assignment in the
        # append-only arena is deterministic (arena slot = arrival
        # index), so shipped insert payloads land here, indexed by slot
        self.char_logs: dict[int, list[int]] = {}
        # every op the device consumed, in arena order, with the char-log
        # offset of its payload — the host half of the serving path
        self.op_logs: dict[int, list[tuple[DenseOp, int]]] = {}
        # root type name per slot (needed to encode origin-less items)
        self.root_names: dict[int, str] = {}
        self.projected_len: dict[int, int] = {}
        self._retired: set[int] = set()
        self.total_integrated = 0
        # degradation accounting: at 100k docs nobody notices 3% of docs
        # silently falling off the plane unless it is counted
        self.counters: dict[str, int] = {
            "docs_retired_overflow": 0,
            "docs_retired_desync": 0,
            "docs_retired_unsupported": 0,
            "docs_retired_capacity": 0,
            "docs_retired_fallback": 0,
            "sync_serves": 0,
            "plane_broadcasts": 0,
            "cpu_fallbacks": 0,
        }

    # -- registry ----------------------------------------------------------

    def register(self, name: str) -> Optional[int]:
        if name in self.slots:
            return self.slots[name]
        if not self.free:
            return None
        slot = self.free.pop()
        self.slots[name] = slot
        self.lowerers[slot] = DocLowerer()
        self.queues[slot] = []
        self.char_logs[slot] = []
        self.op_logs[slot] = []
        self.projected_len[slot] = 0
        return slot

    def release(self, name: str) -> None:
        slot = self.slots.pop(name, None)
        if slot is None:
            return
        self.lowerers.pop(slot, None)
        self.queues.pop(slot, None)
        self.char_logs.pop(slot, None)
        self.op_logs.pop(slot, None)
        self.root_names.pop(slot, None)
        self.projected_len.pop(slot, None)
        self._retired.discard(slot)
        self._clear_slot(slot)
        self.free.append(slot)

    def retire_slot(self, slot: int, reason: str) -> None:
        """Permanently degrade a doc to the CPU path (slot stays allocated
        until unload so the name keeps resolving to 'unsupported')."""
        lowerer = self.lowerers.get(slot)
        if lowerer is None:
            return
        if slot not in self._retired:
            # counted via _retired, not the unsupported flag: the lowerer
            # flips unsupported itself on unrepresentable content
            self._retired.add(slot)
            self.counters[f"docs_retired_{reason}"] = (
                self.counters.get(f"docs_retired_{reason}", 0) + 1
            )
        lowerer.unsupported = True
        self.queues[slot].clear()
        self.char_logs[slot] = []
        self.op_logs[slot] = []

    def _clear_slot(self, slot: int) -> None:
        empty = make_empty_state(1, self.capacity)
        self.state = DocState(
            *(
                field.at[slot].set(empty_field[0])
                for field, empty_field in zip(self.state, empty)
            )
        )

    def is_supported(self, name: str) -> bool:
        slot = self.slots.get(name)
        if slot is None:
            return False
        return not self.lowerers[slot].unsupported

    # -- queueing ----------------------------------------------------------

    def enqueue_update(self, name: str, update: bytes) -> int:
        """Lower + queue one update; returns the number of ops queued."""
        slot = self.slots.get(name)
        if slot is None:
            slot = self.register(name)
            if slot is None:
                return 0
        lowerer = self.lowerers[slot]
        if lowerer.unsupported:
            return 0
        ops = lowerer.lower_update(update)
        if lowerer.unsupported:
            self.retire_slot(slot, "unsupported")
            return 0
        # host-side mirror of the device capacity check: the lowerer
        # guarantees causal readiness, so inserts succeed until the
        # arena overflows — at which point the doc is CPU-only forever;
        # stop queueing (and logging payloads) instead of leaking
        projected = self.projected_len[slot] + sum(
            op.run_len for op in ops if op.kind == KIND_INSERT
        )
        if projected > self.capacity:
            self.retire_slot(slot, "capacity")
            return 0
        self.projected_len[slot] = projected
        self.queues[slot].extend(ops)
        return len(ops)

    def pending_ops(self) -> int:
        return sum(len(q) for q in self.queues.values())

    # -- device step -------------------------------------------------------

    def flush(self) -> int:
        """Integrate queued ops in (K, D) batches. Returns ops integrated."""
        from ..observability.tracing import get_tracer

        tracer = get_tracer()
        total = 0
        while self.pending_ops() > 0:
            needed = min(
                max(len(q) for q in self.queues.values()),
                self.max_slots_per_flush,
            )
            # round K up to a power of two to bound jit recompilations
            k = 1
            while k < needed:
                k *= 2
            ops = self._build_batch(k)
            # int(count) is a sound completion barrier: both integrate
            # paths data-depend the count on the output state via
            # lax.optimization_barrier (buffer *readiness* of aliased
            # Pallas outputs is not trustworthy — see bench.py sync())
            if tracer.enabled:
                with tracer.device_span("merge_plane.integrate", slots=k) as span:
                    self.state, count = integrate_op_slots_fast(self.state, ops)
                    count = int(count)
                    span.set("integrated", count)
            else:
                self.state, count = integrate_op_slots_fast(self.state, ops)
                count = int(count)
            total += count
        self.total_integrated += total
        return total

    def _build_batch(self, k: int) -> OpBatch:
        d = self.num_docs
        kind = np.zeros((k, d), np.int32)
        client = np.zeros((k, d), np.uint32)
        clock = np.zeros((k, d), np.int32)
        run_len = np.zeros((k, d), np.int32)
        left_client = np.full((k, d), NONE_CLIENT, np.uint32)
        left_clock = np.zeros((k, d), np.int32)
        right_client = np.full((k, d), NONE_CLIENT, np.uint32)
        right_clock = np.zeros((k, d), np.int32)
        for slot, queue in self.queues.items():
            take = queue[:k]
            del queue[:k]
            log = self.char_logs[slot]
            op_log = self.op_logs[slot]
            for i, op in enumerate(take):
                kind[i, slot] = op.kind
                client[i, slot] = op.client
                clock[i, slot] = op.clock
                run_len[i, slot] = op.run_len
                left_client[i, slot] = op.left_client
                left_clock[i, slot] = op.left_clock
                right_client[i, slot] = op.right_client
                right_clock[i, slot] = op.right_clock
                op_log.append((op, len(log)))
                if op.kind == KIND_INSERT:  # payload goes to the host log
                    log.extend(op.chars)
        import jax.numpy as jnp

        return OpBatch(
            kind=jnp.asarray(kind),
            client=jnp.asarray(client),
            clock=jnp.asarray(clock),
            run_len=jnp.asarray(run_len),
            left_client=jnp.asarray(left_client),
            left_clock=jnp.asarray(left_clock),
            right_client=jnp.asarray(right_client),
            right_clock=jnp.asarray(right_clock),
        )

    # -- extraction --------------------------------------------------------

    def text(self, name: str) -> Optional[str]:
        """Decode a document's live text from device state.

        Surrogate-pair handling mirrors Yjs splice semantics: Yjs
        replaces both halves with U+FFFD whenever an item split lands
        inside a pair. The arena never splits (deletes are id-range
        tombstones), so a pair decodes as a real character only when its
        two units are id-consecutive from one client AND rank-adjacent
        (no tombstones between) — every split scenario breaks one of
        those, yielding the same U+FFFD output as the CPU path.
        """
        slot = self.slots.get(name)
        if slot is None:
            return None
        if self.lowerers[slot].unsupported:
            return None  # doc fell back to the CPU path (content/overflow)
        overflow = bool(np.asarray(self.state.overflow)[slot])
        if overflow:
            self.retire_slot(slot, "overflow")
            return None
        log = np.asarray(self.char_logs[slot], dtype=np.int64)
        if len(log) != int(np.asarray(self.state.length)[slot]):
            # host log and arena desynced (op rejected on device) — the
            # CPU document stays authoritative; retire the doc from the
            # plane so it stops consuming queue/log/kernel resources
            self.retire_slot(slot, "desync")
            return None
        live = np.asarray(extract_live_mask(self.state))[slot]
        occupied = np.nonzero(live)[0]
        ranks_all = np.asarray(self.state.rank)[slot][occupied]
        order = np.argsort(ranks_all)
        sel = occupied[order]
        ranks = ranks_all[order]
        chars = log[sel]
        clients = np.asarray(self.state.id_client)[slot][sel]
        clocks = np.asarray(self.state.id_clock)[slot][sel]
        out: list[int] = []
        i = 0
        count = len(chars)
        while i < count:
            c = int(chars[i])
            if 0xD800 <= c <= 0xDBFF:
                if (
                    i + 1 < count
                    and 0xDC00 <= int(chars[i + 1]) <= 0xDFFF
                    and clients[i + 1] == clients[i]
                    and clocks[i + 1] == clocks[i] + 1
                    and ranks[i + 1] == ranks[i] + 1
                ):
                    out.append(c)
                    out.append(int(chars[i + 1]))
                    i += 2
                    continue
                out.append(0xFFFD)
            elif 0xDC00 <= c <= 0xDFFF:
                out.append(0xFFFD)
            else:
                out.append(c)
            i += 1
        return units_to_text(out)


class _MultipleRoots(Exception):
    pass


class TpuMergeExtension(Extension):
    """Puts live documents on the TPU merge plane via onChange.

    Two modes:
    - shadow (serve=False): the plane mirrors every supported text
      document; the CPU document serves (round-1 behavior).
    - serve (serve=True): for supported docs the plane IS the serving
      path — SyncStep2 replies come from device state
      (`Document.sync_source`), per-update CPU fan-out is suppressed
      (`Document.broadcast_source`) and replaced by one merged broadcast
      per device flush. Any degradation (unsupported content, overflow,
      desync) falls the doc back to the CPU path, shipping the full CPU
      state once so receivers that only saw plane broadcasts are whole.

    Replaces the reference's per-connection apply+broadcast loop
    (`packages/server/src/MessageReceiver.ts:195-213`,
    `packages/server/src/Document.ts:228-240`).
    """

    priority = 900

    def __init__(
        self,
        num_docs: int = 256,
        capacity: int = 4096,
        flush_interval_ms: float = 5.0,
        plane: Optional[MergePlane] = None,
        serve: bool = False,
    ) -> None:
        self.plane = plane or MergePlane(num_docs=num_docs, capacity=capacity)
        self.flush_interval_ms = flush_interval_ms
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self.serve = serve
        self.serving = None
        self._docs: dict[str, object] = {}  # name -> server Document being served
        if serve:
            from .serving import PlaneServing

            self.serving = PlaneServing(self.plane)

    # -- hooks ---------------------------------------------------------------

    async def after_load_document(self, data: Payload) -> None:
        from ..crdt import encode_state_as_update

        name = data.document_name
        slot = self.plane.register(name)
        snapshot = encode_state_as_update(data.document)
        queued = self.plane.enqueue_update(name, snapshot)
        if self.serve and slot is not None and self.plane.is_supported(name):
            document = data.document
            try:
                root = self._resolve_root(document)
            except _MultipleRoots:
                self.plane.retire_slot(slot, "unsupported")
                self._schedule_flush()
                return
            if root is not None:
                self.plane.root_names[slot] = root
            from .serving import TpuSyncSource

            # receivers get pre-load state via sync, not broadcast
            self.serving.broadcast_cursor[slot] = queued
            document.sync_source = TpuSyncSource(self.serving, name, document)
            document.broadcast_source = self
            self._docs[name] = document
        self._schedule_flush()

    async def on_change(self, data: Payload) -> None:
        if self.serve and data.document_name in self._docs:
            return  # already captured synchronously in try_capture
        self.plane.enqueue_update(data.document_name, data.update)
        self._schedule_flush()

    async def after_unload_document(self, data: Payload) -> None:
        name = data.document_name
        document = self._docs.pop(name, None)
        if document is not None:
            document.sync_source = None
            document.broadcast_source = None
        slot = self.plane.slots.get(name)
        if slot is not None:
            self.serving and self.serving.broadcast_cursor.pop(slot, None)
        self.plane.release(name)

    async def on_destroy(self, data: Payload) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush()

    # -- serving: update capture (called by Document._handle_update) ---------

    def try_capture(self, document, update: bytes, origin) -> bool:
        """Claim an update for plane-batched broadcast. False = CPU fan-out."""
        name = document.name
        if not self.serve or name not in self._docs:
            return False
        plane = self.plane
        slot = plane.slots.get(name)
        if slot is None or not plane.is_supported(name):
            self._fallback_to_cpu(document)
            return False
        plane.enqueue_update(name, update)
        if not plane.is_supported(name):
            # this very update degraded the doc; it broadcasts via CPU
            self._fallback_to_cpu(document)
            return False
        if plane.root_names.get(slot) is None:
            try:
                root = self._resolve_root(document)
            except _MultipleRoots:
                plane.retire_slot(slot, "unsupported")
                self._fallback_to_cpu(document)
                return False
            if root is not None:
                plane.root_names[slot] = root
        self._schedule_flush()
        return True

    def _resolve_root(self, document) -> Optional[str]:
        """The single content-bearing root type name, None if empty.

        The dense arena models ONE text sequence per doc; a second
        content-bearing root would interleave, so it degrades the doc.
        """
        roots = [
            key
            for key, ytype in document.share.items()
            if ytype._start is not None or getattr(ytype, "_map", None)
        ]
        if len(roots) > 1:
            raise _MultipleRoots()
        return roots[0] if roots else None

    def _fallback_to_cpu(self, document) -> None:
        name = document.name
        if self._docs.pop(name, None) is None:
            return  # already degraded
        document.sync_source = None
        document.broadcast_source = None
        slot = self.plane.slots.get(name)
        if slot is not None:
            self.plane.retire_slot(slot, "fallback")
        self.plane.counters["cpu_fallbacks"] += 1
        # receivers may hold plane broadcasts only up to the last flush;
        # ship the full CPU state once (dedup makes it a cheap no-op for
        # anyone already current)
        from ..crdt import encode_state_as_update

        document.broadcast_update_frame(encode_state_as_update(document))

    # -- flush ---------------------------------------------------------------

    def _flush(self) -> None:
        try:
            self.plane.flush()
            if self.serve:
                self.serving.refresh()
        except Exception:
            # a plane-level device error must not strand captured docs:
            # degrade every served doc to the CPU path (full-state
            # broadcast) rather than silently dropping their updates
            from ..server import logger as _logger_mod

            _logger_mod.log_error("plane flush failed; degrading served docs to CPU")
            for _, document in list(self._docs.items()):
                try:
                    self._fallback_to_cpu(document)
                except Exception:
                    _logger_mod.log_error(f"CPU fallback failed for {document.name!r}")
            return
        if not self.serve:
            return
        for name, document in list(self._docs.items()):
            # per-doc guard: the stated safety model is "any serving
            # error degrades that doc to the CPU path" — an exception
            # here must neither strand this doc's ops nor skip the
            # remaining docs' broadcasts
            try:
                if self.serving.slot_healthy(name) is None:
                    self._fallback_to_cpu(document)
                    continue
                update = self.serving.build_broadcast(name)
                if update is not None:
                    document.broadcast_update_frame(update)
            except Exception:
                from ..server import logger as _logger_mod

                _logger_mod.log_error(
                    f"plane broadcast failed for {name!r}; degrading to CPU path"
                )
                try:
                    self._fallback_to_cpu(document)
                except Exception:
                    _logger_mod.log_error(f"CPU fallback failed for {name!r}")

    def _schedule_flush(self) -> None:
        if self._flush_handle is not None:
            return

        def run() -> None:
            self._flush_handle = None
            self._flush()

        self._flush_handle = asyncio.get_event_loop().call_later(
            self.flush_interval_ms / 1000, run
        )
