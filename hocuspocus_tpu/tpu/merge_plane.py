"""The TPU merge plane: cross-document update queue + batched integrate.

Replaces the reference's per-connection apply loop (SURVEY.md §3.3 hot
loop) with a micro-batched device step: updates from ALL documents are
lowered to dense ops, padded into (K slots, S sequences) tensors, and
integrated by one jitted kernel call. Exposed as `TpuMergeExtension`
hooking the same onChange boundary the reference's extensions use, with
the CPU document remaining the authoritative fallback.

Arena rows are *sequences*, not documents: a plain text doc occupies
one row; a tree doc (ProseMirror XML) occupies one row per element
child-list, so the same YATA kernel integrates every sequence of every
document in one batch. Map items (Y.Map entries, XML attributes) are
host-side last-writer-wins records that never ride the device — they
go straight to the doc's serve log.
"""

from __future__ import annotations

import asyncio
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..aio import spawn_tracked
from ..observability.device_watch import CompileTracker, pytree_nbytes
from ..observability.flight_recorder import get_flight_recorder
from ..observability.tracing import UpdateTraceBook, get_tracer
from ..server.types import Extension, Payload
from .kernels import (
    KIND_DELETE,
    KIND_INSERT,
    NONE_CLIENT,
    OpBatch,
    extract_live_mask,
    make_empty_state,
)
from .lowering import DenseOp, DocLowerer, units_to_text


@dataclass
class LogRec:
    """One serve-log record: an op the plane integrated (device or host).

    slot is None for host-only map items; unit_off indexes the slot's
    unit log where the op's payload starts (sequence inserts only).
    """

    op: DenseOp
    slot: Optional[int] = None
    unit_off: int = 0
    # op arrived from a peer instance (redis origin): excluded from the
    # cross-instance window republish — every peer already received it
    # from the original publisher (echo amplification would be O(N^2))
    remote: bool = False


@dataclass
class PlaneDoc:
    """Per-document host state: sequence registry + serve log."""

    name: str
    lowerer: DocLowerer = field(default_factory=DocLowerer)
    seqs: dict[tuple, int] = field(default_factory=dict)  # seq_key -> slot
    serve_log: list[LogRec] = field(default_factory=list)
    # delete ranges that target host-side map items (client, clock, len)
    map_tombstones: list[tuple] = field(default_factory=list)
    retired: bool = False
    retire_reason: Optional[str] = None  # first reason wins (see retire_doc)
    # native text lane (see native/text_lane.cpp): when set, the whole
    # host path — lowering, serve log, unit log, dispatch queue — lives
    # in C++; serve_log/unit_logs here are lazy materializations for
    # the cold serving paths, cached under lane_cache_key
    lane_slot: Optional[int] = None
    lane_cache_key: Optional[tuple] = None
    # residency compaction (tpu/residency.py): client -> ([starts],
    # [(start, end, left_id, right_id)]) for id ranges the tombstone-GC
    # kernel removed from the device — future ops whose origins land in
    # a removed range re-anchor to the recorded live neighbor
    origin_remap: dict = field(default_factory=dict)


class _FlushStaging:
    """One reusable host-side batch staging buffer, sized at the max
    flush shape (K_max, D). Each batch takes a `(k, b)` view of it —
    zero fresh numpy allocations on the flush hot path (the old builder
    allocated 8 fresh (K, D) arrays per batch, which dominated host
    time at the 100k-doc regime). MergePlane keeps TWO of these and
    alternates per batch (double buffering): the host build of batch
    i+1 must never mutate arrays whose upload for batch i may still be
    in flight on an asynchronously-transferring runtime."""

    __slots__ = ("fields", "slots")

    # per-field reset value: left/right client columns default to the
    # NONE_CLIENT sentinel, everything else to zero (KIND_NOOP)
    _DEFAULTS = (0, 0, 0, 0, NONE_CLIENT, 0, NONE_CLIENT, 0)
    _DTYPES = (
        np.int32, np.uint32, np.int32, np.int32,
        np.uint32, np.int32, np.uint32, np.int32,
    )

    def __init__(self, k_max: int, num_docs: int) -> None:
        self.fields = tuple(
            np.full((k_max, num_docs), default, dtype)
            for default, dtype in zip(self._DEFAULTS, self._DTYPES)
        )
        self.slots = np.zeros((num_docs,), np.int32)

    def views(self, k: int, b: int) -> tuple:
        """(k, b) views of the 8 op fields, reset to noop defaults."""
        views = tuple(field[:k, :b] for field in self.fields)
        for view, default in zip(views, self._DEFAULTS):
            view[...] = default
        return views

    def slot_view(self, b: int) -> np.ndarray:
        return self.slots[:b]

    def nbytes(self, k: int, b: int, with_slots: bool) -> int:
        per_field = sum(dtype().itemsize for dtype in self._DTYPES)
        return k * b * per_field + (b * 4 if with_slots else 0)


class _AppendStaging:
    """Run-merge twin of _FlushStaging: the append fast path ships only
    three (K, B) run fields (client, clock, run_len) plus the (B,)
    routing vector — under half the dense op layout's bytes — and only
    the run_len view needs resetting per batch (run_len == 0 IS the
    noop sentinel; stale client/clock under a zero length are never
    read by the kernel)."""

    __slots__ = ("client", "clock", "run_len", "slots")

    def __init__(self, k_max: int, num_docs: int) -> None:
        self.client = np.zeros((k_max, num_docs), np.uint32)
        self.clock = np.zeros((k_max, num_docs), np.int32)
        self.run_len = np.zeros((k_max, num_docs), np.int32)
        self.slots = np.zeros((num_docs,), np.int32)

    def views(self, k: int, b: int) -> tuple:
        views = (
            self.client[:k, :b],
            self.clock[:k, :b],
            self.run_len[:k, :b],
        )
        views[2][...] = 0
        return views

    def slot_view(self, b: int) -> np.ndarray:
        return self.slots[:b]

    def nbytes(self, k: int, b: int) -> int:
        return k * b * 12 + b * 4


class MergePlane:
    """Device-resident arenas for up to `num_docs` sequences.

    (The parameter keeps its historical name; for plain text docs
    sequences == documents. Tree docs consume one row per sequence.)

    Pass a `jax.sharding.Mesh` (axes "doc" × "unit", see
    tpu/sharding.py) to back the arenas with multi-chip sharded state:
    the sequence axis is data-parallel over the mesh's doc axis (ICI
    collectives only for the global op count), the arena axis optionally
    sequence-parallel over the unit axis. Host-side logic (queues,
    serve logs, health readbacks) is identical either way.
    """

    def __init__(
        self,
        num_docs: int = 256,
        capacity: int = 4096,
        max_slots_per_flush: int = 16,
        mesh=None,
        arena: str = "unit",
        device=None,
    ) -> None:
        """arena: "unit" (one arena slot per UTF-16 unit; capacity =
        units) or "rle" (one entry per run of consecutively-typed
        units; capacity = ENTRIES). The RLE arena's cost grows with op
        count + fragmentation instead of cumulative unit count, so
        long-lived busy docs survive churn that exhausts the unit
        arena — the device-side replacement for yjs GC semantics
        (reference `packages/server/src/types.ts:152-155` yDocOptions.gc).

        device: pin the whole arena (and every upload) to ONE jax
        device — the multi-device cell plane (tpu/cells.py) builds one
        plane per chip this way. The arena state is committed to the
        device, so every jitted step runs there; uploads device_put
        straight to it (never touching the default device). Mutually
        exclusive with mesh= (a mesh IS a device layout).
        """
        if arena not in ("unit", "rle"):
            raise ValueError(f"unknown arena {arena!r}")
        if device is not None and mesh is not None:
            raise ValueError("pass device= or mesh=, not both")
        self.arena = arena
        self.device = device
        self.num_docs = num_docs
        self.capacity = capacity
        self.max_slots_per_flush = max_slots_per_flush
        self.mesh = mesh
        # serializes flush + device readbacks when the extension runs
        # flushes off the event loop (direct synchronous use — tests,
        # benches — never contends)
        self.flush_lock = asyncio.Lock()
        # thread-level companion: flush() donates the old state buffers
        # to the kernel, so a reader interleaving with an executor-side
        # flush can observe garbage (and must never RETIRE a doc based
        # on it). flush() holds this for the duration of the device
        # step; synchronous readers (text, health checks, the sync
        # serve adapter) acquire it. Reentrant so a sync serve can hold
        # it across its own flush()+reads sequence.
        self._step_lock = threading.RLock()
        self._sharded_step = None
        self._sharded_sparse_step = None
        self._sharded_compact_step = None
        self._sharded_append_step = None
        self._op_shardings = None
        self._sparse_op_shardings = None
        self._slots_sharding = None
        self._append_field_sharding = None
        if mesh is not None:
            from .sharding import (
                make_sharded_rle_sparse_step,
                make_sharded_rle_state,
                make_sharded_rle_step,
                make_sharded_sparse_step,
                make_sharded_state,
                make_sharded_step,
                ops_sharding,
                sparse_ops_sharding,
            )

            doc_axis = mesh.shape["doc"]
            unit_axis = mesh.shape["unit"]
            if num_docs % doc_axis or capacity % unit_axis:
                raise ValueError(
                    f"num_docs ({num_docs}) must be a multiple of the mesh doc "
                    f"axis ({doc_axis}) and capacity ({capacity}) a multiple of "
                    f"the unit axis ({unit_axis})"
                )
            from .sharding import (
                make_sharded_compact_step,
                make_sharded_rle_compact_step,
            )

            from .sharding import (
                make_sharded_append_step,
                make_sharded_rle_append_step,
            )

            if arena == "rle":
                self.state = make_sharded_rle_state(mesh, num_docs, capacity)
                self._sharded_step = make_sharded_rle_step(mesh)
                self._sharded_sparse_step = make_sharded_rle_sparse_step(mesh)
                self._sharded_compact_step = make_sharded_rle_compact_step(mesh)
                self._sharded_append_step = make_sharded_rle_append_step(mesh)
            else:
                self.state = make_sharded_state(mesh, num_docs, capacity)
                self._sharded_step = make_sharded_step(mesh)
                self._sharded_sparse_step = make_sharded_sparse_step(mesh)
                self._sharded_compact_step = make_sharded_compact_step(mesh)
                self._sharded_append_step = make_sharded_append_step(mesh)
            self._op_shardings = ops_sharding(mesh)
            self._sparse_op_shardings, self._slots_sharding = sparse_ops_sharding(
                mesh
            )
            from jax.sharding import NamedSharding, PartitionSpec

            self._append_field_sharding = NamedSharding(
                mesh, PartitionSpec(None, None)
            )
        else:
            self.state = self._make_empty(num_docs, capacity)
            if device is not None:
                # COMMIT the arena to its chip: jit follows committed
                # input placement, so every step (flush, canary, warm,
                # compact) runs on this device with no resharding
                import jax

                self.state = jax.device_put(self.state, device)
        self.docs: dict[str, PlaneDoc] = {}
        self.free: list[int] = list(range(num_docs - 1, -1, -1))
        self.slot_owner: dict[int, str] = {}  # slot -> doc name
        self.queues: dict[int, list[DenseOp]] = {}
        # slots with (possibly) queued ops: per-batch bookkeeping —
        # depth scan, drain, dispatch — walks THIS set, O(busy), never
        # the full queue registry, O(D). Maintained lock-free under the
        # GIL: enqueue_update adds AFTER every extend (unconditionally),
        # so a drain-side discard that races an enqueue is always
        # repaired by the enqueuer's own add; a stale member whose
        # queue emptied elsewhere (retire/release also discard) is
        # pruned at the next depth scan. Native-lane queues are not
        # tracked here — the lane keeps its own registry of nonempty
        # queues in C++ (lane_queue_max / lane_drain are O(lane slots)).
        self._busy_slots: set[int] = set()
        # per-slot insert units handed to the device so far / as of the
        # last completed flush. Serve logs are written at ENQUEUE time
        # (so broadcasts never wait on the device); health checks
        # therefore compare device lengths against the VALIDATED
        # snapshot — the dispatch tally at the moment the readback was
        # taken — never against the (optimistically ahead) host logs.
        # ndarrays so the post-flush sweep is one vectorized compare
        # over every slot instead of a Python loop over every doc.
        self.dispatched_units = np.zeros(num_docs, np.int64)
        self.validated_units = np.zeros(num_docs, np.int64)
        # monotonic plane-wide dispatch tally, bumped ONLY at the two
        # dispatch sites below — never by slot rebinds or residency
        # rebuilds (hydration credits per-slot counters wholesale). The
        # fleet autoscaler (fleet/controller.py) diffs this for a load
        # RATE that stays honest while docs migrate between cells.
        self.dispatched_total = 0
        # minimal-work run merge (the sequential fast path): the flush
        # classifier routes a drained column to the O(new ops) append
        # program only when every op chains off the column's RANK TAIL
        # — the id of the last unit in rank order, tracked host-side so
        # eligibility costs no device read. A tail is (client, clock)
        # with client == NONE_CLIENT meaning "empty row"; _tail_known
        # gates the whole check (False -> the column takes the full
        # integrate, and the slot joins _tail_dirty so the next flush
        # cycle's health readback re-arms it with one fused tail_probe
        # over the dirty slots — never an O(D) sweep). Rows start, and
        # are cleared back to, known-empty; full-integrate columns and
        # residency compaction (rank remaps) invalidate.
        self.run_merge_enabled = True
        self._tail_client = np.full(num_docs, NONE_CLIENT, np.uint32)
        self._tail_clock = np.zeros(num_docs, np.int64)
        self._tail_known = np.ones(num_docs, bool)
        self._tail_dirty: set[int] = set()
        # slots currently bound to a live (non-retired) doc: the post-
        # flush health sweep masks with this so freed/retired rows
        # compared against stale caches can't read as desyncs
        self.slot_live = np.zeros(num_docs, bool)
        # per-slot binding generation, bumped at every alloc/release/
        # retire. Health snapshots (_sync_health) record the generations
        # they were taken under; a compare is only meaningful when the
        # snapshot's generation matches the slot's current one —
        # otherwise the cached device row belongs to a previous tenant
        # of the slot and must not condemn the new one.
        self.slot_gen = np.zeros(num_docs, np.int64)
        self.last_gen: Optional[np.ndarray] = None
        # bumped whenever device state may have changed (a flush cycle
        # completed, a slot was cleared): consumers caching device
        # readbacks (serving's tombstone cache) key on (slot_gen, this)
        self.flush_epoch = 0
        # docs with new serve-log records since the last broadcast pass
        self.dirty: set[str] = set()
        # last combined health readback (see _sync_health): the remote-
        # attached runtime charges ~a full RTT per transfer, so the
        # flush cycle fetches lengths+overflow as ONE array and callers
        # adopt these instead of re-reading device state
        self.last_lengths: Optional[np.ndarray] = None
        self.last_overflows: Optional[np.ndarray] = None
        # unit payloads never touch the device: slot assignment in the
        # append-only arena is deterministic (arena slot = arrival
        # index), so shipped payloads land here, indexed by slot. An
        # entry is an int UTF-16 unit for text, or the decoded Content
        # object for rich units (formats/embeds/types/values).
        self.unit_logs: dict[int, list] = {}
        self.projected_len: dict[int, int] = {}
        self.total_integrated = 0
        # degradation accounting: at 100k docs nobody notices 3% of docs
        # silently falling off the plane unless it is counted
        self.counters: dict[str, int] = {
            "docs_retired_overflow": 0,
            "docs_retired_desync": 0,
            "docs_retired_unsupported": 0,
            "docs_retired_capacity": 0,
            "docs_retired_fallback": 0,
            "docs_retired_plane_full": 0,
            "docs_retired_lane_demote": 0,
            "docs_recycled": 0,
            # residency subsystem (tpu/residency.py): slots as a managed
            # cache — idle docs snapshot off, cold docs re-admit through
            # the hydration queue, pressured rows compact in place
            "docs_evicted": 0,
            "docs_hydrated": 0,
            "docs_compacted": 0,
            "hydrations_declined": 0,
            "compactions_declined": 0,
            "sync_serves": 0,
            # join-storm sync cache (serving.SyncFrameCache): joiners
            # sharing a (doc, state-vector) within one flush epoch pay
            # one encode, not one each
            "sync_cache_hits": 0,
            "sync_cache_misses": 0,
            "sync_cache_evictions": 0,
            # on-device catch-up encode: slots whose tombstone read
            # shipped as the packed device readback vs the full-row
            # host gather (pack-width overflow or pack disabled)
            "sync_encode_device": 0,
            "sync_encode_host": 0,
            "plane_broadcasts": 0,
            "cpu_fallbacks": 0,
            # flush-engine accounting: staging buffers are allocated
            # once and reused (the regression suite pins allocs flat
            # while reuses grow), and sparse vs dense says which
            # dispatch layout flush cycles actually take
            "flush_staging_allocs": 0,
            "flush_staging_reuses": 0,
            "flush_batches_sparse": 0,
            "flush_batches_dense": 0,
            # minimal-work run merge: ops dispatched through the
            # append fast path vs the full-row integrate, plus the
            # fast-path batch count (the sparse/dense counters above
            # keep counting only full-integrate batches)
            "flush_batches_fast": 0,
            "flush_fast_ops": 0,
            "flush_slow_ops": 0,
        }
        # last completed flush cycle's stage breakdown (exported as
        # gauges by observability/extension.py; reported by bench.py's
        # sparse-load pass): host build / upload / device+readback ms,
        # the (K, B) shape dispatched, busy width and fraction, bytes
        # shipped. Overwritten per cycle, never accumulated.
        self.flush_stats: dict[str, float] = {
            "build_ms": 0.0,
            "upload_ms": 0.0,
            "dispatch_ms": 0.0,
            "device_sync_ms": 0.0,
            "busy_slots": 0,
            "busy_fraction": 0.0,
            "batch_k": 0,
            "batch_b": 0,
            "batches": 0,
            "upload_bytes": 0,
            # per-cycle fast/slow split (run-merge classifier): the
            # fraction is this cycle's, the counters above accumulate
            "fast_path_ops": 0,
            "slow_path_ops": 0,
            "fast_path_fraction": 0.0,
        }
        # residency manager seam (tpu/residency.py): set by the manager
        # at construction. retire_doc consults it to preserve host logs
        # through compactable retires; observability exports the stats.
        self.residency = None
        self.residency_stats: dict[str, float] = {
            "evicted_docs": 0,
            "evicted_bytes": 0,
            "hydration_queue_depth": 0,
            "hydration_queue_peak": 0,
            "hydrations_inflight": 0,
            "hydration_p50_ms": 0.0,
            "hydration_p99_ms": 0.0,
            "last_hydration_batch": 0,
            "last_eviction_ms": 0.0,
            "last_compaction_ms": 0.0,
        }
        # double-buffered staging (see _FlushStaging): allocated on the
        # first flush, alternated per batch so building batch i+1 never
        # mutates arrays batch i's upload may still be reading. The
        # alternation alone only guarantees ONE batch of separation, so
        # _staging_inflight remembers each buffer's last uploaded device
        # arrays and _staging_for blocks on them before handing the
        # buffer out again — on an asynchronously-transferring runtime
        # a 3+-batch cycle must not reset staging[0] while batch 0's
        # transfer is still in flight (two dispatches have passed by
        # then, so the block is ~always a no-op).
        self._staging: "Optional[list[_FlushStaging]]" = None
        self._staging_inflight: "list[Optional[tuple]]" = [None, None]
        # fast-path twin of the staging pair: 3 run fields + routing,
        # same double-buffer + inflight-retire discipline, alternated
        # on its own batch counter (fast and slow batches interleave
        # freely within a cycle)
        self._append_staging: "Optional[list[_AppendStaging]]" = None
        self._append_inflight: "list[Optional[tuple]]" = [None, None]
        self._append_batches = 0
        # native text lane (enable_lane): the C++ host path for plain-
        # text docs. _lane_banned remembers docs that demoted (rich
        # content) so re-onboarding goes straight to the Python path.
        self._lane = None
        self._lane_codec = None
        self._lane_banned: set[str] = set()
        # update-lifecycle trace pipeline (observability/tracing.py):
        # the capture seam stamps sampled updates here; the flush loop
        # below carries their trace ids through drain → build → upload
        # → device → readback, and the broadcast pass closes them. One
        # truth test per flush batch when tracing is idle.
        self.update_traces = UpdateTraceBook()
        # device runtime watch (observability/device_watch.py): every
        # jitted dispatch — warmup, canary, live flush batch — is
        # classified fresh-compile vs cache-hit per (site, shape), and
        # fresh compiles past the warm grid raise the recompile-storm
        # alarm. device_stats accumulates the HBM/stall side: readback-
        # barrier time and the biggest single-cycle upload.
        self.compile_watch = CompileTracker()
        self.device_stats: dict[str, float] = {
            "readback_stall_ms_total": 0.0,
            "readback_stalls": 0,
            "upload_bytes_peak": 0,
        }
        # short-TTL memo for memory_stats (one scrape = one pytree walk)
        self._memory_stats_cache: "tuple[float, Optional[dict]]" = (0.0, None)
        # device-lane arbiter seam (tpu/scheduler.py): set by the owning
        # extension. The plane never admits itself — its CLIENTS (flush
        # engine, hydration, compaction, canary, warmup) hold the lane;
        # the dispatch sites below only ACCOUNT each device dispatch as
        # in-lane or bypass, so the scheduler-accounting test can pin
        # "no dispatch bypasses the arbiter" on the scheduled paths.
        self.lane = None

    def _note_dispatch(self, site: str, batches: int = 1) -> None:
        if self.lane is not None:
            self.lane.note_dispatch(site, batches)

    # -- arena dispatch ----------------------------------------------------

    def _make_empty(self, num_docs: int, capacity: int):
        if self.arena == "rle":
            from .kernels_rle import make_empty_rle_state

            return make_empty_rle_state(num_docs, capacity)
        return make_empty_state(num_docs, capacity)

    def _step_fn(self):
        if self._sharded_step is not None:
            return self._sharded_step
        if self.arena == "rle":
            from .pallas_kernels_rle import integrate_op_slots_rle_fast

            return integrate_op_slots_rle_fast
        from .pallas_kernels import integrate_op_slots_fast

        return integrate_op_slots_fast

    def _sparse_step_fn(self):
        """The sparse (busy-doc) twin of _step_fn: takes (state, (K, B)
        ops, (B,) slot routing)."""
        if self._sharded_sparse_step is not None:
            return self._sharded_sparse_step
        if self.arena == "rle":
            from .pallas_kernels_rle import integrate_op_slots_rle_sparse_fast

            return integrate_op_slots_rle_sparse_fast
        from .pallas_kernels import integrate_op_slots_sparse_fast

        return integrate_op_slots_sparse_fast

    def _compact_step_fn(self):
        """The compact (tombstone-GC / defragment) kernel for this
        arena: takes (state, (B,) slot routing), returns (state,
        per-slot packed sizes). Called by the residency manager
        (tpu/residency.py) under the step lock."""
        if self._sharded_compact_step is not None:
            return self._sharded_compact_step
        if self.arena == "rle":
            from .pallas_kernels_rle import compact_doc_rows_rle_fast

            return compact_doc_rows_rle_fast
        from .pallas_kernels import compact_doc_rows_fast

        return compact_doc_rows_fast

    def _append_step_fn(self):
        """The run-append fast-path kernel: takes (state, (K, B) client,
        clock, run_len, (B,) slot routing), returns (state, applied-run
        count). Dispatched only for columns the flush classifier proved
        all-sequential (see _classify_fast)."""
        if self._sharded_append_step is not None:
            return self._sharded_append_step
        if self.arena == "rle":
            from .pallas_kernels_rle import append_run_slots_rle_sparse_fast

            return append_run_slots_rle_sparse_fast
        from .pallas_kernels import append_run_slots_sparse_fast

        return append_run_slots_sparse_fast

    def _tail_probe_fn(self):
        """The rank-tail id readback kernel for this arena: (state, (W,)
        slots) -> (2W,) uint32 [clients..., clocks...]. Used by
        _sync_health to re-arm tails the full-integrate path or a
        compaction invalidated."""
        if self.arena == "rle":
            from .kernels_rle import tail_probe_rle

            return tail_probe_rle
        from .kernels import tail_probe

        return tail_probe

    # -- native text lane --------------------------------------------------

    def enable_lane(self) -> bool:
        """Switch on the C++ host path for plain-text docs (see
        native/text_lane.cpp). Safe no-op when the codec is missing."""
        if self._lane is not None:
            return True
        from ..native import get_codec

        codec = get_codec()
        # gate on the NEWEST lane symbol: a stale prebuilt .so (build()
        # failed but the old module imported) must degrade to the safe
        # no-op, not AttributeError inside the serve path
        if codec is None or not hasattr(codec, "lane_window_sm"):
            return False
        self._lane_codec = codec
        self._lane = codec.lane_new()
        return True

    def register_lane(self, name: str) -> Optional[PlaneDoc]:
        """Register `name` on the native text lane (one slot, opened
        eagerly). Returns None when the lane is off / banned for this
        doc / the plane is full — caller falls back to register()."""
        if self._lane is None or name in self._lane_banned:
            return None
        doc = self.docs.get(name)
        if doc is not None:
            return doc if doc.lane_slot is not None else None
        if not self.free:
            return None
        slot = self.free.pop()
        doc = PlaneDoc(name)
        doc.lane_slot = slot
        self.docs[name] = doc
        self.slot_owner[slot] = name
        self.queues[slot] = []  # stays empty: ops queue natively
        self.unit_logs[slot] = []  # lazy materialization target
        self.projected_len[slot] = 0
        self.dispatched_units[slot] = 0
        self.validated_units[slot] = 0
        self.slot_live[slot] = True
        self.slot_gen[slot] += 1
        self._set_tail_empty(slot)
        self._lane_codec.lane_open(self._lane, slot)
        return doc

    def _enqueue_lane(
        self, doc: PlaneDoc, update: bytes, presync: bool, remote: bool
    ) -> int:
        slot = doc.lane_slot
        res = self._lane_codec.lane_apply(self._lane, slot, update, presync, remote)
        if res is None:
            # rich/tree/map content: this doc needs the Python path.
            # The ban makes the re-onboard (load-time retry or recycle)
            # take the plain register() route.
            self._lane_banned.add(doc.name)
            self.retire_doc(doc.name, "lane_demote")
            return 0
        ops_added, queued_units, queued_ops, root = res
        if root is not None and not doc.seqs:
            doc.seqs[("root", root)] = slot
        # RLE cost counts device-bound QUEUE entries, not serve-log
        # records: host-only GC records never consume arena entries
        # (mirrors the Python path routing GC to map_out)
        cost = queued_ops if self.arena == "rle" else queued_units
        projected = self.projected_len[slot] + cost
        if projected > self.capacity:
            self.retire_doc(doc.name, "capacity")
            return 0
        self.projected_len[slot] = projected
        if ops_added:
            self.dirty.add(doc.name)
        return ops_added

    def materialize_lane(self, doc: PlaneDoc) -> None:
        """Fill doc.serve_log / unit_logs / lowerer.known from the
        native lane for the Python serving paths (cold/stale syncs,
        text(), the RLE payload index). Cached on the log lengths, so
        repeated serves of an unchanged doc pay one export."""
        if doc.lane_slot is None or self._lane is None:
            return
        slot = doc.lane_slot
        key = self._lane_codec.lane_log_len(self._lane, slot)
        if doc.lane_cache_key == key:
            return
        ops, units_bytes, known, root = self._lane_codec.lane_export(
            self._lane, slot
        )
        self.unit_logs[slot] = np.frombuffer(
            units_bytes, np.dtype("<u2")
        ).tolist()
        parent = ("root", root) if root is not None else None
        recs = []
        for kind, client, clock, run_len, lc, lk, rc, rk, unit_off, flags in ops:
            gc = bool(flags & 2)
            op = DenseOp(
                kind=kind,
                client=client,
                clock=clock,
                run_len=run_len,
                left_client=lc,
                left_clock=lk,
                right_client=rc,
                right_clock=rk,
                deleted_content=bool(flags & 1),
                gc=gc,
                presync=bool(flags & 4),
                # mirrors the Python lowerer: the wire parent only
                # exists on origin-less items (and never on deletes/gc)
                parent=(
                    parent
                    if (
                        kind == KIND_INSERT
                        and not gc
                        and lc == NONE_CLIENT
                        and rc == NONE_CLIENT
                    )
                    else None
                ),
            )
            recs.append(
                LogRec(
                    op=op,
                    # gc records are host-only in the Python path
                    slot=None if gc else slot,
                    unit_off=unit_off,
                    remote=bool(flags & 8),
                )
            )
        doc.serve_log = recs
        doc.lowerer.known = dict(known)
        doc.lane_cache_key = key

    # -- registry ----------------------------------------------------------

    def register(self, name: str) -> PlaneDoc:
        doc = self.docs.get(name)
        if doc is None:
            doc = PlaneDoc(name)
            self.docs[name] = doc
        return doc

    def _alloc_seq(self, doc: PlaneDoc, seq_key: tuple) -> Optional[int]:
        slot = doc.seqs.get(seq_key)
        if slot is not None:
            return slot
        if not self.free:
            return None
        slot = self.free.pop()
        doc.seqs[seq_key] = slot
        self.slot_owner[slot] = doc.name
        self.queues[slot] = []
        self.unit_logs[slot] = []
        self.projected_len[slot] = 0
        self.dispatched_units[slot] = 0
        self.validated_units[slot] = 0  # freed slots keep length 0 too
        self.slot_live[slot] = True
        self.slot_gen[slot] += 1
        self._set_tail_empty(slot)
        return slot

    def note_trace(self, name: str) -> Optional[int]:
        """Capture-seam stamp: give one just-enqueued update a lifecycle
        trace id (sampled). Called by try_capture and the benches."""
        return self.update_traces.stamp(name)

    def release(self, name: str) -> None:
        doc = self.docs.pop(name, None)
        if doc is None:
            return
        self.dirty.discard(name)
        self.update_traces.drop(name)
        # Serialization: release() only runs from unload paths that hold
        # the extension's flush_lock (see TpuMergeExtension._flush_now
        # docstring), so no executor-side flush is in flight here —
        # _clear_slot may rebuild self.state without racing a device
        # step that donated its buffers. Do NOT take _step_lock on the
        # event loop: it can be held across a device step or a warmup
        # compile (tens of seconds cold), freezing every websocket.
        slots = set(doc.seqs.values())
        if doc.lane_slot is not None:
            slots.add(doc.lane_slot)  # may predate root discovery
            self._lane_codec.lane_close(self._lane, doc.lane_slot)
        for slot in slots:
            self.slot_owner.pop(slot, None)
            self.queues.pop(slot, None)
            self._busy_slots.discard(slot)
            self.unit_logs.pop(slot, None)
            self.projected_len.pop(slot, None)
            self.dispatched_units[slot] = 0
            self.validated_units[slot] = 0
            self.slot_live[slot] = False
            self.slot_gen[slot] += 1
            self.free.append(slot)
        # ONE fused device rebuild for every released row (a tree doc
        # spans many): the old per-slot _clear_slot rebuilt the whole
        # state pytree once per sequence
        self._clear_slots(sorted(slots))

    def retire_doc(self, name: str, reason: str, count: bool = True) -> None:
        """Permanently degrade a doc to the CPU path (rows stay allocated
        until unload so the name keeps resolving to 'unsupported').

        count=False marks the doc retired without incrementing the
        degradation counter — used when a failed RECYCLE re-retires the
        fresh registration of an incident that was already counted, so
        the counters keep meaning 'degradation incidents', not retire
        calls."""
        doc = self.docs.get(name)
        if doc is None:
            return
        if not doc.retired:
            doc.retired = True
            doc.retire_reason = reason
            # strict key access: every retire reason must be pre-declared
            # in __init__ so metrics exporters that bind to the counter
            # keys at configure time (observability/extension.py) can
            # never miss a degradation class added later
            if count:
                self.counters[f"docs_retired_{reason}"] += 1
            get_flight_recorder().record(name, "retire", reason=reason)
        self.update_traces.drop(name)
        doc.lowerer.unsupported = True
        # residency seam: a row-exhaustion retire keeps its host logs so
        # the compaction path (tpu/residency.py) can rebuild the doc in
        # place — a declined attempt calls drop_doc_logs to finish this.
        # Judged on the STICKY first reason, not this call's: the CPU
        # fallback re-retires with "fallback" and must not destroy the
        # logs a capacity retire just preserved.
        preserve = self.residency is not None and self.residency.wants_logs(
            doc, doc.retire_reason
        )
        if preserve:
            # the residency sweep visits preserved docs proactively, so
            # an idle retired doc doesn't hold these logs until its
            # next edit
            self.residency.note_preserved(doc.name)
        else:
            doc.serve_log = []
            doc.map_tombstones = []
        self.dirty.discard(name)
        # LOCK-FREE by documented invariant (not oversight): retires run
        # on the event loop (enqueue degrades, broadcast-timer fallback)
        # while an executor-side _build_batch may be slicing these same
        # queues under _step_lock. Taking that lock here would block the
        # loop for a device step or warmup compile. Safe without it:
        # (a) _build_batch's take/del is linearizable against clear()
        #     (it deletes exactly len(take) front items it captured);
        # (b) ops captured into `take` before the clear still dispatch,
        #     but land in rows whose generation is bumped below —
        #     slot_gen/slot_live masking excludes them from every health
        #     compare, and the rows stay inert until release() clears
        #     them under the extension flush_lock;
        # (c) unit_logs is REBOUND (not mutated): an in-flight serve
        #     holding the old list keeps a consistent snapshot.
        for slot in doc.seqs.values():
            self._tail_known[slot] = False  # rows go inert: never fast-path
            self._tail_dirty.discard(slot)
            if not preserve:
                # preserve-mode keeps the QUEUES too: those ops are
                # already in the serve/unit logs and the lowerer's known
                # clocks, so dropping them here would leave the arena
                # permanently behind the host mirrors — the compaction
                # path drains them into the (inert, uncleared) rows
                # before rebuilding instead
                self.queues[slot].clear()
                self._busy_slots.discard(slot)
                self.unit_logs[slot] = []
            self.slot_live[slot] = False
            self.slot_gen[slot] += 1
        if doc.lane_slot is not None:
            # lane slots may predate root discovery (not yet in seqs)
            slot = doc.lane_slot
            self._lane_codec.lane_clear_queue(self._lane, slot)
            self.slot_live[slot] = False
            self.slot_gen[slot] += 1
            self._tail_known[slot] = False
            self._tail_dirty.discard(slot)

    def _clear_slot(self, slot: int) -> None:
        self._clear_slots([slot])

    def _clear_slots(self, slots: "list[int]") -> None:
        """Reset a batch of arena rows to empty in ONE state rebuild
        (and one flush-epoch bump): `.at[slots].set` over every field
        instead of a full pytree rebuild per slot."""
        if not slots:
            return
        # type(self.state): DocState or RleState, same field-wise rebuild
        if len(slots) == 1:
            # static-index fast path (dynamic_update_slice, the shape
            # every flush cycle already compiled) — the gather/scatter
            # below would pay a fresh first-call compile for a hot,
            # common case
            empty = self._make_empty(1, self.capacity)
            idx = slots[0]
            self.state = type(self.state)(
                *(
                    field.at[idx].set(empty_field[0])
                    for field, empty_field in zip(self.state, empty)
                )
            )
        else:
            import jax.numpy as jnp

            # power-of-two routing width with the num_docs drop
            # sentinel (the sparse/compact steps' contract): release()
            # runs on the event loop, where an unpadded width would
            # pay a first-call scatter compile for every distinct
            # released-slot count
            width = 1
            while width < len(slots):
                width *= 2
            empty = self._make_empty(width, self.capacity)
            idx = jnp.asarray(
                list(slots) + [self.num_docs] * (width - len(slots)),
                jnp.int32,
            )
            self.state = type(self.state)(
                *(
                    field.at[idx].set(empty_field, mode="drop")
                    for field, empty_field in zip(self.state, empty)
                )
            )
        for slot in slots:
            self._set_tail_empty(slot)
        self.flush_epoch += 1

    def _set_tail_empty(self, slot: int) -> None:
        """Mark a slot's rank tail KNOWN-EMPTY (fresh/cleared row)."""
        self._tail_client[slot] = NONE_CLIENT
        self._tail_clock[slot] = 0
        self._tail_known[slot] = True
        self._tail_dirty.discard(slot)

    def invalidate_tails(self, slots) -> None:
        """Forget the tracked rank tails for `slots` (and queue them for
        the probe re-arm at the next flush readback). Called by the
        residency manager after a compaction — tombstone GC remaps
        ranks, so the host-tracked tail id may no longer be the rank
        tail."""
        for slot in slots:
            slot = int(slot)
            self._tail_known[slot] = False
            if self.slot_live[slot]:
                self._tail_dirty.add(slot)

    def drop_doc_logs(self, name: str) -> None:
        """Finish a log-preserving retire (see retire_doc): the
        compaction attempt declined, so release the host memory (and
        the retained queues) the ordinary retire would have freed."""
        doc = self.docs.get(name)
        if doc is None:
            return
        doc.serve_log = []
        doc.map_tombstones = []
        for slot in doc.seqs.values():
            self.unit_logs[slot] = []
            queue = self.queues.get(slot)
            if queue:
                queue.clear()
            self._busy_slots.discard(slot)

    def is_supported(self, name: str) -> bool:
        doc = self.docs.get(name)
        if doc is None:
            return False
        return not doc.lowerer.unsupported

    # -- queueing ----------------------------------------------------------

    def enqueue_update(
        self, name: str, update: bytes, presync: bool = False, remote: bool = False
    ) -> int:
        """Lower + queue one update; returns the number of ops accepted."""
        lane_doc = self.docs.get(name)
        if lane_doc is not None and lane_doc.lane_slot is not None:
            if lane_doc.lowerer.unsupported:
                return 0
            return self._enqueue_lane(lane_doc, update, presync, remote)
        doc = self.register(name)
        if doc.lowerer.unsupported:
            return 0
        seq_ops, map_ops, map_tombs = doc.lowerer.lower_update(update)
        if doc.lowerer.unsupported:
            self.retire_doc(name, "unsupported")
            return 0
        count = 0
        for seq_key, ops in seq_ops.items():
            if doc.origin_remap:
                self._remap_origins(doc, seq_key, ops)
            slot = self._alloc_seq(doc, seq_key)
            if slot is None:
                self.retire_doc(name, "plane_full")
                return 0
            # host-side mirror of the device capacity check: the lowerer
            # guarantees causal readiness, so inserts succeed until the
            # arena overflows — at which point the doc is CPU-only
            # forever; stop queueing (and logging payloads) instead of
            # leaking. Unit arena: exact (capacity = units, cost =
            # run_len per insert). RLE arena: neutral 1/op estimate —
            # run-aligned churn deletes cost 0 device entries and
            # mid-run splits cost up to 2, so the host bound only stops
            # unbounded queueing on a doomed doc; the DEVICE overflow
            # flag is the real authority (caught one flush later, and
            # routed through the same recycle seam as capacity).
            if self.arena == "rle":
                projected = self.projected_len[slot] + len(ops)
            else:
                projected = self.projected_len[slot] + sum(
                    op.run_len for op in ops if op.kind == KIND_INSERT
                )
            if projected > self.capacity:
                self.retire_doc(name, "capacity")
                return 0
            self.projected_len[slot] = projected
            if presync:
                for op in ops:
                    op.presync = True
            self.queues[slot].extend(ops)
            # AFTER the extend, unconditionally: this ordering is what
            # makes the busy set lock-free against the drain side (see
            # _busy_slots in __init__)
            self._busy_slots.add(slot)
            # log at ENQUEUE time: broadcast frames build from the host
            # log without waiting for the device flush (the device round
            # trip must never sit on the edit->broadcast critical path —
            # ~an RTT per transfer on remote-attached TPUs). Arena slot
            # assignment is deterministic (arrival order), so unit
            # offsets are final here; health checks compare device state
            # against dispatched tallies, not these logs.
            log = self.unit_logs[slot]
            for op in ops:
                doc.serve_log.append(
                    LogRec(op=op, slot=slot, unit_off=len(log), remote=remote)
                )
                if op.kind == KIND_INSERT:
                    log.extend(op.chars)
            count += len(ops)
        for op in map_ops:
            op.presync = presync
            doc.serve_log.append(LogRec(op=op, slot=None, remote=remote))
            count += 1
        for client, clock, length in map_tombs:
            doc.map_tombstones.append((client, clock, length))
            doc.serve_log.append(
                LogRec(
                    op=DenseOp(
                        kind=KIND_DELETE, client=client, clock=clock, run_len=length,
                        presync=presync,
                    ),
                    slot=None,
                    remote=remote,
                )
            )
            # a map-tombstone-only update still produces a serve-log
            # record that must broadcast: count it like every other op
            count += 1
        if count:
            self.dirty.add(name)
        return count

    def _remap_origins(self, doc: PlaneDoc, seq_key: tuple, ops: list) -> None:
        """Re-anchor op origins that reference ids the tombstone-GC
        compaction removed from the device (tpu/residency.py): the left
        origin falls back to the nearest live unit that preceded the
        removed range at compaction time, the right origin to the
        nearest that followed — the same positional approximation yjs
        accepts once tombstones are garbage-collected. An op whose both
        origins dissolve into doc boundaries gets the sequence as its
        explicit wire parent (serve-time Item.write needs one)."""
        from bisect import bisect_right

        remap = doc.origin_remap

        def removed(client: int, clock: int):
            entry = remap.get(client)
            if entry is None:
                return None
            starts, rows = entry
            i = bisect_right(starts, clock) - 1
            if i >= 0 and rows[i][0] <= clock < rows[i][1]:
                return rows[i]
            return None

        def resolve(client: int, clock: int, side: int):
            """Chase the remap transitively: a recorded neighbor may
            itself sit in a range a LATER compaction removed, so a
            single lookup could hand back a dead id. Each hop follows
            the same side (a left origin wants its replacement's own
            left neighbor) and lands in a strictly newer removed range
            — replacements were live when their row was written — so
            the walk terminates."""
            moved = False
            while client != NONE_CLIENT:
                row = removed(client, clock)
                if row is None:
                    break
                moved = True
                repl = row[side]
                if repl is None:
                    client, clock = NONE_CLIENT, 0
                    break
                client, clock = repl
            return moved, client, clock

        for op in ops:
            if op.kind != KIND_INSERT:
                continue
            if op.left_client != NONE_CLIENT:
                moved, client, clock = resolve(op.left_client, op.left_clock, 2)
                if moved:
                    op.left_client, op.left_clock = client, clock
            if op.right_client != NONE_CLIENT:
                moved, client, clock = resolve(op.right_client, op.right_clock, 3)
                if moved:
                    op.right_client, op.right_clock = client, clock
            if (
                op.left_client == NONE_CLIENT
                and op.right_client == NONE_CLIENT
                and op.parent is None
            ):
                op.parent = seq_key

    def pending_ops(self) -> int:
        # O(busy), not O(D): walk the nonempty-slot set, not the full
        # queue registry. list() snapshot: the event-loop thread can
        # add busy slots while an executor-side flush calls this.
        total = 0
        for slot in list(self._busy_slots):
            queue = self.queues.get(slot)
            if queue:
                total += len(queue)
        if self._lane is not None:
            total += self._lane_codec.lane_queue_total(self._lane)
        return total

    # -- device step -------------------------------------------------------

    def flush(self, max_batches: Optional[int] = None) -> int:
        """Integrate queued ops in (K, D) batches. Returns ops integrated.

        max_batches bounds the kernel calls in this cycle (one batch
        already covers up to max_slots_per_flush ops for EVERY queue):
        the serving flush loop uses 1 so broadcasts interleave with
        integration instead of waiting for a full drain; sync serves
        drain fully (covers() needs everything integrated)."""
        with self._step_lock:
            return self._flush_locked(max_batches)

    def warmup_compiles(self, shape=None, shared: bool = False) -> bool:
        """Pre-compile the integrate step over the (K, B) flush grid.

        The first flush at each batch shape otherwise pays the
        XLA/Mosaic compile (seconds on CPU, tens of seconds cold on
        TPU) in the serving path — with the flush off the event loop
        that surfaced as broadcasts delayed until the compile finished.
        A no-op batch exercises the identical jitted program without
        touching document state. Pass a (k, b) tuple from
        warmup_shapes() to compile one shape (callers can interleave
        lock acquisition per shape), a bare int k for the dense
        (k, num_docs) shape, or nothing for the whole grid.

        shared=True consults the process-wide warm registry
        (tpu/scheduler.py): the jitted steps are module-level, so a
        shape another identically-shaped plane already warmed is a
        guaranteed jit-cache hit — skip the redundant no-op dispatch
        and seed this plane's CompileTracker instead (shards 2..N of a
        sharded deployment boot without N identical warm sweeps).
        Mesh-backed planes build per-plane jitted closures and never
        share. Returns True when any program was actually dispatched.
        """
        full_grid = shape is None
        shapes = (
            [shape]
            if shape is not None
            else self.warmup_shapes() + self.warmup_aux_shapes()
        )
        shapes = [
            entry if isinstance(entry, tuple) else (entry, self.num_docs)
            for entry in shapes
        ]
        share = shared and self.mesh is None
        if share:
            from .scheduler import note_warmed, shared_warm_filter

            shapes, covered = shared_warm_filter(
                self.arena,
                self.num_docs,
                self.capacity,
                shapes,
                device=self._warm_device_key(),
            )
            for entry in covered:
                site, shape_key = self._warm_site(entry)
                self.compile_watch.mark_covered(site, shape_key)
        dispatched = False
        with self._step_lock:
            for entry in shapes:
                site, shape_key = self._warm_site(entry)
                if site == "append_sparse":
                    _, k, b = entry
                    args = self._empty_append_batch(k, b)
                    with self.compile_watch.track(site, shape_key, warmup=True):
                        self.state, count = self._append_step_fn()(
                            self.state, *args
                        )
                        int(count)  # completion barrier (data-dependent)
                elif site == "tail_probe":
                    _, w = entry
                    probe = np.zeros((w,), np.int32)  # re-reads row 0
                    with self.compile_watch.track(site, shape_key, warmup=True):
                        np.asarray(
                            self._tail_probe_fn()(
                                self.state, self._upload_slots(probe)
                            )
                        )
                elif site == "integrate_dense":
                    k, b = entry
                    ops = self._empty_batch(k)
                    with self.compile_watch.track(site, shape_key, warmup=True):
                        self.state, count = self._step_fn()(self.state, ops)
                        int(count)  # completion barrier (data-dependent)
                else:
                    k, b = entry
                    ops, slots = self._empty_sparse_batch(k, b)
                    with self.compile_watch.track(site, shape_key, warmup=True):
                        self.state, count = self._sparse_step_fn()(
                            self.state, ops, slots
                        )
                        int(count)  # completion barrier (data-dependent)
                self._note_dispatch("warmup")
                dispatched = True
                if share:
                    note_warmed(
                        self.arena,
                        self.num_docs,
                        self.capacity,
                        entry,
                        device=self._warm_device_key(),
                    )
        if full_grid:
            # the whole grid is compiled: any later fresh compile means
            # the flush shapes drifted off the warmed buckets
            self.compile_watch.mark_warmed()
        return dispatched

    def _warm_device_key(self) -> str:
        """The shared-warm-registry discriminator for a pinned plane:
        XLA caches executables per device placement, so identically-
        shaped planes on DIFFERENT chips never share a warm pass."""
        if self.device is None:
            return ""
        return str(getattr(self.device, "id", self.device))

    def canary_probe(self) -> float:
        """One tiny no-op integrate + data-dependent readback: the plane
        supervisor's liveness probe (tpu/supervisor.py). Returns the
        elapsed seconds. Deliberately takes the step lock — a wedged
        flush holding it blocks the probe, which is exactly the
        condition the watchdog's deadline detects. Uses the smallest
        sparse shape (K=1, B=1) so the probe's device work is O(1)
        rows, not a full-population sweep."""
        started = time.perf_counter()
        with self._step_lock:
            if self.num_docs > 1:
                # (K_max, 1): the first entry of the warmup grid — a
                # warmed plane's probes never pay a compile
                k_max = self._k_buckets()[-1]
                ops, slots = self._empty_sparse_batch(k_max, 1)
                with self.compile_watch.track("integrate_sparse", (k_max, 1)):
                    self.state, count = self._sparse_step_fn()(self.state, ops, slots)
                    int(count)  # completion barrier (data-dependent readback)
            else:
                ops = self._empty_batch(1)
                with self.compile_watch.track("integrate_dense", (1, self.num_docs)):
                    self.state, count = self._step_fn()(self.state, ops)
                    int(count)  # completion barrier (data-dependent readback)
            self._note_dispatch("canary")
        return time.perf_counter() - started

    def _k_buckets(self) -> list[int]:
        buckets = []
        k = 1
        while True:
            buckets.append(k)
            if k >= self.max_slots_per_flush:
                return buckets
            k *= 2

    def _b_buckets(self) -> list[int]:
        """SPARSE busy-width buckets: powers of four (a subset of the
        powers of two, so two octaves of headroom per bucket) strictly
        below the population. A busy width above the top bucket takes
        the dense (K, D) layout instead — so the full set of reachable
        batch shapes is this ladder plus the dense K ladder."""
        buckets = []
        b = 1
        while b < self.num_docs:
            buckets.append(b)
            b *= 4
        return buckets

    def warmup_shapes(self) -> "list[tuple[int, int]]":
        """Every (K, B) batch shape a flush can dispatch.

        Sparse batches PIN K to the top bucket (the op axis is cheap at
        sparse widths, and pinning turns the compile grid from
        |K| x |B| — measured ~1s of XLA compile per shape — into
        |K| + |B|): one shape per sparse B bucket, plus the dense
        (k, num_docs) ladder where the op axis does matter. The first
        entry, (K_max, 1), is also the canary probe's shape, so a
        supervisor warm pass covers the watchdog's program before the
        first probe fires."""
        k_max = self._k_buckets()[-1]
        return [(k_max, b) for b in self._b_buckets()] + [
            (k, self.num_docs) for k in self._k_buckets()
        ]

    def warmup_aux_shapes(self) -> "list[tuple]":
        """Tagged warm-grid entries beyond the integrate (k, b) pairs:
        the run-append fast path's ("append", K_max, B) ladder (same
        pinned-K discipline as the sparse integrate, plus the
        num_docs-wide routing the dense regime takes) and the
        ("tail", W) probe widths _sync_health can dispatch. Kept out
        of warmup_shapes() so its (k, b)-pair contract — relied on by
        the supervisor grid checks — survives."""
        k_max = self._k_buckets()[-1]
        shapes: "list[tuple]" = [
            ("append", k_max, b) for b in self._b_buckets() + [self.num_docs]
        ]
        widths = [16] if self.num_docs <= 16 else [16, self._TAIL_PROBE_MAX]
        shapes += [("tail", w) for w in widths]
        return shapes

    def _warm_site(self, entry: tuple) -> "tuple[str, tuple]":
        """(compile-watch site, shape key) for one warm-grid entry —
        plain (k, b) integrate pairs or tagged aux entries."""
        if entry[0] == "append":
            return "append_sparse", (entry[1], entry[2])
        if entry[0] == "tail":
            return "tail_probe", (entry[1],)
        k, b = entry
        if b >= self.num_docs:
            return "integrate_dense", (k, self.num_docs)
        return "integrate_sparse", (k, b)

    def _empty_append_batch(self, k: int, b: int) -> tuple:
        """All-noop append fast-path args (run_len == 0 everywhere,
        every routing entry the drop sentinel): applies nothing,
        compiles the exact program of a real (k, b) fast batch."""
        client = np.zeros((k, b), np.uint32)
        clock = np.zeros((k, b), np.int32)
        run_len = np.zeros((k, b), np.int32)
        slots = np.full((b,), self.num_docs, np.int32)
        return self._upload_append_batch((client, clock, run_len), slots)

    def _bucket_b(self, busy: int) -> int:
        """Round a busy width up to its sparse bucket; num_docs (the
        dense layout) when it exceeds the top sparse bucket."""
        b = 1
        while b < busy:
            b *= 4
        return b if b < self.num_docs else self.num_docs

    def _plan_batch(self, busy: int) -> "tuple[bool, int]":
        """The flush layout decision, in ONE place: (dense, b). Sparse
        — a compact (K, B) batch plus slot routing — while the busy
        width buckets below the population; the dense (K, D) sweep once
        it doesn't, where routing would only add gather/scatter
        overhead. _flush_locked derives K from `dense` (depth ladder vs
        pinned k_max) and _assemble_batch lays the batch out from the
        same verdict — never recomputed separately."""
        b = self._bucket_b(busy)
        return b >= self.num_docs, b

    def _empty_batch(self, k: int) -> OpBatch:
        d = self.num_docs
        fields = (
            np.zeros((k, d), np.int32),
            np.zeros((k, d), np.uint32),
            np.zeros((k, d), np.int32),
            np.zeros((k, d), np.int32),
            np.full((k, d), NONE_CLIENT, np.uint32),
            np.zeros((k, d), np.int32),
            np.full((k, d), NONE_CLIENT, np.uint32),
            np.zeros((k, d), np.int32),
        )
        return self._upload_batch(fields)

    def _empty_sparse_batch(self, k: int, b: int) -> tuple:
        """All-noop (K, B) batch with every routing entry the padding
        sentinel (num_docs): integrates nothing, compiles/exercises the
        exact sparse program of a real (k, b) flush batch."""
        fields = tuple(
            np.full((k, b), default, dtype)
            for default, dtype in zip(
                _FlushStaging._DEFAULTS, _FlushStaging._DTYPES
            )
        )
        slots = np.full((b,), self.num_docs, np.int32)
        return self._upload_sparse_batch(fields, slots)

    def _flush_locked(self, max_batches: Optional[int] = None) -> int:
        tracer = get_tracer()
        book = self.update_traces
        trace_batches: list = []
        k_max = self._k_buckets()[-1]
        total = 0
        batches = 0
        device_batches = 0
        fast_total = slow_total = 0
        build_ms = upload_ms = dispatch_ms = 0.0
        upload_bytes = 0
        k_last = b_last = busy_last = 0
        while max_batches is None or batches < max_batches:
            t0 = time.perf_counter()
            drained = self._drain_ops(k_max)
            if drained is None:
                break
            cycle_traces = None
            if book.active():
                # stamped updates whose slots drained this batch enter
                # the in-flight set; t0 closes their queue-wait stage
                cycle_traces = book.take_drained(
                    (self.slot_owner.get(int(s)) for s in drained[4]), t0
                )
            built = drained[5]
            busy_total = int(drained[4].size)
            # minimal-work run merge: split the drained columns into
            # all-sequential (fast) and genuinely-concurrent (slow)
            # sets. A column is entirely one or the other per batch —
            # the two dispatches below touch disjoint rows, so their
            # relative order is immaterial.
            fast = None
            slow = drained
            if self.run_merge_enabled:
                fast, slow = self._classify_fast(drained)
            if fast is not None:
                (
                    run_row, run_col, f_client, f_clock, f_run,
                    f_slots, f_ops, f_tail_cl, f_tail_ck,
                ) = fast
                nf = int(f_slots.size)
                bf = self._bucket_b(nf)
                staging_f = self._append_staging_for(self._append_batches, k_max)
                cl_v, ck_v, rn_v = staging_f.views(k_max, bf)
                cl_v[run_row, run_col] = f_client
                ck_v[run_row, run_col] = f_clock
                rn_v[run_row, run_col] = f_run
                slot_view_f = staging_f.slot_view(bf)
                slot_view_f[:nf] = f_slots
                slot_view_f[nf:] = self.num_docs
                t1 = time.perf_counter()
                args_f = self._upload_append_batch(
                    (cl_v, ck_v, rn_v), slot_view_f
                )
                self._append_inflight[self._append_batches % 2] = args_f
                self._append_batches += 1
                t2 = time.perf_counter()
                step_f = self._append_step_fn()
                if tracer.enabled:
                    with tracer.device_span(
                        "merge_plane.append", slots=k_max, busy=bf
                    ) as span:
                        self.state, _count = step_f(self.state, *args_f)
                        span.set("integrated", f_ops)
                else:
                    self.state, _count = step_f(self.state, *args_f)
                t_dispatch = time.perf_counter()
                self.compile_watch.observe(
                    "append_sparse", (k_max, bf), t_dispatch - t2
                )
                # the dispatched runs land at the rank tail, so the new
                # tail is each column's last coalesced run — tracked
                # here with no device read; the slot stays fast-eligible
                self._tail_client[f_slots] = f_tail_cl
                self._tail_clock[f_slots] = f_tail_ck
                self.counters["flush_batches_fast"] += 1
                self.counters["flush_fast_ops"] += f_ops
                fast_total += f_ops
                device_batches += 1
                build_ms += (t1 - t0) * 1000.0
                upload_ms += (t2 - t1) * 1000.0
                dispatch_ms += (t_dispatch - t2) * 1000.0
                upload_bytes += staging_f.nbytes(k_max, bf)
                k_last, b_last = k_max, bf
                if cycle_traces and slow is None:
                    trace_batches.append((cycle_traces, t1, t2, t_dispatch))
                t0 = t_dispatch  # the slow build, if any, starts here
            if slow is not None:
                depth = slow[6]
                # sparse batches pin K to the top bucket (one compiled
                # program per B bucket — see warmup_shapes); dense
                # batches keep the power-of-two K ladder, where the op
                # axis multiplies a full-population sweep
                dense, b_bucket = self._plan_batch(int(slow[4].size))
                if dense:
                    k = 1
                    while k < depth:
                        k *= 2
                else:
                    k = k_max
                staging = self._staging_for(batches, k)
                fields, slot_view, b, b_actual = self._assemble_batch(
                    k, slow, staging, dense, b_bucket
                )
                t1 = time.perf_counter()
                if slot_view is None:
                    step_args = (self._upload_batch(fields),)
                    step = self._step_fn()
                    self.counters["flush_batches_dense"] += 1
                else:
                    ops, slots_dev = self._upload_sparse_batch(fields, slot_view)
                    step_args = (ops, slots_dev)
                    step = self._sparse_step_fn()
                    self.counters["flush_batches_sparse"] += 1
                # remember what this staging buffer fed the device:
                # _staging_for blocks on it before the buffer's next
                # reuse (two batches from now), so an async transfer can
                # never still be reading views a later batch resets
                self._staging_inflight[batches % 2] = step_args
                t2 = time.perf_counter()
                # `built` is the host-side op count — identical to the
                # device's kind!=NOOP sum by construction, so the flush
                # needs no per-batch count readback (a full RTT each on
                # remote-attached TPUs); _sync_health below is the
                # cycle's single completion barrier (content readback —
                # buffer *readiness* of aliased Pallas outputs is not
                # trustworthy, see bench.py sync()). The dispatch itself
                # is ASYNC: while the device integrates batch i, the
                # next loop iteration builds and uploads batch i+1 from
                # the OTHER staging buffer — that alternation is the
                # double-buffered pipeline.
                if tracer.enabled:
                    with tracer.device_span(
                        "merge_plane.integrate", slots=k, busy=b
                    ) as span:
                        self.state, _count = step(self.state, *step_args)
                        span.set("integrated", slow[5])
                else:
                    self.state, _count = step(self.state, *step_args)
                t_dispatch = time.perf_counter()
                # compile-event classification from the timestamps
                # already taken: a first dispatch at this (site, shape)
                # paid its XLA/Mosaic compile inline in t_dispatch - t2
                if slot_view is None:
                    self.compile_watch.observe(
                        "integrate_dense", (k, self.num_docs), t_dispatch - t2
                    )
                else:
                    self.compile_watch.observe(
                        "integrate_sparse", (k, b), t_dispatch - t2
                    )
                # full-integrate columns invalidate their tracked rank
                # tails (a concurrent insert/delete may have moved the
                # tail); _sync_health re-arms the live ones below
                slow_cols = slow[4].astype(np.intp)
                self._tail_known[slow_cols] = False
                for col in slow_cols:
                    col = int(col)
                    if self.slot_live[col]:
                        self._tail_dirty.add(col)
                self.counters["flush_slow_ops"] += slow[5]
                slow_total += slow[5]
                device_batches += 1
                if cycle_traces:
                    trace_batches.append((cycle_traces, t1, t2, t_dispatch))
                build_ms += (t1 - t0) * 1000.0
                upload_ms += (t2 - t1) * 1000.0
                # ~0 where dispatch is truly asynchronous; on
                # synchronous backends this is the device compute the
                # cycle pays inline
                dispatch_ms += (t_dispatch - t2) * 1000.0
                upload_bytes += staging.nbytes(k, b, slot_view is not None)
                k_last, b_last = k, b
            total += built
            busy_last = busy_total
            batches += 1
        if batches:
            self._note_dispatch("flush", device_batches)
            t3 = time.perf_counter()
            self._sync_health()
            t_sync = time.perf_counter()
            # readback-barrier stall: the host time this cycle spent
            # blocked on the device before results were visible
            self.device_stats["readback_stall_ms_total"] += (t_sync - t3) * 1000.0
            self.device_stats["readback_stalls"] += 1
            if upload_bytes > self.device_stats["upload_bytes_peak"]:
                self.device_stats["upload_bytes_peak"] = upload_bytes
            self._memory_stats_cache = (0.0, None)  # staging/stalls moved
            if trace_batches:
                # the cycle's single readback barrier closes every
                # in-flight trace's device/readback stages
                book.complete_cycle(trace_batches, t_sync)
            self.flush_stats.update(
                build_ms=round(build_ms, 3),
                upload_ms=round(upload_ms, 3),
                dispatch_ms=round(dispatch_ms, 3),
                device_sync_ms=round((t_sync - t3) * 1000.0, 3),
                busy_slots=busy_last,
                busy_fraction=round(busy_last / max(self.num_docs, 1), 6),
                batch_k=k_last,
                batch_b=b_last,
                batches=batches,
                upload_bytes=upload_bytes,
                fast_path_ops=fast_total,
                slow_path_ops=slow_total,
                fast_path_fraction=round(fast_total / max(total, 1), 6),
            )
        self.total_integrated += total
        return total

    def memory_stats(self) -> dict:
        """Device/host memory footprint (HBM watch): arena state bytes
        (constant after construction), allocated staging bytes, the
        biggest single-cycle upload and the cumulative readback-stall
        time. Array `.nbytes` reads only metadata — no transfer. The
        pytree walks are cached briefly: one /metrics scrape reads five
        gauges off this dict and must pay one walk, not five (x shards
        on the summed variant)."""
        now = time.monotonic()
        cached_at, cached = self._memory_stats_cache
        if cached is not None and now - cached_at < 0.5:
            return cached
        staging_bytes = 0
        for staging in self._staging or ():
            staging_bytes += pytree_nbytes(staging.fields) + staging.slots.nbytes
        for staging in self._append_staging or ():
            staging_bytes += (
                staging.client.nbytes
                + staging.clock.nbytes
                + staging.run_len.nbytes
                + staging.slots.nbytes
            )
        stats = {
            "arena_bytes": pytree_nbytes(self.state),
            "staging_bytes": staging_bytes,
            "upload_bytes_peak": self.device_stats["upload_bytes_peak"],
            "readback_stall_ms_total": round(
                self.device_stats["readback_stall_ms_total"], 3
            ),
            "readback_stalls": self.device_stats["readback_stalls"],
        }
        self._memory_stats_cache = (now, stats)
        return stats

    def _sync_health(self) -> None:
        """ONE combined device->host readback per flush cycle.

        Fetches lengths + overflow as a single array (each transfer
        costs ~a full RTT on remote-attached runtimes) — this read is
        also the completion barrier for every batch dispatched above,
        by data dependence. The dispatched->validated snapshot is taken
        at the same point (under the step lock), so health checks
        compare device rows against exactly the ops the device has
        integrated, never against optimistically-ahead host logs. A
        launch failure surfaces here and propagates to the caller
        (flush -> extension degrade path).

        When full-integrate columns (or a compaction) invalidated
        tracked rank tails, the dirty LIVE slots' tail ids ride the
        same fused readback via the tail_probe kernel — one transfer,
        never a second RTT — and re-arm the run-merge classifier for
        the next cycle. At most _TAIL_PROBE_MAX slots re-arm per cycle
        (two compiled probe widths, never an unbounded shape ladder);
        the remainder stay dirty for the next readback."""
        import jax.numpy as jnp

        probe_slots = None
        probe_width = 0
        if self._tail_dirty and self.run_merge_enabled:
            live = sorted(
                slot for slot in self._tail_dirty if self.slot_live[slot]
            )
            self._tail_dirty.clear()
            if len(live) > self._TAIL_PROBE_MAX:
                self._tail_dirty.update(live[self._TAIL_PROBE_MAX :])
                live = live[: self._TAIL_PROBE_MAX]
            if live:
                probe_slots = np.asarray(live, np.intp)
                probe_width = (
                    16 if len(live) <= 16 else self._TAIL_PROBE_MAX
                )
        parts = [
            self.state.length.astype(jnp.uint32),
            self.state.overflow.astype(jnp.uint32),
        ]
        if probe_slots is not None:
            padded = np.zeros(probe_width, np.int32)
            padded[: probe_slots.size] = probe_slots  # pad: re-read slot 0
            with self.compile_watch.track("tail_probe", (probe_width,)):
                parts.append(
                    self._tail_probe_fn()(self.state, self._upload_slots(padded))
                )
            self._note_dispatch("tail_probe")
        combined = np.asarray(jnp.concatenate(parts))
        lengths = combined[: self.num_docs].astype(np.int64)
        self.last_lengths = lengths
        self.last_overflows = combined[self.num_docs : 2 * self.num_docs].astype(
            bool
        )
        if probe_slots is not None:
            probe = combined[2 * self.num_docs :]
            n = probe_slots.size
            clients = probe[:n].astype(np.uint32)
            clocks = probe[probe_width : probe_width + n].astype(np.int64)
            empty = lengths[probe_slots] == 0
            self._tail_client[probe_slots] = np.where(
                empty, np.uint32(NONE_CLIENT), clients
            )
            self._tail_clock[probe_slots] = np.where(empty, 0, clocks)
            self._tail_known[probe_slots] = True
        self.validated_units = self.dispatched_units.copy()
        self.last_gen = self.slot_gen.copy()
        self.flush_epoch += 1

    # per-cycle cap on tail re-arms: bounds both the probe's device
    # work and the compiled width ladder to {16, _TAIL_PROBE_MAX}
    _TAIL_PROBE_MAX = 256

    def _drain_ops(self, k: int):
        """Pop up to k ops from every BUSY queue (Python + native lane)
        into flat coordinate/value lists — O(busy), never a walk of the
        full queue registry. Returns None when nothing was drained,
        else (rows, slots, vals, lane, cols, built, depth): python op
        coordinates (row-in-batch, arena slot) + 8 per-field value
        columns, the lane's columnar drain tuple (or None), the sorted
        unique busy slot ids, the total op count, and the deepest
        per-queue take (the dense layout's K requirement).

        The busy snapshot is taken via sorted(set) (atomic under the
        GIL); enqueues landing after the snapshot wait for the next
        batch, exactly like the old full-registry snapshot."""
        rows: list[int] = []
        slots: list[int] = []
        vals: tuple[list[int], ...] = ([], [], [], [], [], [], [], [])
        built = 0
        depth = 0
        for slot in sorted(self._busy_slots):
            queue = self.queues.get(slot)
            if not queue:
                self._busy_slots.discard(slot)
                if queue:  # an enqueue raced the discard: repair
                    self._busy_slots.add(slot)
                continue
            take = queue[:k]
            # del by len(take), not k: the loop thread may EXTEND this
            # queue between the slice and the del (both atomic alone
            # under the GIL, not together). Appends only touch the back,
            # so the front len(take) items are exactly the taken ones —
            # `del queue[:k]` with k > len(take) would silently discard
            # ops appended in that window (logged in serve_log but never
            # dispatched: permanent host/device divergence).
            del queue[: len(take)]
            if not queue:
                self._busy_slots.discard(slot)
                if queue:  # an enqueue raced the discard: repair
                    self._busy_slots.add(slot)
            dispatched = 0
            for i, op in enumerate(take):
                rows.append(i)
                slots.append(slot)
                vals[0].append(op.kind)
                vals[1].append(op.client)
                vals[2].append(op.clock)
                vals[3].append(op.run_len)
                vals[4].append(op.left_client)
                vals[5].append(op.left_clock)
                vals[6].append(op.right_client)
                vals[7].append(op.right_clock)
                if op.kind == KIND_INSERT:
                    dispatched += op.run_len
            built += len(take)
            if len(take) > depth:
                depth = len(take)
            self.dispatched_units[slot] += dispatched
            self.dispatched_total += dispatched
        lane = None
        if self._lane is not None:
            # native lane drain: one C call pops up to k ops per lane
            # slot into columnar buffers scattered by _assemble_batch —
            # no per-op Python at all on the hot-doc flush path
            drained = self._lane_codec.lane_drain(self._lane, k)
            if drained[0]:
                lane = drained
                ds = np.frombuffer(drained[11], np.int64)
                lane_units = np.frombuffer(drained[12], np.int64)
                self.dispatched_units[ds] += lane_units
                self.dispatched_total += int(lane_units.sum())
                built += drained[0]
                lane_rows = np.frombuffer(drained[1], np.int64)
                depth = max(depth, int(lane_rows.max()) + 1)
        if not built:
            return None
        py_cols = np.unique(np.asarray(slots, np.int64))
        if lane is not None:
            lane_cols = np.unique(np.frombuffer(lane[2], np.int64))
            cols = np.union1d(py_cols, lane_cols)
        else:
            cols = py_cols
        return rows, slots, vals, lane, cols, built, depth

    def _classify_fast(self, drained):
        """The run-merge concurrency classifier: split one drained cycle
        into fast COLUMNS (every op a chained tail append — integrable
        by the near-O(new ops) append program) and slow columns (the
        full-row integrate). Returns (fast_pack | None, slow | None)
        where `slow` has the same shape as a _drain_ops result (lane
        ops already folded into the flat arrays, lane=None).

        An op is a pure tail append iff it is an INSERT with no right
        origin whose left origin is the column's current rank tail —
        the Yjs end-append shape. For such ops the YATA conflict window
        is empty, so the append program is bit-identical to the scan
        integrate (tpu/kernels.py, "minimal-work run merge"). Chains
        verify inductively: op m's left must be op m-1's last unit.
        All checks are vectorized numpy over the drained cycle — the
        classifier costs O(drained ops), no Python per-op loop, no
        device read (tails are host-tracked, see _tail_known)."""
        rows, slots, vals, lane, cols, built, depth = drained
        n_py = len(rows)
        if lane is None and n_py == 0:
            return None, drained
        parts_row: list = []
        parts_slot: list = []
        parts_f: "list[list]" = [[] for _ in range(8)]
        if n_py:
            parts_row.append(np.asarray(rows, np.int64))
            parts_slot.append(np.asarray(slots, np.int64))
            for i in range(8):
                dtype = np.uint32 if i in (1, 4, 6) else np.int64
                parts_f[i].append(np.asarray(vals[i], dtype))
        if lane is not None:
            parts_row.append(np.frombuffer(lane[1], np.int64))
            parts_slot.append(np.frombuffer(lane[2], np.int64))
            for i, buf in enumerate(lane[3:11]):
                if i in (1, 4, 6):
                    parts_f[i].append(np.frombuffer(buf, np.uint32))
                else:
                    parts_f[i].append(
                        np.frombuffer(buf, np.int32).astype(np.int64)
                    )
        if len(parts_row) == 1:
            op_row, op_slot = parts_row[0], parts_slot[0]
            fields = [p[0] for p in parts_f]
        else:
            op_row = np.concatenate(parts_row)
            op_slot = np.concatenate(parts_slot)
            fields = [np.concatenate(p) for p in parts_f]
        n = op_slot.size
        # column-major order: a slot's ops are contiguous, row-ordered
        # (a slot drains from exactly one source — Python queue or lane
        # — so concatenation never interleaves within a column)
        order = np.lexsort((op_row, op_slot))
        s = op_slot[order]
        row_s = op_row[order]
        kind_s = fields[0][order]
        cl_s = fields[1][order]
        ck_s = fields[2][order]
        rn_s = fields[3][order]
        lc_s = fields[4][order]
        lk_s = fields[5][order]
        rc_s = fields[6][order]
        rk_s = fields[7][order]
        first = np.ones(n, bool)
        first[1:] = s[1:] != s[:-1]
        sp = s.astype(np.intp)
        head_ok = np.where(
            lc_s == NONE_CLIENT,
            # an origin-less insert appends only to an EMPTY row
            self._tail_client[sp] == np.uint32(NONE_CLIENT),
            (lc_s == self._tail_client[sp])
            & (lk_s == self._tail_clock[sp]),
        )
        prev_cl = np.empty(n, np.uint32)
        prev_end = np.empty(n, np.int64)
        prev_cl[0] = 0
        prev_end[0] = 0
        prev_cl[1:] = cl_s[:-1]
        prev_end[1:] = ck_s[:-1] + rn_s[:-1] - 1
        ok = (
            (kind_s == KIND_INSERT)
            & (rc_s == NONE_CLIENT)
            & self._tail_known[sp]
            & np.where(first, head_ok, (lc_s == prev_cl) & (lk_s == prev_end))
        )
        col_starts = np.flatnonzero(first)
        col_ok = np.logical_and.reduceat(ok, col_starts)
        if not col_ok.any():
            return None, drained
        counts = np.diff(np.append(col_starts, n))
        member = np.repeat(col_ok, counts)
        # coalesce the fast subset: consecutive same-client runs with
        # clock continuity merge into ONE device run (a typing burst of
        # K ops ships as a single (client, clock, len) triple)
        fs = s[member]
        fcl = cl_s[member]
        fck = ck_s[member]
        frn = rn_s[member]
        m = int(fs.size)
        newrun = np.ones(m, bool)
        newrun[1:] = (
            (fs[1:] != fs[:-1])
            | (fcl[1:] != fcl[:-1])
            | (fck[1:] != fck[:-1] + frn[:-1])
        )
        run_starts = np.flatnonzero(newrun)
        run_slot = fs[run_starts]
        run_client = fcl[run_starts]
        run_clock = fck[run_starts]
        run_len = np.add.reduceat(frn, run_starts)
        run_first = np.ones(run_slot.size, bool)
        run_first[1:] = run_slot[1:] != run_slot[:-1]
        col_of_run = np.cumsum(run_first) - 1
        first_run = np.flatnonzero(run_first)
        run_row = np.arange(run_slot.size) - first_run[col_of_run]
        last_run = np.append(first_run[1:] - 1, run_slot.size - 1)
        fast = (
            run_row.astype(np.intp),
            col_of_run.astype(np.intp),
            run_client,
            run_clock.astype(np.int64),
            run_len.astype(np.int64),
            run_slot[run_first].astype(np.int64),
            m,
            run_client[last_run],
            (run_clock[last_run] + run_len[last_run] - 1).astype(np.int64),
        )
        if member.all():
            return fast, None
        keep = ~member
        slow = (
            row_s[keep],
            s[keep],
            (
                kind_s[keep], cl_s[keep], ck_s[keep], rn_s[keep],
                lc_s[keep], lk_s[keep], rc_s[keep], rk_s[keep],
            ),
            None,
            s[col_starts][~col_ok],
            int(n - m),
            int(row_s[keep].max()) + 1,
        )
        return fast, slow

    def _append_staging_for(self, batch_index: int, k: int) -> _AppendStaging:
        """The append fast path's staging buffer for this batch — same
        double-buffer + retire-before-reuse discipline as _staging_for."""
        if (
            self._append_staging is None
            or self._append_staging[0].client.shape[0] < k
        ):
            k_max = max(self._k_buckets()[-1], k)
            self._append_staging = [
                _AppendStaging(k_max, self.num_docs) for _ in range(2)
            ]
            self._append_inflight = [None, None]
            self.counters["flush_staging_allocs"] += 2
        else:
            self.counters["flush_staging_reuses"] += 1
        index = batch_index % 2
        inflight = self._append_inflight[index]
        if inflight is not None:
            import jax

            jax.block_until_ready(inflight)
            self._append_inflight[index] = None
        return self._append_staging[index]

    def _upload_append_batch(self, fields: tuple, slots: np.ndarray) -> tuple:
        """Upload the three (K, B) run fields + (B,) routing — the
        append twin of _upload_sparse_batch (same placement rules)."""
        if self._append_field_sharding is not None:
            import jax

            return tuple(
                jax.device_put(field, self._append_field_sharding)
                for field in fields
            ) + (jax.device_put(slots, self._slots_sharding),)
        if self.device is not None:
            import jax

            return tuple(
                jax.device_put(field, self.device) for field in fields
            ) + (jax.device_put(slots, self.device),)
        import jax.numpy as jnp

        return tuple(jnp.asarray(field) for field in fields) + (
            jnp.asarray(slots),
        )

    def _upload_slots(self, slots: np.ndarray):
        """Upload a bare routing vector (tail probe) with the plane's
        placement rules."""
        import jax

        if self._slots_sharding is not None:
            return jax.device_put(slots, self._slots_sharding)
        if self.device is not None:
            return jax.device_put(slots, self.device)
        import jax.numpy as jnp

        return jnp.asarray(slots)

    def _staging_for(self, batch_index: int, k: int) -> _FlushStaging:
        """The staging buffer for this batch (alternating between the
        two preallocated sets), with its previous upload retired first:
        block_until_ready on the device arrays last fed from this
        buffer, so resetting it can never race an in-flight host->device
        transfer (device_put pins the host views until the transfer
        completes). Reallocation only happens when a caller asks for a
        K beyond the bucketed grid (equivalence tests) — counted, so
        the reuse regression suite can pin allocs flat."""
        if self._staging is None or self._staging[0].fields[0].shape[0] < k:
            k_max = max(self._k_buckets()[-1], k)
            self._staging = [
                _FlushStaging(k_max, self.num_docs) for _ in range(2)
            ]
            # fresh buffers: nothing uploaded from them yet (old
            # buffers' transfers keep their own pins alive)
            self._staging_inflight = [None, None]
            self.counters["flush_staging_allocs"] += 2
        else:
            self.counters["flush_staging_reuses"] += 1
        index = batch_index % 2
        inflight = self._staging_inflight[index]
        if inflight is not None:
            import jax

            jax.block_until_ready(inflight)
            self._staging_inflight[index] = None
        return self._staging[index]

    def _assemble_batch(
        self, k: int, drained, staging: _FlushStaging, dense: bool, b: int
    ):
        """Scatter drained ops into staging views.

        `dense`/`b` come from _plan_batch (the single source of the
        layout decision — this method never recomputes it). Returns
        (fields, slot_view, b, b_actual). Sparse layout — a compact
        (K, B) batch over the busy columns plus the int32 (B,)
        slot-routing view; dense (K, D) layout (column = arena slot,
        slot_view None) when every slot is effectively busy, where
        routing would only add gather/scatter overhead."""
        rows, slots, vals, lane, cols, _built, _depth = drained
        b_actual = int(cols.size)
        if dense:
            b = self.num_docs
            views = staging.views(k, b)
            col_idx = np.asarray(slots, np.intp)
            slot_view = None
        else:
            views = staging.views(k, b)
            col_idx = np.searchsorted(cols, np.asarray(slots, np.int64))
            slot_view = staging.slot_view(b)
            slot_view[:b_actual] = cols
            # padding columns route to the out-of-range sentinel: the
            # device gather clips (reads some real row, applies noops),
            # the scatter drops the write — padding can never alias a
            # busy row (see kernels.integrate_op_slots_sparse)
            slot_view[b_actual:] = self.num_docs
        if len(rows):  # list (live drain) or ndarray (classifier remainder)
            ri = np.asarray(rows, np.intp)
            views[0][ri, col_idx] = vals[0]
            views[1][ri, col_idx] = np.asarray(vals[1], np.uint32)
            views[2][ri, col_idx] = vals[2]
            views[3][ri, col_idx] = vals[3]
            views[4][ri, col_idx] = np.asarray(vals[4], np.uint32)
            views[5][ri, col_idx] = vals[5]
            views[6][ri, col_idx] = np.asarray(vals[6], np.uint32)
            views[7][ri, col_idx] = vals[7]
        if lane is not None:
            (
                _lane_built, l_rows, l_slots, l_kind, l_client, l_clock,
                l_run, l_lc, l_lk, l_rc, l_rk, _d_slots, _d_units,
            ) = lane
            ri = np.frombuffer(l_rows, np.int64)
            lane_slots = np.frombuffer(l_slots, np.int64)
            ci = lane_slots if dense else np.searchsorted(cols, lane_slots)
            views[0][ri, ci] = np.frombuffer(l_kind, np.int32)
            views[1][ri, ci] = np.frombuffer(l_client, np.uint32)
            views[2][ri, ci] = np.frombuffer(l_clock, np.int32)
            views[3][ri, ci] = np.frombuffer(l_run, np.int32)
            views[4][ri, ci] = np.frombuffer(l_lc, np.uint32)
            views[5][ri, ci] = np.frombuffer(l_lk, np.int32)
            views[6][ri, ci] = np.frombuffer(l_rc, np.uint32)
            views[7][ri, ci] = np.frombuffer(l_rk, np.int32)
        return views, slot_view, b, b_actual

    def _build_batch(self, k: int) -> "tuple[OpBatch, int]":
        """Drain + assemble + upload one DENSE (K, D) batch.

        Kept for callers that want the dense layout regardless of busy
        width (lane/Python equivalence tests compare batches column by
        column); the flush loop itself dispatches through the
        sparse/dense pipeline in _flush_locked."""
        drained = self._drain_ops(k)
        if drained is None:
            return self._empty_batch(k), 0
        staging = self._staging_for(0, k)
        fields, _slot_view, _b, _busy = self._assemble_batch(
            k, drained, staging, True, self.num_docs
        )
        ops = self._upload_batch(fields)
        self._staging_inflight[0] = (ops,)
        return ops, drained[5]

    def _upload_batch(self, fields: tuple) -> OpBatch:
        if self._op_shardings is not None:
            # upload straight to the mesh layout — routing through
            # jnp.asarray would commit to the default device first and
            # pay a second device-to-device reshard per field per flush
            import jax

            return OpBatch(
                *(
                    jax.device_put(field, sharding)
                    for field, sharding in zip(fields, self._op_shardings)
                )
            )
        if self.device is not None:
            # straight to the pinned chip: an uncommitted jnp.asarray
            # would land on the default device and pay a device-to-
            # device hop per field per flush
            import jax

            return OpBatch(
                *(jax.device_put(field, self.device) for field in fields)
            )
        import jax.numpy as jnp

        return OpBatch(*(jnp.asarray(field) for field in fields))

    def _upload_sparse_batch(self, fields: tuple, slots: np.ndarray) -> tuple:
        """Upload a compact (K, B) batch + its (B,) routing vector.

        On a mesh the tiny op fields replicate (sparse_ops_sharding);
        XLA routes each busy row's gather/scatter to the shard owning
        it. jnp.asarray/device_put COPY the staging views, so the
        staging buffers are free to be rebuilt two batches later."""
        if self._sparse_op_shardings is not None:
            import jax

            ops = OpBatch(
                *(
                    jax.device_put(field, sharding)
                    for field, sharding in zip(fields, self._sparse_op_shardings)
                )
            )
            return ops, jax.device_put(slots, self._slots_sharding)
        if self.device is not None:
            import jax

            return (
                OpBatch(
                    *(jax.device_put(field, self.device) for field in fields)
                ),
                jax.device_put(slots, self.device),
            )
        import jax.numpy as jnp

        return OpBatch(*(jnp.asarray(field) for field in fields)), jnp.asarray(
            slots
        )

    # -- extraction --------------------------------------------------------

    def check_doc_health(
        self,
        name: str,
        doc: PlaneDoc,
        lengths: np.ndarray,
        overflows: np.ndarray,
        validated: Optional[np.ndarray] = None,
        gens: Optional[np.ndarray] = None,
    ) -> bool:
        """Device/host invariants for every row of a doc; retires on fail.

        The single health definition shared by text() and the serving
        path (PlaneServing.doc_healthy) — callers supply the (D,)
        length/overflow rows AND the validated-unit + generation
        snapshots taken with them, so serving can reuse its refresh()
        caches. Device lengths are compared against VALIDATED dispatch
        tallies (what the device had been given as of that readback),
        never the host unit logs — those run optimistically ahead of
        the device by design. A slot whose binding generation changed
        since the snapshot (released + reallocated) is skipped: the
        cached row describes the previous tenant, and the next
        consistent snapshot will cover the new one.
        """
        if validated is None:
            validated = self.validated_units
        if gens is None:
            gens = self.last_gen
        for slot in doc.seqs.values():
            if gens is None or gens[slot] != self.slot_gen[slot]:
                continue  # snapshot predates this slot's binding
            if bool(overflows[slot]):
                self.retire_doc(name, "overflow")
                return False
            if int(validated[slot]) != int(lengths[slot]):
                # dispatched ops and arena desynced (op rejected on
                # device) — the CPU document stays authoritative; retire
                # the doc so it stops consuming queue/log/kernel
                # resources
                self.retire_doc(name, "desync")
                return False
        return True

    def text(self, name: str) -> Optional[str]:
        """Decode a plain-text document's live text from device state.

        Defined for docs whose content is a single root sequence of
        text units (formats are zero-width, as in Yjs); tree docs and
        value sequences return None — they are served byte-level, not
        materialized. Surrogate-pair handling mirrors Yjs splice
        semantics: a pair decodes as a real character only when its two
        units are id-consecutive from one client AND rank-adjacent —
        every split scenario breaks one of those, yielding the same
        U+FFFD output as the CPU path.
        """
        from ..crdt.content import ContentFormat

        doc = self.docs.get(name)
        if doc is None:
            return None
        if doc.lowerer.unsupported:
            return None  # doc fell back to the CPU path (content/overflow)
        self.materialize_lane(doc)
        roots = [key for key in doc.seqs if key[0] == "root"]
        if len(doc.seqs) != len(roots) or len(roots) > 1:
            return None  # tree-shaped: byte-served, not materialized
        if not roots:
            return ""
        with self._step_lock:  # never read state mid-flush (donation)
            if self.pending_ops() > 0:
                # broadcasts run ahead of the device on purpose; a
                # direct device read must first drain the queues so
                # "live text" means everything enqueued (reentrant lock:
                # _flush_locked re-acquires)
                self._flush_locked(None)
            if not self.check_doc_health(
                name, doc, np.asarray(self.state.length), np.asarray(self.state.overflow)
            ):
                return None
            slot = doc.seqs[roots[0]]
            log = self.unit_logs[slot]
            if self.arena == "rle":
                expanded = self._rle_live_units(doc, slot, log)
                if expanded is None:
                    return None
                clients, clocks, ranks, entries = expanded
            else:
                live = np.asarray(extract_live_mask(self.state))[slot]
                occupied = np.nonzero(live)[0]
                ranks_all = np.asarray(self.state.rank)[slot][occupied]
                order = np.argsort(ranks_all)
                sel = occupied[order]
                ranks = ranks_all[order]
                clients = np.asarray(self.state.id_client)[slot][sel]
                clocks = np.asarray(self.state.id_clock)[slot][sel]
                entries = [log[i] for i in sel]
        out: list[int] = []
        i = 0
        count = len(entries)
        while i < count:
            entry = entries[i]
            if entry is None:
                return None  # RLE: payload not locatable in the unit log
            if not isinstance(entry, int):
                if isinstance(entry, ContentFormat):
                    i += 1  # zero-width formatting boundary
                    continue
                return None  # embeds/values: not a plain text doc
            c = entry
            if 0xD800 <= c <= 0xDBFF:
                nxt = entries[i + 1] if i + 1 < count else None
                if (
                    isinstance(nxt, int)
                    and 0xDC00 <= nxt <= 0xDFFF
                    and clients[i + 1] == clients[i]
                    and clocks[i + 1] == clocks[i] + 1
                    and ranks[i + 1] == ranks[i] + 1
                ):
                    out.append(c)
                    out.append(nxt)
                    i += 2
                    continue
                out.append(0xFFFD)
            elif 0xDC00 <= c <= 0xDFFF:
                out.append(0xFFFD)
            else:
                out.append(c)
            i += 1
        return units_to_text(out)

    def unit_off_index(self, doc: PlaneDoc, slot: int) -> "dict[int, list]":
        """client -> clock-sorted [(clock, unit_off, run_len)] intervals
        for the slot's insert records: maps an arbitrary (client, clock)
        id to its payload position in the slot's unit log. The RLE
        arena stores runs, not per-unit arrival indices, so payload
        lookup goes through the host serve log (which is written at
        enqueue time in dispatch order)."""
        self.materialize_lane(doc)
        index: dict[int, list] = {}
        for rec in doc.serve_log:
            op = rec.op
            if rec.slot != slot or op.kind != KIND_INSERT:
                continue
            # every sequence insert logs exactly run_len entries (units,
            # zero markers for ContentDeleted, repeated Content objects
            # for rich units — lowering._emit_seq), so intervals tile
            # the log densely; gc records are host-only (slot None)
            index.setdefault(op.client, []).append(
                (op.clock, rec.unit_off, op.run_len)
            )
        for intervals in index.values():
            intervals.sort()
        return index

    def _rle_live_units(self, doc: PlaneDoc, slot: int, log: list):
        """Expand the slot's live RLE entries, rank-ordered, to parallel
        per-unit arrays (clients, clocks, ranks, entries) matching the
        unit-arena extraction — payloads resolved via unit_off_index.
        An entry of None means the unit's payload wasn't found (rich
        content in the log, or a divergence): text() returns None."""
        from bisect import bisect_right

        num = int(np.asarray(self.state.num_runs)[slot])
        rcl = np.asarray(self.state.run_client)[slot][:num]
        rck = np.asarray(self.state.run_clock)[slot][:num]
        rln = np.asarray(self.state.run_len)[slot][:num]
        rrk = np.asarray(self.state.run_rank)[slot][:num]
        rdl = np.asarray(self.state.run_deleted)[slot][:num]
        keep = (rln > 0) & ~rdl
        order = np.argsort(rrk[keep])
        index = self.unit_off_index(doc, slot)
        clients: list[int] = []
        clocks: list[int] = []
        ranks: list[int] = []
        entries: list = []
        kcl, kck, kln, krk = rcl[keep], rck[keep], rln[keep], rrk[keep]
        for i in order:
            client, clock0, length, rank0 = (
                int(kcl[i]), int(kck[i]), int(kln[i]), int(krk[i]),
            )
            intervals = index.get(client)
            if not intervals:
                return None
            # a run's payload may span SEVERAL insert records: residency
            # compaction merges id-consecutive fragments whose payloads
            # were logged by different ops — walk the clock range across
            # the intervals instead of requiring a single container
            clk = clock0
            rnk = rank0
            remaining = length
            while remaining > 0:
                pos = bisect_right(intervals, (clk, 0x7FFFFFFF, 0)) - 1
                if pos < 0:
                    return None
                iv_clock, iv_off, iv_len = intervals[pos]
                if not (iv_clock <= clk < iv_clock + iv_len):
                    return None
                take = min(remaining, iv_clock + iv_len - clk)
                base = iv_off + (clk - iv_clock)
                for u in range(take):
                    clients.append(client)
                    clocks.append(clk + u)
                    ranks.append(rnk + u)
                    entries.append(log[base + u] if base + u < len(log) else None)
                clk += take
                rnk += take
                remaining -= take
        return clients, clocks, ranks, entries


class TpuMergeExtension(Extension):
    """Puts live documents on the TPU merge plane via onChange.

    Two modes:
    - shadow (serve=False): the plane mirrors every supported document;
      the CPU document serves (round-1 behavior).
    - serve (serve=True): for supported docs the plane IS the serving
      path — SyncStep2 replies come from device state
      (`Document.sync_source`), per-update CPU fan-out is suppressed
      (`Document.broadcast_source`) and replaced by one merged broadcast
      per device flush. Any degradation (unsupported content, overflow,
      desync) falls the doc back to the CPU path, shipping the full CPU
      state once so receivers that only saw plane broadcasts are whole.

    Replaces the reference's per-connection apply+broadcast loop
    (`packages/server/src/MessageReceiver.ts:195-213`,
    `packages/server/src/Document.ts:228-240`).
    """

    priority = 900

    def __init__(
        self,
        num_docs: int = 256,
        capacity: int = 4096,
        flush_interval_ms: float = 5.0,
        plane: Optional[MergePlane] = None,
        serve: bool = False,
        mesh=None,
        device=None,
        broadcast_interval_ms: float = 2.0,
        arena: str = "unit",
        native_lane: bool = True,
        evict_idle_secs: float = 0.0,
        hydrate_batch: int = 64,
        compact_threshold: float = 0.0,
        governor: bool = True,
        lane=None,
        phase_offset_ms: Optional[float] = None,
        drain_watermark: int = 256,
        flush_stretch: float = 4.0,
        lane_promote_ms: float = 250.0,
    ) -> None:
        """Scheduling knobs (docs/guides/tpu-scheduling.md):

        governor — arrival-aware batching: the flush cadence and the
        kernel calls per cycle follow the op-arrival EWMA, queue depth
        and lane congestion instead of the fixed flush_interval_ms
        (which stays the governor's BASE cadence). False restores the
        fixed timer exactly.
        lane — the device-lane arbiter this extension's device work
        admits through: a DeviceLane instance, None for the process-
        global one (all shards of one chip must share an arbiter), or
        False to disable arbitration entirely (benches' off-leg).
        phase_offset_ms — deterministic timer phase (the sharded router
        assigns i/N spreads so N shards stop tick-aligning dispatches).
        drain_watermark — queue depth that collapses the tick to an
        immediate full drain. flush_stretch — max tick stretch under
        sparse arrivals. lane_promote_ms — lane starvation guard: a
        waiter older than this is promoted to the interactive class.
        """
        if plane is not None and (mesh is not None or device is not None):
            raise ValueError(
                "pass mesh=/device= to the MergePlane you construct, not "
                "alongside plane= (an explicit plane keeps its own device "
                "layout)"
            )
        self.plane = plane or MergePlane(
            num_docs=num_docs,
            capacity=capacity,
            mesh=mesh,
            arena=arena,
            device=device,
        )
        from .scheduler import BatchGovernor, get_device_lane

        if lane is False:
            self.lane = None
        elif lane is None:
            self.lane = get_device_lane()
        else:
            self.lane = lane
        if self.lane is not None:
            self.lane.promote_after_s = max(lane_promote_ms, 0.0) / 1000.0
        self.plane.lane = self.lane
        self.governor = (
            BatchGovernor(
                base_interval_ms=flush_interval_ms,
                max_stretch=flush_stretch,
                drain_watermark=drain_watermark,
            )
            if governor
            else None
        )
        self.phase_offset_ms = phase_offset_ms
        # governor policy inputs ride a short-TTL depth cache:
        # pending_ops() is O(busy slots) and the capture seam calls the
        # governor per update — during a 2k-doc hydration storm an
        # exact walk per capture would cost the interactive path more
        # than the scheduling saves. Policy tolerates 5ms staleness;
        # the post-flush reschedule check stays exact.
        self._depth_cache = 0
        self._depth_cache_at = 0.0
        # native text lane: the C++ host path (lower+log+queue+window)
        # for plain-text docs — the round-3 host-plane bottleneck fix.
        # Serve-mode only (its broadcast windows ride the lane) and
        # contingent on the codec building.
        self.native_lane = bool(native_lane and serve and self.plane.enable_lane())
        self.flush_interval_ms = flush_interval_ms
        # broadcasts build from the HOST serve logs and run on their own
        # (shorter) coalescing window, decoupled from the device flush:
        # edits landing within the window share one frame per doc, and
        # the device round trip (an RTT per transfer when the chip is
        # remote-attached) never sits on the edit->observe path
        self.broadcast_interval_ms = broadcast_interval_ms
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        # single-flight guard for the flush task: captures keep the
        # timer armed, and without this a long background lane hold
        # (one hydration round can run hundreds of ms) would stack one
        # queued flush task per tick — hundreds of waiters the arbiter
        # then scans per grant. One cycle in flight; it reschedules.
        self._flush_inflight = False
        self._broadcast_handle: Optional[asyncio.TimerHandle] = None
        self._last_broadcast_at = 0.0
        self.serve = serve
        self.serving = None
        self._docs: dict[str, object] = {}  # name -> server Document being served
        self._instance = None  # hocuspocus instance (hook dispatch)
        # strong refs to in-flight flush tasks: the event loop only
        # weakly references tasks, and a GC'd flush task silently stops
        # the serve pipeline (or strands the flush lock mid-acquire)
        self._flush_tasks: set = set()
        # docs whose recycle attempt found no headroom for their live
        # state: further attempts are suppressed until unload (each
        # attempt costs a snapshot re-lower under the flush lock, and a
        # queued attempt re-registering the doc must see this verdict —
        # extension-level, since release+register replaces PlaneDocs)
        self._recycle_declined: set[str] = set()
        if serve:
            from .serving import PlaneServing

            self.serving = PlaneServing(self.plane)
            self.serving.flush_failure_handler = self._degrade_all_served
        # arena residency manager (tpu/residency.py): idle-doc eviction,
        # admission-controlled hydration, on-device compaction. Opt-in
        # (serve mode + a nonzero policy knob) so the default extension
        # keeps its permanent-lease behavior exactly.
        self.residency = None
        self._residency_handle: Optional[asyncio.TimerHandle] = None
        if serve and (evict_idle_secs > 0 or compact_threshold > 0):
            from .residency import ResidencyManager

            self.residency = ResidencyManager(
                self,
                evict_idle_secs=evict_idle_secs,
                hydrate_batch=hydrate_batch,
                compact_threshold=compact_threshold,
            )

    def _spawn_tracked(self, coro) -> None:
        spawn_tracked(self._flush_tasks, coro)

    # -- supervisor surface (tpu/supervisor.py) ------------------------------

    def planes(self) -> "list[MergePlane]":
        return [self.plane]

    def servings(self) -> list:
        return [] if self.serving is None else [self.serving]

    def scheduler_snapshot(self) -> dict:
        """Lane + governor state for /debug/scheduler (uniform with the
        sharded router's aggregate)."""
        return {
            "lane": None if self.lane is None else self.lane.snapshot(),
            "governors": [
                None if self.governor is None else self.governor.snapshot()
            ],
            "phase_offsets_ms": [self.phase_offset_ms],
        }

    def is_served(self, document_name: str) -> bool:
        return document_name in self._docs

    def degrade_all(self) -> None:
        """Drain every served doc to the CPU path (full-state fallback
        broadcast each) — the supervisor's breaker-open action."""
        recorder = get_flight_recorder()
        for name in list(self._docs):
            recorder.record(name, "breaker_degrade")
        self._degrade_all_served()

    def cancel_timers(self) -> None:
        """Teardown without touching the device (the supervisor's
        non-READY shutdown: a wedged runtime must not hang destroy)."""
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        if self._broadcast_handle is not None:
            self._broadcast_handle.cancel()
            self._broadcast_handle = None
        if self._residency_handle is not None:
            self._residency_handle.cancel()
            self._residency_handle = None

    async def reonboard(self, document, instance=None) -> None:
        """Fresh plane registration for a live document (supervisor hot
        attach / breaker recovery): drop any previous registration and
        run the ordinary load-time onboarding path."""
        name = document.name
        async with self.plane.flush_lock:
            self._detach_serving(name, self._docs.pop(name, None))
            if name in self.plane.docs:
                self.plane.release(name)
            self._recycle_declined.discard(name)
            if self.residency is not None:
                self.residency.forget_doc(name)
        await self.after_load_document(
            Payload(
                instance=instance if instance is not None else self._instance,
                document_name=name,
                document=document,
            )
        )

    # -- hooks ---------------------------------------------------------------

    async def on_listen(self, data: Payload) -> None:
        """Kick off compile warmup so the first live flush at each batch
        shape doesn't pay XLA/Mosaic compile time in the serving path.

        The warm grid rides the device lane at the LOWEST priority, one
        admission per shape (tpu/scheduler.py): early client flushes
        preempt between compiles instead of waiting out the whole grid,
        and the shared warm registry makes shard 2..N of a sharded
        deployment skip shapes shard 1 already compiled (the jitted
        steps are module-level, so the XLA cache already holds them)."""

        async def warm() -> None:
            from .scheduler import CLASS_CANARY, LaneDeferred

            loop = asyncio.get_event_loop()
            # one lock acquisition per shape: early client syncs and
            # unloads interleave between compiles instead of stalling
            # for the whole warmup
            for shape in (
                self.plane.warmup_shapes() + self.plane.warmup_aux_shapes()
            ):
                ticket = None
                if self.lane is not None:
                    try:
                        ticket = await self.lane.admit(
                            CLASS_CANARY, site="warmup", weight=1
                        )
                    except LaneDeferred:
                        return  # parked: the re-attach warm pass retries
                try:
                    async with self.plane.flush_lock:
                        await loop.run_in_executor(
                            None,
                            lambda s=shape: self.plane.warmup_compiles(
                                s, shared=True
                            ),
                        )
                except Exception:
                    from ..server import logger as _logger_mod

                    _logger_mod.log_error("plane compile warmup failed (continuing)")
                    return
                finally:
                    if ticket is not None:
                        ticket.release(preempted=ticket.should_yield())
            # from here every flush shape is compiled: a later fresh
            # compile is the recompile-storm signal
            self.plane.compile_watch.mark_warmed()
            if self.serving is not None:
                # one lock acquisition per gather width (mirrors the
                # shape loop above): a lane-demote rebuild or an early
                # sync serve slots in between compiles
                for width in self.serving._gather_widths():
                    ticket = None
                    if self.lane is not None:
                        try:
                            ticket = await self.lane.admit(
                                CLASS_CANARY, site="warmup", weight=1
                            )
                        except LaneDeferred:
                            return
                    try:
                        async with self.plane.flush_lock:
                            await loop.run_in_executor(
                                None,
                                lambda w=width: self.serving.warmup_gathers(w),
                            )
                    except Exception:
                        from ..server import logger as _logger_mod

                        _logger_mod.log_error("gather warmup failed (continuing)")
                    finally:
                        if ticket is not None:
                            ticket.release()

        self._spawn_tracked(warm())
        self._schedule_residency()

    def _attach_serving(self, name: str, document) -> None:
        """Hook a document into the plane's serving seams (shared by
        load-time onboarding and capacity recycling — the mirror of
        _detach_serving)."""
        from .serving import TpuSyncSource

        document.sync_source = TpuSyncSource(self.serving, name, document)
        document.broadcast_source = self
        self._docs[name] = document

    async def after_load_document(self, data: Payload) -> None:
        from ..crdt import encode_state_as_update

        self._instance = data.instance
        name = data.document_name
        if self.residency is not None:
            self.residency.touch(name)
            if self.residency.is_evicted(name):
                # cold load of an evicted doc: re-enter through the
                # admission-controlled hydration queue (a storm of cold
                # loads must never thundering-herd the device); the doc
                # serves from the CPU path until its batch lands
                self.residency.request_hydration(name, data.document)
                return
        lane_doc = None
        if self.native_lane:
            lane_doc = self.plane.register_lane(name)
        if lane_doc is None:
            self.plane.register(name)
        snapshot = encode_state_as_update(data.document)
        # receivers get pre-load state via sync, not broadcast
        self.plane.enqueue_update(name, snapshot, presync=True)
        if lane_doc is not None and not self.plane.is_supported(name):
            # load-time lane demote (the snapshot holds rich content):
            # nothing is served yet, so retry on the Python path in
            # place instead of the full fallback+recycle dance.
            # flush_lock: release() rebuilds device state and must not
            # race an executor-side flush holding donated buffers.
            plane_doc = self.plane.docs.get(name)
            if plane_doc is not None and plane_doc.retire_reason == "lane_demote":
                async with self.plane.flush_lock:
                    self.plane.release(name)
                    self.plane.register(name)
                    self.plane.enqueue_update(name, snapshot, presync=True)
        if self.serve and self.plane.is_supported(name):
            self._attach_serving(name, data.document)
        self._schedule_flush()

    async def on_change(self, data: Payload) -> None:
        if self.serve and data.document_name in self._docs:
            return  # already captured synchronously in try_capture
        if self.residency is not None:
            self.residency.touch(data.document_name)
            if self.residency.is_evicted(data.document_name):
                # fresh traffic on an evicted doc: updates ride the CPU
                # fan-out while the doc queues for hydration (the live
                # document tail replayed at admission carries them)
                self.residency.request_hydration(
                    data.document_name, data.document
                )
                return
        if self.serve:
            # fresh traffic on a doc that degraded off the plane (e.g.
            # a device OVERFLOW retire from the health sweep — a seam
            # try_capture never sees, since capture stops at fallback):
            # busy docs are worth re-onboarding from their live snapshot
            plane_doc = self.plane.docs.get(data.document_name)
            if plane_doc is not None and plane_doc.retired:
                self._maybe_recycle(data.document, plane_doc.retire_reason)
                return
        accepted = self.plane.enqueue_update(data.document_name, data.update)
        if accepted and self.governor is not None:
            self.governor.note_arrival(accepted)
        self._schedule_flush()

    async def after_unload_document(self, data: Payload) -> None:
        name = data.document_name
        instance = data.instance
        # release mutates the queue/log registries a concurrent
        # executor-side flush iterates — serialize with it. ALL of the
        # teardown sits inside the lock and behind a liveness re-check:
        # a rejoin can re-load the document while unload hooks await,
        # and plane.register() then reuses this registration (same
        # rows, same lowerer clocks — the arena already mirrors the
        # doc), so a late release here would silently detach the NEW
        # incarnation from the plane for the rest of its life.
        while True:
            async with self.plane.flush_lock:
                loading = (
                    None if instance is None else instance.loading_documents.get(name)
                )
                if loading is None:
                    if instance is not None and name in instance.documents:
                        return  # re-loaded while we waited: registration lives on
                    self._detach_serving(name, self._docs.pop(name, None))
                    self.plane.release(name)
                    # a future incarnation starts with a fresh recycle
                    # budget (its live state may be much smaller).
                    # _lane_banned is deliberately NOT cleared: a doc
                    # that demoted carries rich content in its stored
                    # state — re-trying the lane on every reload would
                    # re-pay the demote transient (degraded cross-
                    # instance flow while the rebuild lands) each time.
                    self._recycle_declined.discard(name)
                    if self.residency is not None:
                        self.residency.forget_doc(name)
                    return
            # A re-load is in flight. Wait for it OUTSIDE the lock: on
            # success its own eventual unload fires this hook again; on
            # FAILURE no further after_unload will ever fire for this
            # name (failed loads never enter instance.documents), so we
            # must loop back and do the teardown ourselves or the plane
            # registration leaks forever.
            try:
                await asyncio.shield(loading)
                return
            except Exception:
                # an already-failed future raises without suspending;
                # yield so create_document's finally (which pops
                # loading_documents) runs before we re-check — without
                # this the loop can spin forever without ever letting
                # the event loop breathe
                await asyncio.sleep(0)

    async def on_destroy(self, data: Payload) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        if self._broadcast_handle is not None:
            self._broadcast_handle.cancel()
        if self._residency_handle is not None:
            self._residency_handle.cancel()
            self._residency_handle = None
        # flush the broadcast tail (LOCAL only: higher-priority
        # extensions like Redis destroy first, so their pub/sub is
        # already closed — peers heal via the join protocol and
        # anti-entropy), then fully drain the device queues: no timer
        # fires after teardown to pick up either. final=True: the drain
        # is pause-exempt — a parked lane must not strand teardown
        self._broadcast_served(cross_instance=False)
        await self._flush_now(max_batches=None, final=True)

    # -- serving: update capture (called by Document._handle_update) ---------

    def is_capturing(self, name: str) -> bool:
        """True when this doc's updates actually ride plane windows
        right now. False during degrade/demote windows, where updates
        take the per-update CPU fan-out — consumers that suppress
        per-op propagation in favor of window frames (the Redis
        extension's cross-instance publish) must fall back to per-op
        when this is False, or remote peers starve down to
        anti-entropy rates."""
        if name not in self._docs:
            return False
        if self.residency is not None and self.residency.is_compacting(name):
            return False  # compaction window: updates ride per-op fan-out
        doc = self.plane.docs.get(name)
        return doc is not None and not doc.retired

    def try_capture(self, document, update: bytes, origin) -> bool:
        """Claim an update for plane-batched broadcast. False = CPU fan-out."""
        from ..server.hocuspocus import REDIS_ORIGIN
        from ..server.types import REPLICA_ORIGIN

        name = document.name
        if not self.serve or name not in self._docs:
            return False
        if self.residency is not None:
            self.residency.touch(name)
            if self.residency.is_compacting(name):
                # an executor-side compaction is rewriting this doc's
                # rows: enqueueing would race the serve-log rebuild.
                # Ride the CPU fan-out (always correct); the manager's
                # post-compaction tail replay re-syncs the plane.
                return False
        plane = self.plane
        if not plane.is_supported(name):
            plane_doc = plane.docs.get(name)
            reason = plane_doc.retire_reason if plane_doc is not None else None
            if reason == "lane_demote":
                # keep serving attached; this update rides the CPU
                # fan-out until the Python-plane registration lands.
                # Re-spawn per update: an earlier attempt may have
                # bailed (e.g. zero connections at the time) and the
                # rebuild's own guards make redundant spawns no-ops.
                self._spawn_tracked(self._rebuild_lane_doc(document))
                return False
            # already degraded (e.g. a device OVERFLOW retire from the
            # post-flush health sweep, where no recycle seam runs) —
            # this fresh traffic is the signal the doc is still busy
            # and worth re-onboarding
            self._fallback_to_cpu(document)
            self._maybe_recycle(document, reason)
            return False
        # capture seam: stamp the (sampled) update with a trace id + its
        # enqueue timestamp BEFORE queueing — an executor-side flush can
        # drain the queue the moment the op lands, and a stamp arriving
        # after that drain would miss its own flush cycle
        book = plane.update_traces
        trace_id = plane.note_trace(name) if book.enabled else None
        # replica-stream applies count as remote ops: the merged window's
        # cross_update must carry only locally-originated ops, or the
        # plane would echo the owner's ticks back over the replica lane
        accepted = plane.enqueue_update(
            name, update, remote=origin in (REDIS_ORIGIN, REPLICA_ORIGIN)
        )
        if trace_id is not None and not accepted:
            # nothing queued (deduplicated, or the doc degraded during
            # the enqueue — where retire already dropped the doc's book)
            book.unstamp(name, trace_id)
        if not plane.is_supported(name):
            # this very update degraded the doc; it broadcasts via CPU
            plane_doc = plane.docs.get(name)
            reason = plane_doc.retire_reason if plane_doc is not None else None
            if reason == "lane_demote":
                # the doc outgrew the native text lane (first map/rich
                # op): rebuild it on the Python plane IN PLACE — serving
                # stays attached, this and subsequent updates ride the
                # per-update CPU fan-out until the rebuild lands
                self._spawn_tracked(self._rebuild_lane_doc(document))
                return False
            self._fallback_to_cpu(document)
            self._maybe_recycle(document, reason)
            return False
        if accepted and self.governor is not None:
            # feed the arrival-rate EWMA BEFORE scheduling: the cadence
            # decision below reads it
            self.governor.note_arrival(accepted)
        self._schedule_flush()
        self._schedule_broadcast()
        return True

    async def _rebuild_lane_doc(self, document) -> None:
        """In-place re-onboard of a lane-demoted doc onto the Python
        plane path.

        Unlike capacity recycling there is no CPU-fallback broadcast:
        receivers stay current through (1) the pending lane window,
        shipped here before the log is dropped, and (2) per-update CPU
        fan-out for every update between the demote and this rebuild
        (try_capture returns False for a retired doc). The ban set
        routes register() to the Python path."""
        from ..crdt import encode_state_as_update

        name = document.name
        plane = self.plane
        async with plane.flush_lock:
            if document.get_connections_count() <= 0:
                return  # unloading anyway
            doc = plane.docs.get(name)
            if (
                doc is None
                or not doc.retired
                or doc.retire_reason != "lane_demote"
                or name not in self._docs
            ):
                return  # state moved on; leave it be
            try:
                pair = self.serving.build_broadcast_pair(name)
            except Exception:
                pair = None
            if pair is not None:
                update, cross = pair
                document.broadcast_update_frame(update)
                if cross is not None and self._instance is not None:
                    self._spawn_tracked(
                        self._instance.hooks(
                            "on_plane_broadcast",
                            Payload(
                                instance=self._instance,
                                document_name=name,
                                document=document,
                                update=cross,
                            ),
                        )
                    )
            try:
                plane.release(name)
                plane.register(name)
                plane.enqueue_update(
                    name, encode_state_as_update(document), presync=True
                )
                new_doc = plane.docs.get(name)
                if new_doc is None or new_doc.lowerer.unsupported:
                    raise RuntimeError("live content unsupported")
                # the cursor still points into the LANE's op log; left
                # stale it would swallow (or mis-slice) every window of
                # the fresh Python-path registration
                self.serving.broadcast_cursor[name] = len(new_doc.serve_log)
            except Exception:
                # genuinely unsupported content: the doc leaves the
                # plane for the plain CPU path
                self._fallback_to_cpu(document)
                return
        self._schedule_flush()

    def _maybe_recycle(self, document, reason: "Optional[str]") -> None:
        """Schedule a recycle for row-exhaustion retires.

        Arena rows are append-only and tree docs hold one row per
        sequence (including deleted subtrees'), so a long-lived busy
        doc eventually exhausts its rows (host-projected: "capacity";
        device-detected mid-flush, e.g. RLE split costs the host bound
        can't see: "overflow") or the plane ("plane_full") — re-onboard
        with fresh rows lowered from the live CPU snapshot. Collected
        SUBTREES vanish from the snapshot, so such docs reclaim most of
        their rows; on the RLE arena a re-lowered snapshot is compact
        again (ContentDeleted runs cost one entry each). Docs whose
        live state itself has no headroom are left on the CPU path by
        the recycle guards. Content retires ("unsupported") and desyncs
        never recycle — the condition is permanent or needs a human.
        """
        if reason not in ("capacity", "plane_full", "overflow", "lane_demote"):
            return
        if document.name in self._recycle_declined:
            return
        self._spawn_tracked(self._recycle_capacity_doc(document))

    async def _recycle_capacity_doc(self, document) -> None:
        """Give a row-exhaustion-retired doc fresh arena rows.

        The triggering update already reached receivers via the CPU
        fallback broadcast; this re-onboards the doc for FUTURE traffic
        exactly like a reload does — release the exhausted rows (ALL of
        them, including deleted subtrees'), re-register, lower the live
        snapshot as presync. If the live state itself nearly fills a
        row (no headroom) or still doesn't fit the plane, the doc stays
        on the CPU path rather than thrash through recycles.
        """
        from .scheduler import CLASS_CATCHUP, LaneDeferred

        ticket = None
        if self.lane is not None:
            try:
                # catch-up class: recovery work for a live busy doc —
                # outranks compaction sweeps, yields to live flushes
                ticket = await self.lane.admit(CLASS_CATCHUP, site="recycle")
            except LaneDeferred:
                return  # parked: the next capture on this doc retries
        try:
            await self._recycle_capacity_doc_admitted(document)
        finally:
            if ticket is not None:
                ticket.release()

    async def _recycle_capacity_doc_admitted(self, document) -> None:
        from ..crdt import encode_state_as_update

        name = document.name
        plane = self.plane
        async with plane.flush_lock:
            if document.get_connections_count() <= 0:
                return  # unloading anyway
            if name in self._docs:
                return  # already re-onboarded
            if name in self._recycle_declined:
                return  # a queued attempt ran after the verdict landed
            existing = plane.docs.get(name)
            if existing is None or not existing.retired:
                return  # registration changed under us; leave it be
            if (
                self.residency is not None
                and existing.retire_reason in ("capacity", "overflow")
            ):
                # on-device compaction first: when the doc's LIVE state
                # fits its rows, the tombstone-GC kernel recycles it in
                # place — no release, no snapshot re-lower, no re-upload.
                # On failure (nothing reclaimable, or the replayed tail
                # re-exhausted the row) fall through to the snapshot
                # recycle below.
                if await self.residency.compact_and_replay_locked(
                    name, document
                ):
                    return
            try:
                plane.release(name)
                # a hot plain-text doc keeps its native lane across the
                # recycle (unless it demoted: the ban set routes it to
                # the Python path inside register_lane)
                if not (self.native_lane and plane.register_lane(name)):
                    plane.register(name)
                snapshot = encode_state_as_update(document)
                plane.enqueue_update(name, snapshot, presync=True)
                doc = plane.docs.get(name)
                if (
                    doc is not None
                    and doc.retired
                    and doc.retire_reason == "lane_demote"
                ):
                    # the doc had never attempted the lane before (not
                    # banned) and its snapshot is rich: retry in place
                    # on the Python path instead of stranding it
                    plane.release(name)
                    plane.register(name)
                    plane.enqueue_update(name, snapshot, presync=True)
                    doc = plane.docs.get(name)
                if doc is None or doc.lowerer.unsupported:
                    self._recycle_declined.add(name)
                    return  # live content unsupported/too big: stays on CPU
                # guard retires below use count=False: this incident was
                # already counted when the original registration retired
                for slot in doc.seqs.values():
                    if plane.projected_len[slot] > plane.capacity * 3 // 4:
                        plane.retire_doc(name, "capacity", count=False)
                        self._recycle_declined.add(name)
                        return  # no row headroom: recycling would thrash
                if len(plane.free) < 2:
                    # plane-level headroom: with no spare rows the next
                    # new sequence would plane_full again immediately —
                    # each thrash cycle costs a full-state broadcast
                    # plus a snapshot re-lower, strictly worse than the
                    # CPU path
                    plane.retire_doc(name, "plane_full", count=False)
                    self._recycle_declined.add(name)
                    return
                plane.counters["docs_recycled"] += 1
                get_flight_recorder().record(name, "recycle")
                self._attach_serving(name, document)
            except Exception:
                # a half-recycled registration (released + re-registered
                # but never attached) would silently swallow ops: mark
                # it retired so the doc lives plainly on the CPU path
                from ..server import logger as _logger_mod

                _logger_mod.log_error(f"recycle failed for {name!r}; staying on CPU")
                plane.retire_doc(name, "fallback", count=False)
                return
        self._schedule_flush()

    def _detach_serving(self, name: str, document) -> None:
        """Unhook a document from the plane's serving seams and drop its
        serving caches (shared by CPU fallback and unload teardown)."""
        if document is not None:
            document.sync_source = None
            document.broadcast_source = None
        if self.serving is not None:
            self.serving.forget(name, self.plane.docs.get(name))

    def _fallback_to_cpu(self, document) -> None:
        name = document.name
        if self._docs.pop(name, None) is None:
            return  # already degraded
        self._detach_serving(name, document)
        if name in self.plane.docs:
            self.plane.retire_doc(name, "fallback")
        self.plane.update_traces.drop(name)
        get_flight_recorder().record(name, "degrade")
        self.plane.counters["cpu_fallbacks"] += 1
        # receivers may hold plane broadcasts only up to the last flush;
        # ship the full CPU state once (dedup makes it a cheap no-op for
        # anyone already current)
        from ..crdt import encode_state_as_update

        document.broadcast_update_frame(encode_state_as_update(document))

    # -- flush ---------------------------------------------------------------

    def _degrade_all_served(self) -> None:
        """Device-flush fault: the dead flush already consumed queued ops,
        so every served doc degrades to the CPU path via a full-state
        broadcast rather than silently dropping captured updates."""
        from ..server import logger as _logger_mod

        _logger_mod.log_error("plane flush failed; degrading served docs to CPU")
        for _, document in list(self._docs.items()):
            try:
                self._fallback_to_cpu(document)
            except Exception:
                _logger_mod.log_error(f"CPU fallback failed for {document.name!r}")

    def _broadcast_served(self, cross_instance: bool = True) -> None:
        """One broadcast pass: every doc with new serve-log records gets
        one merged frame. Pure host work (serve logs + cached health
        rows) — never waits on the device flush; a desync the validator
        finds a cycle later degrades that doc via full-state CPU
        fallback, which supersedes any optimistic frames (receivers
        converge by CRDT idempotence either way)."""
        if not self.serve:
            return
        plane = self.plane
        dirty = list(plane.dirty)
        plane.dirty.clear()
        docs_by_name: dict = {}
        served_dirty: list = []
        for name in dirty:
            document = self._docs.get(name)
            if document is not None:
                docs_by_name[name] = document
                served_dirty.append(name)
        # one vectorized health compare covers the common case; only
        # suspects pay the per-doc check (which retires on failure)
        try:
            healthy, suspects = self.serving.filter_healthy(served_dirty)
        except Exception:
            from ..server import logger as _logger_mod

            _logger_mod.log_error(
                "vectorized health filter failed; falling back to per-doc checks"
            )
            healthy, suspects = [], served_dirty
        for name in suspects:
            document = docs_by_name[name]
            # per-doc guard: the stated safety model is "any serving
            # error degrades that doc to the CPU path" — an exception
            # here must neither strand this doc's ops nor skip the
            # remaining docs' broadcasts
            try:
                if self.serving.doc_healthy(name) is None:
                    self._fallback_to_cpu(document)
                    continue
            except Exception:
                self._degrade_one(name, document)
                continue
            healthy.append(name)
        if not healthy:
            return
        try:
            # lane docs inside resolve in ONE batched native call — the
            # per-doc Python overhead dominates at 10k-doc window widths;
            # Python-path docs are isolated per doc inside (failed list)
            pairs, failed = self.serving.build_broadcast_pairs(healthy)
        except Exception:
            # only the batch call itself can land here (per-doc failures
            # come back in `failed`): a plane-level fault, so degrading
            # the set is the honest outcome
            for name in healthy:
                self._degrade_one(name, docs_by_name[name])
            return
        for name in failed:
            self._degrade_one(name, docs_by_name[name])
        book = plane.update_traces
        for name, pair in pairs:
            document = docs_by_name[name]
            try:
                if pair is None:
                    # empty window (e.g. presync-only records): close any
                    # flushed traces anyway — fan-out was a no-op
                    book.finish(name)
                    continue
                update, cross_update = pair
                # window frames ride the document's broadcast tick
                # (server/fanout.py): one merged frame per audience,
                # catch-up tiering for slow sockets — and the lifecycle
                # trace closes at LAST-SOCKET-ENQUEUE via the tick's
                # completion callback, keeping the span-sum invariant
                # honest about when fan-out actually finished
                document.queue_broadcast(
                    update,
                    on_complete=(
                        lambda t_last, _name=name: book.finish(_name, t_last)
                    ),
                )
                if (
                    cross_instance
                    and cross_update is not None
                    and self._instance is not None
                ):
                    # cross-instance fan-out rides the merged window
                    # frame (extensions like Redis publish it) minus
                    # remote-origin ops, replacing per-op SyncStep1
                    # chatter with one coalesced message per window
                    self._spawn_tracked(
                        self._instance.hooks(
                            "on_plane_broadcast",
                            Payload(
                                instance=self._instance,
                                document_name=name,
                                document=document,
                                update=cross_update,
                            ),
                        )
                    )
            except Exception:
                self._degrade_one(name, document)

    def _degrade_one(self, name: str, document) -> None:
        from ..server import logger as _logger_mod

        _logger_mod.log_error(
            f"plane broadcast failed for {name!r}; degrading to CPU path"
        )
        try:
            self._fallback_to_cpu(document)
        except Exception:
            _logger_mod.log_error(f"CPU fallback failed for {name!r}")

    async def _flush_now(
        self, max_batches: Optional[int] = 1, final: bool = False
    ) -> None:
        """Flush+serve with the DEVICE step off the event loop.

        plane.flush() host-syncs on the integrate step; running it
        inline froze the loop for the duration of every device step
        (measured 16x send-throughput loss on the CPU backend at config2
        shape). The executor hop keeps websockets pumping while the
        device integrates; the lock serializes against the batched
        catch-up drain and unload-time registry mutation.

        Broadcasts do NOT run here: they build from the host serve logs
        on their own timer (_schedule_broadcast), so the device cycle —
        upload + kernel + one combined health readback, each transfer ~a
        full RTT on a remote-attached chip — only gates validation and
        sync serves, never the edit->observe path. The default of ONE
        kernel batch per cycle keeps cycles short; the remainder
        reschedules. on_destroy passes final=True with max_batches=None
        for a pause-exempt full drain — no timer fires after teardown.

        The cycle admits through the device lane as INTERACTIVE before
        touching the flush lock (tpu/scheduler.py): background clients
        — hydration batches, compaction sweeps, warm compiles — queue
        behind it and yield between their own microbatches, so a 2-doc
        flush never sits behind a full-population sweep. A parked lane
        (supervisor breaker open) defers the cycle instead of stacking
        blocked tasks onto a wedged device.
        """
        from .scheduler import CLASS_INTERACTIVE, CLASS_NAMES, LaneDeferred

        if self._flush_inflight and not final:
            return  # the in-flight cycle reschedules; don't stack waiters
        self._flush_inflight = True
        try:
            ticket = None
            if self.lane is not None:
                try:
                    ticket = await self.lane.admit(
                        CLASS_INTERACTIVE,
                        site="flush",
                        ignore_pause=final,
                        deadline_s=5.0 if final else None,
                    )
                except LaneDeferred as deferred:
                    get_flight_recorder().record(
                        "__plane__",
                        "flush_deferred",
                        lane_class=CLASS_NAMES[deferred.lane_class],
                        wait_ms=round(deferred.waited_s * 1000.0, 3),
                        reason=deferred.reason,
                    )
                    if final:
                        ticket = None  # teardown drain proceeds unarbitrated
                    elif self.plane.pending_ops() > 0:
                        # parked: retry on a slow cadence (the supervisor
                        # resumes the lane at re-attach; a tight retry loop
                        # would just churn timers against a wedged device)
                        self._schedule_flush(delay_override=0.25)
                        return
                    else:
                        return
            try:
                if self.governor is not None and max_batches == 1:
                    congested = self.lane is not None and self.lane.contended()
                    max_batches = self.governor.max_batches(
                        self._policy_depth(), congested
                    )
                async with self.plane.flush_lock:
                    try:
                        await asyncio.get_event_loop().run_in_executor(
                            None, lambda: self.plane.flush(max_batches)
                        )
                        if self.serve:
                            self.serving.refresh()
                    except Exception:
                        self._degrade_all_served()
                        return
                    if self.serve:
                        self._validate_served()
                if self.governor is not None:
                    self.governor.note_cycle(self.plane.flush_stats)
            finally:
                if ticket is not None:
                    ticket.release()
            if self.plane.pending_ops() > 0:
                self._schedule_flush()
            elif self.governor is not None:
                self.governor.note_park()
        finally:
            self._flush_inflight = False

    def _validate_served(self) -> None:
        """Post-flush desync sweep, vectorized over every slot.

        Broadcasts run optimistically ahead of the device, so this
        sweep — one numpy compare of the flush's combined readback
        against the validated dispatch tallies — is what catches a
        device-side op rejection even when no further edit or sync
        would touch the doc. Affected served docs degrade to the CPU
        path via the usual full-state fallback broadcast.
        """
        plane = self.plane
        if plane.last_lengths is None or plane.last_gen is None:
            return
        bad = (
            plane.slot_live
            & (plane.last_gen == plane.slot_gen)
            & ((plane.validated_units != plane.last_lengths) | plane.last_overflows)
        )
        if not bad.any():
            return
        for slot in np.nonzero(bad)[0]:
            name = plane.slot_owner.get(int(slot))
            if name is None:
                continue
            # doc_healthy retires with the right reason; served docs
            # then fall back with the one-time full-state broadcast
            if self.serving.doc_healthy(name) is None:
                document = self._docs.get(name)
                if document is not None:
                    self._fallback_to_cpu(document)

    def _schedule_flush(self, delay_override: Optional[float] = None) -> None:
        if self._flush_handle is not None:
            return

        def run() -> None:
            self._flush_handle = None
            self._spawn_tracked(self._flush_now())

        if delay_override is not None:
            delay = delay_override
        elif self.governor is not None:
            # arrival-aware cadence: immediate full drain past the
            # queue-depth watermark, base cadence under steady load or
            # lane congestion, stretched ticks when arrivals are sparse
            congested = self.lane is not None and self.lane.contended()
            delay = self.governor.flush_delay_s(
                self._policy_depth(), congested
            )
        else:
            delay = self.flush_interval_ms / 1000
        if delay:
            # sustained-cadence ticks quantize onto the shard's phase
            # grid; the watermark's zero-delay drain stays IMMEDIATE
            # (same exemption as the broadcast scheduler's idle path)
            delay = self._align_to_phase(delay, self.flush_interval_ms / 1000)
        self._flush_handle = asyncio.get_event_loop().call_later(delay, run)

    def _policy_depth(self) -> int:
        """Queued-op depth for GOVERNOR decisions only (5ms-stale)."""
        now = time.monotonic()
        if now - self._depth_cache_at > 0.005:
            self._depth_cache = self.plane.pending_ops()
            self._depth_cache_at = now
        return self._depth_cache

    def _align_to_phase(self, delay: float, interval_s: float) -> float:
        """Deterministic per-shard timer stagger: quantize the fire time
        onto this shard's phase grid (offset i/N of the interval, set by
        the sharded router) so N shards stop tick-aligning their device
        dispatches. Never fires earlier than asked — alignment only adds
        up to one interval. No-op for unsharded extensions."""
        if self.phase_offset_ms is None or interval_s <= 0:
            return delay
        now = asyncio.get_event_loop().time()
        phase = (self.phase_offset_ms / 1000.0) % interval_s
        fire = now + delay
        aligned = (
            math.ceil((fire - phase) / interval_s) * interval_s + phase
        )
        return max(aligned - now, delay)

    def _schedule_residency(self) -> None:
        """Periodic residency maintenance (eviction + proactive
        compaction sweeps), riding its own timer like the flush and
        broadcast cadences."""
        if self.residency is None or self._residency_handle is not None:
            return

        def run() -> None:
            self._residency_handle = None
            self._spawn_tracked(self._residency_tick())

        self._residency_handle = asyncio.get_event_loop().call_later(
            self.residency.maintenance_interval, run
        )

    async def _residency_tick(self) -> None:
        try:
            await self.residency.run_maintenance()
        except Exception:
            from ..server import logger as _logger_mod

            _logger_mod.log_error("residency maintenance failed (continuing)")
        self._schedule_residency()

    def _schedule_broadcast(self) -> None:
        if not self.serve or self._broadcast_handle is not None:
            return
        loop = asyncio.get_event_loop()

        def run() -> None:
            self._broadcast_handle = None
            self._last_broadcast_at = loop.time()
            self._broadcast_served()

        # coalescing window only under sustained traffic: a lone edit
        # after an idle gap broadcasts on the next loop tick (the
        # window would be pure added latency), while back-to-back edits
        # within the window share one frame per doc. Sustained-traffic
        # windows quantize onto the shard's phase grid (sharded router)
        # so N shards' broadcast passes stop landing on the same tick.
        window = self.broadcast_interval_ms / 1000
        idle = loop.time() - self._last_broadcast_at
        delay = 0.0 if idle >= window else window
        if delay:
            delay = self._align_to_phase(delay, window)
        self._broadcast_handle = loop.call_later(delay, run)
