"""Multi-chip sharding for the merge plane (jax.sharding + jit).

The doc axis is the data-parallel dimension (SURVEY.md §5.7: documents
are the scaling dimension); the arena (unit) axis is the
sequence-parallel dimension. Shardings are annotated and XLA inserts the
collectives (all-gathers for cross-shard gathers, all-reduce for the
global op count) — the ICI-riding equivalent of the reference's
Redis fan-out data plane.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .kernels import DocState, OpBatch, integrate_op_slots, make_empty_state


def enumerate_devices(count: int = 0) -> list:
    """The device roster for the per-chip cell plane (tpu/cells.py).

    count <= 0 means "every local device" (the MULTICHIP capture's 8
    chips); an explicit count larger than the physical roster wraps
    (cell i pins to device i % n) so CI hosts with one forced-host CPU
    device can still exercise an 8-cell plane, and a count smaller than
    the roster uses the first `count` chips."""
    devices = jax.local_devices()
    if count <= 0:
        return list(devices)
    return [devices[i % len(devices)] for i in range(count)]


def make_mesh(devices: Optional[list] = None, doc_axis: Optional[int] = None) -> Mesh:
    """1D or 2D mesh over (doc, unit). Defaults to all devices on doc."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if doc_axis is None:
        doc_axis = n
    unit_axis = n // doc_axis
    device_array = np.asarray(devices).reshape(doc_axis, unit_axis)
    return Mesh(device_array, ("doc", "unit"))


def state_sharding(mesh: Mesh) -> DocState:
    """NamedShardings for each DocState field."""
    arena = NamedSharding(mesh, P("doc", "unit"))
    per_doc = NamedSharding(mesh, P("doc"))
    return DocState(
        id_client=arena,
        id_clock=arena,
        rank=arena,
        origin_rank=arena,
        deleted=arena,
        length=per_doc,
        overflow=per_doc,
    )


def ops_sharding(mesh: Mesh) -> OpBatch:
    slot_doc = NamedSharding(mesh, P(None, "doc"))
    return OpBatch(
        kind=slot_doc,
        client=slot_doc,
        clock=slot_doc,
        run_len=slot_doc,
        left_client=slot_doc,
        left_clock=slot_doc,
        right_client=slot_doc,
        right_clock=slot_doc,
    )


def make_sharded_step(mesh: Mesh, use_pallas: Optional[bool] = None, interpret: bool = False):
    """Jitted multi-chip integrate step with explicit in/out shardings.

    The returned callable takes (DocState, OpBatch with (K, D, ...) op
    slots) and returns (DocState, integrated-op count). The op count is
    a global reduction — XLA lowers it to an all-reduce over the mesh.

    Two lowering strategies:
    - XLA scan (default off-TPU, and whenever the arena axis is itself
      sharded): plain jit with shardings; XLA inserts the collectives
      that the arena-axis reductions need.
    - Pallas per shard (default on TPU with a doc-only mesh): shard_map
      over the 'doc' axis runs the VMEM-resident kernel independently
      on each device's doc shard — zero cross-device traffic in the hot
      loop, one psum for the global count. Documents never interact, so
      doc-parallelism is embarrassingly parallel by construction.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and mesh.shape["unit"] == 1
    if use_pallas and mesh.shape["unit"] != 1:
        raise ValueError("the Pallas sharded step requires a doc-only mesh")

    if not use_pallas:
        st_shard = state_sharding(mesh)
        op_shard = ops_sharding(mesh)
        count_sharding = NamedSharding(mesh, P())
        return jax.jit(
            integrate_op_slots.__wrapped__,  # re-jit with shardings
            in_shardings=(st_shard, op_shard),
            out_shardings=(st_shard, count_sharding),
            donate_argnums=(0,),
        )

    from .pallas_kernels import integrate_op_slots_pallas

    arena = P("doc", None)
    per_doc = P("doc")
    st_spec = DocState(arena, arena, arena, arena, arena, per_doc, per_doc)
    op_spec_p = P(None, "doc")
    ops_spec = OpBatch(*([op_spec_p] * 8))

    def local_step(state: DocState, ops: OpBatch):
        new_state, count = integrate_op_slots_pallas(state, ops, interpret=interpret)
        return new_state, jax.lax.psum(count, "doc")

    return jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(st_spec, ops_spec),
            out_specs=(st_spec, P()),
            # pallas_call out_shapes carry no varying-mesh-axes info
            check_vma=False,
        ),
        donate_argnums=(0,),
    )


def sparse_ops_sharding(mesh: Mesh) -> "tuple[OpBatch, NamedSharding]":
    """(K, B) sparse op batches + the (B,) slot-routing vector are tiny
    (B = busy docs, not the population) — replicate them across the
    mesh and let XLA route each busy row's gather/scatter to the shard
    that owns it. Returns (op shardings, slots sharding)."""
    replicated = NamedSharding(mesh, P(None, None))
    return OpBatch(*([replicated] * 8)), NamedSharding(mesh, P(None))


def make_sharded_sparse_step(mesh: Mesh):
    """Jitted multi-chip SPARSE integrate step: (K, B) ops + (B,) slot
    routing against the doc-sharded arenas. The gather/scatter pair is
    partitioned by XLA — each shard materializes only its own busy
    rows' updates (collectives route rows whose owner differs from the
    batch layout), so per-flush traffic scales with B, not D."""
    from .kernels import integrate_op_slots_sparse

    st_shard = state_sharding(mesh)
    op_shard, slot_shard = sparse_ops_sharding(mesh)
    count_sharding = NamedSharding(mesh, P())
    return jax.jit(
        integrate_op_slots_sparse.__wrapped__,
        in_shardings=(st_shard, op_shard, slot_shard),
        out_shardings=(st_shard, count_sharding),
        donate_argnums=(0,),
    )


def make_sharded_append_step(mesh: Mesh):
    """Jitted multi-chip run-append step (the sequential fast path):
    three replicated (K, B) run fields + the (B,) slot routing vector
    against the doc-sharded arenas — the same small-batch replication
    discipline as make_sharded_sparse_step, so per-flush traffic scales
    with B whichever path the classifier picks."""
    from .kernels import append_run_slots_sparse

    st_shard = state_sharding(mesh)
    _, slot_shard = sparse_ops_sharding(mesh)
    replicated = NamedSharding(mesh, P(None, None))
    count_sharding = NamedSharding(mesh, P())
    return jax.jit(
        append_run_slots_sparse.__wrapped__,
        in_shardings=(st_shard, replicated, replicated, replicated, slot_shard),
        out_shardings=(st_shard, count_sharding),
        donate_argnums=(0,),
    )


def make_sharded_rle_append_step(mesh: Mesh):
    """RLE twin of make_sharded_append_step."""
    from .kernels_rle import append_run_slots_rle_sparse

    st_shard = rle_state_sharding(mesh)
    _, slot_shard = sparse_ops_sharding(mesh)
    replicated = NamedSharding(mesh, P(None, None))
    count_sharding = NamedSharding(mesh, P())
    return jax.jit(
        append_run_slots_rle_sparse.__wrapped__,
        in_shardings=(st_shard, replicated, replicated, replicated, slot_shard),
        out_shardings=(st_shard, count_sharding),
        donate_argnums=(0,),
    )


def make_sharded_compact_step(mesh: Mesh):
    """Jitted multi-chip compact (tombstone-GC) step: the (B,) slot
    routing vector replicates like the sparse op batches, the
    doc-sharded arenas stay in place, and XLA partitions the
    gather/compact/scatter so only the shards owning routed rows do
    work (residency compaction touches a handful of rows at a time)."""
    from .kernels import compact_doc_rows

    st_shard = state_sharding(mesh)
    _, slot_shard = sparse_ops_sharding(mesh)
    lengths_sharding = NamedSharding(mesh, P(None))
    return jax.jit(
        compact_doc_rows.__wrapped__,
        in_shardings=(st_shard, slot_shard),
        out_shardings=(st_shard, lengths_sharding),
        donate_argnums=(0,),
    )


def make_sharded_rle_compact_step(mesh: Mesh):
    """RLE twin of make_sharded_compact_step (defragmentation)."""
    from .kernels_rle import compact_doc_rows_rle

    st_shard = rle_state_sharding(mesh)
    _, slot_shard = sparse_ops_sharding(mesh)
    counts_sharding = NamedSharding(mesh, P(None))
    return jax.jit(
        compact_doc_rows_rle.__wrapped__,
        in_shardings=(st_shard, slot_shard),
        out_shardings=(st_shard, counts_sharding),
        donate_argnums=(0,),
    )


def make_sharded_rle_sparse_step(mesh: Mesh):
    """RLE twin of make_sharded_sparse_step."""
    from .kernels_rle import integrate_op_slots_rle_sparse

    st_shard = rle_state_sharding(mesh)
    op_shard, slot_shard = sparse_ops_sharding(mesh)
    count_sharding = NamedSharding(mesh, P())
    return jax.jit(
        integrate_op_slots_rle_sparse.__wrapped__,
        in_shardings=(st_shard, op_shard, slot_shard),
        out_shardings=(st_shard, count_sharding),
        donate_argnums=(0,),
    )


def make_sharded_state(mesh: Mesh, num_docs: int, capacity: int) -> DocState:
    state = make_empty_state(num_docs, capacity)
    shardings = state_sharding(mesh)
    return DocState(
        *(jax.device_put(field, sharding) for field, sharding in zip(state, shardings))
    )


# -- run-length arena ---------------------------------------------------------


def rle_state_sharding(mesh: Mesh):
    """NamedShardings for each RleState field: entry axis rides the
    mesh's 'unit' axis (the sequence-parallel dimension), doc axis is
    data-parallel — same layout discipline as the unit arena."""
    from .kernels_rle import RleState

    arena = NamedSharding(mesh, P("doc", "unit"))
    per_doc = NamedSharding(mesh, P("doc"))
    return RleState(
        run_client=arena,
        run_clock=arena,
        run_len=arena,
        run_rank=arena,
        run_orank=arena,
        run_deleted=arena,
        num_runs=per_doc,
        total_units=per_doc,
        overflow=per_doc,
    )


def make_sharded_rle_state(mesh: Mesh, num_docs: int, entries: int):
    from .kernels_rle import make_empty_rle_state

    state = make_empty_rle_state(num_docs, entries)
    shardings = rle_state_sharding(mesh)
    return type(state)(
        *(jax.device_put(field, sharding) for field, sharding in zip(state, shardings))
    )


def make_sharded_rle_step(mesh: Mesh, use_pallas: Optional[bool] = None, interpret: bool = False):
    """Jitted multi-chip RLE integrate step; same two lowering
    strategies as make_sharded_step (XLA scan with shardings, or
    shard_map(Pallas) over a doc-only mesh)."""
    from .kernels_rle import RleState, integrate_op_slots_rle

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and mesh.shape["unit"] == 1
    if use_pallas and mesh.shape["unit"] != 1:
        raise ValueError("the Pallas sharded RLE step requires a doc-only mesh")

    if not use_pallas:
        st_shard = rle_state_sharding(mesh)
        op_shard = ops_sharding(mesh)
        count_sharding = NamedSharding(mesh, P())
        return jax.jit(
            integrate_op_slots_rle.__wrapped__,
            in_shardings=(st_shard, op_shard),
            out_shardings=(st_shard, count_sharding),
            donate_argnums=(0,),
        )

    from .pallas_kernels_rle import integrate_op_slots_rle_pallas

    arena = P("doc", None)
    per_doc = P("doc")
    st_spec = RleState(*([arena] * 6 + [per_doc] * 3))
    ops_spec = OpBatch(*([P(None, "doc")] * 8))

    def local_step(state, ops):
        new_state, count = integrate_op_slots_rle_pallas(state, ops, interpret=interpret)
        return new_state, jax.lax.psum(count, "doc")

    return jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(st_spec, ops_spec),
            out_specs=(st_spec, P()),
            check_vma=False,
        ),
        donate_argnums=(0,),
    )
