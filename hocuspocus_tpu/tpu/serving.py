"""Serve sync replies and broadcasts from TPU merge-plane state.

This is the piece that promotes the merge plane from a shadow mirror to
the serving path: for supported documents, SyncStep2 payloads and
steady-state update broadcasts are PRODUCED from device state — arena
ids / rank / tombstones read back from the TPU, combined with the
host-side serve/unit logs — instead of from the CPU document
(reference hot path: `packages/server/src/MessageReceiver.ts:137-213`
building SyncStep2 via `Y.encodeStateAsUpdate`, and
`packages/server/src/Document.ts:228-240` re-broadcasting every
incoming update per-connection).

Safety model:
- The CPU document stays the fallback: every serve checks the plane is
  healthy (supported, no overflow, host/device logs in sync) AND covers
  the CPU document's state vector; otherwise the caller falls back.
- SYNC serves read delete sets for *sequence* content from the DEVICE
  tombstone mask — a cold joiner can never receive a deletion the
  kernel did not apply. Map-item deletions (host-only content that
  never rides the device) are merged in from the host tombstone log.
- BROADCASTS ship the window's own delete ranges from the serve log
  (O(window), not O(doc-lifetime tombstones)): the kernel applies
  id-range tombstones unconditionally over ids the lowerer proved
  integrated, and any host/device divergence retires the doc via the
  health check (full-state CPU fallback) before the next broadcast —
  see build_broadcast.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..crdt.content import ContentDeleted, ContentString
from ..crdt.delete_set import DeleteSet
from ..crdt.encoding import Encoder
from ..crdt.ids import ID
from ..crdt.structs import GC, Item
from ..crdt.update import _write_structs, decode_state_vector
from ..observability.tracing import get_tracer
from ..observability.wire import get_wire_telemetry
from .kernels import KIND_DELETE, KIND_INSERT, NONE_CLIENT
from .lowering import DenseOp, units_to_text
from .merge_plane import LogRec, MergePlane, PlaneDoc


class SyncFrameCache:
    """Join-storm sync cache: (doc, state-vector) -> encoded SyncStep2
    payload, scoped to the serve-log/flush epoch.

    A join storm is N clients asking for the same diff between two
    flushes — cold joiners (empty state vector) after a deploy, or a
    partitioned building's worth of tabs reconnecting with the same
    stale SV. Entries key on the doc name + the CUTOFF MAP actually
    encoded (canonical: sorted (client, clock) pairs — two wire SVs
    that trim to the same cutoffs share one entry) and validate against
    (PlaneDoc identity, serve-log key, plane flush epoch): any
    integrated op (log grows), device flush (epoch bump), compaction
    (epoch bump + `forget`), or re-registration (fresh PlaneDoc) misses
    naturally. `forget(name)` — unload/evict/degrade — drops a doc's
    entries outright. Bounded per doc (LRU): distinct stale SVs are
    unbounded in principle, and one hot doc must not evict another
    doc's storm entry.
    """

    PER_DOC_CAP = 32

    def __init__(self) -> None:
        # name -> OrderedDict[sv_key -> (PlaneDoc, epoch_key, payload)]
        self._by_name: "dict[str, OrderedDict]" = {}
        self.evictions = 0

    def get(self, name: str, doc, epoch_key, sv_key) -> Optional[bytes]:
        entries = self._by_name.get(name)
        if entries is None:
            return None
        entry = entries.get(sv_key)
        if entry is None:
            return None
        if entry[0] is not doc or entry[1] != epoch_key:
            del entries[sv_key]  # stale epoch: drop eagerly
            return None
        entries.move_to_end(sv_key)
        return entry[2]

    def put(self, name: str, doc, epoch_key, sv_key, payload: bytes) -> None:
        entries = self._by_name.setdefault(name, OrderedDict())
        entries[sv_key] = (doc, epoch_key, payload)
        entries.move_to_end(sv_key)
        while len(entries) > self.PER_DOC_CAP:
            entries.popitem(last=False)
            self.evictions += 1

    def forget(self, name: str) -> None:
        entries = self._by_name.pop(name, None)
        if entries:
            self.evictions += len(entries)

    # dict-like surface for tests / debugging
    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __bool__(self) -> bool:
        return bool(self._by_name)

    def __len__(self) -> int:
        return sum(len(entries) for entries in self._by_name.values())


def _wire_parent(parent: Optional[tuple]):
    """DenseOp parent tuple -> the Item.write representation."""
    if parent is None:
        return None
    if parent[0] == "root":
        return parent[1]
    return ID(parent[1], parent[2])


def _make_item(rec: LogRec, unit_logs: dict):
    op = rec.op
    if op.gc:
        # collected subtree: re-encode the clock range verbatim
        return GC(ID(op.client, op.clock), op.run_len)
    origin = ID(op.left_client, op.left_clock) if op.left_client != NONE_CLIENT else None
    right_origin = (
        ID(op.right_client, op.right_clock) if op.right_client != NONE_CLIENT else None
    )
    if op.content is not None:
        content = op.content
    elif op.deleted_content:
        content = ContentDeleted(op.run_len)
    else:
        log = unit_logs[rec.slot]
        content = ContentString(
            units_to_text(log[rec.unit_off : rec.unit_off + op.run_len])
        )
    return Item(
        ID(op.client, op.clock),
        None,
        origin,
        None,
        right_origin,
        _wire_parent(op.parent),  # consulted by Item.write only when origin-less
        op.parent_sub,
        content,
    )


class PlaneServing:
    """Builds yjs update bytes from plane state for sync + broadcast."""

    def __init__(self, plane: MergePlane) -> None:
        self.plane = plane
        # doc name -> serve_log index whose records receivers already have
        self.broadcast_cursor: dict[str, int] = {}
        self._length_cache: Optional[np.ndarray] = None
        self._overflow_cache: Optional[np.ndarray] = None
        self._validated_cache: Optional[np.ndarray] = None
        self._gen_cache: Optional[np.ndarray] = None
        # slot -> ((slot_gen, flush_epoch), sorted merged deleted
        # (client, clock, length) ranges): see _slot_deleted_ranges
        self._tombstone_cache: dict[int, tuple] = {}
        # join-storm sync cache: every joiner asking for the same diff
        # of the same epoch receives the SAME SyncStep2 bytes (sync
        # serves drain the queues first, so the payload is a pure
        # function of the serve log + cutoff map) — a reconnect storm
        # re-encodes once per (doc state, SV), not once per joiner.
        # Generalizes the old cold-only cache to arbitrary stale SVs.
        self._sync_cache = SyncFrameCache()
        # catch-up batching: SyncStep1s that arrive in the same storm
        # window are triaged by ONE state_vector_diff kernel call
        self._catchup_queue: list[tuple] = []  # (name, document, sv_bytes, future)
        self._catchup_scheduled = False
        self._drain_tasks: set = set()
        # set by TpuMergeExtension: invoked when a device flush dies so
        # served docs degrade to the CPU path (captured ops were already
        # popped from the queues — they only survive via the full-state
        # fallback broadcast)
        self.flush_failure_handler = None
        # supervisor drain seam (tpu/supervisor.py): while paused, every
        # sync serve resolves to None (CPU fallback) WITHOUT touching
        # the device — a wedged runtime must never stall a document
        self.paused = False
        # on-device catch-up encode: tombstone reads ship as packed
        # (counts + tombstones) readbacks instead of full arena rows;
        # rows whose tombstone count overflows the pack width fall back
        # to the full-row gather per chunk (see _fetch_slot_rows)
        self.device_pack_enabled = True
        # unresolved batched-sync futures, so abort_pending can resolve
        # waiters stranded behind a wedged flush
        self._inflight: set = set()

    # -- device readback cache ---------------------------------------------

    def refresh(self) -> None:
        """Adopt the plane's last combined health readback; per-slot
        checks then stay host-side.

        The three caches — lengths, overflows, validated dispatch
        tallies — are snapshotted together under the step lock so they
        describe ONE device state: serve logs run optimistically ahead
        of the device, and comparing rows from flush N against tallies
        from flush N+1 would misread healthy docs as desynced. When the
        plane has already fetched the rows this cycle (_sync_health),
        this costs no device I/O at all."""
        plane = self.plane
        with plane._step_lock:
            if plane.last_lengths is not None:
                self._length_cache = plane.last_lengths
                self._overflow_cache = plane.last_overflows
            else:
                t0 = time.perf_counter()
                self._length_cache = np.asarray(plane.state.length)
                self._overflow_cache = np.asarray(plane.state.overflow)
                # cache-miss path only: a real device→host transfer,
                # charged to the same stall meter as the flush barrier
                plane.device_stats["readback_stall_ms_total"] += (
                    time.perf_counter() - t0
                ) * 1000.0
                plane.device_stats["readback_stalls"] += 1
            self._validated_cache = plane.validated_units.copy()
            self._gen_cache = None if plane.last_gen is None else plane.last_gen.copy()

    def _lengths(self) -> np.ndarray:
        if self._length_cache is None:
            self.refresh()
        return self._length_cache

    def _overflows(self) -> np.ndarray:
        if self._overflow_cache is None:
            self.refresh()
        return self._overflow_cache

    def forget(self, name: str, doc: Optional[PlaneDoc]) -> None:
        """Drop every per-doc serving cache at unload/degrade time.

        The sync cache holds a strong ref to the PlaneDoc (and its
        whole serve log); without eviction a server that churns through
        transient doc names leaks each one forever.
        """
        self.broadcast_cursor.pop(name, None)
        self._sync_cache.forget(name)
        if doc is not None:
            for slot in doc.seqs.values():
                self._tombstone_cache.pop(slot, None)
            if doc.lane_slot is not None:
                # lane slots may predate root discovery (not yet in
                # seqs): a stale entry left here would survive into the
                # slot's next tenant's cache lookups
                self._tombstone_cache.pop(doc.lane_slot, None)

    # -- health -------------------------------------------------------------

    def doc_healthy(self, name: str) -> Optional[PlaneDoc]:
        plane = self.plane
        doc = plane.docs.get(name)
        if doc is None:
            return None
        if doc.lowerer.unsupported:
            return None
        if self._length_cache is None:
            # no completed flush has been adopted yet — there is nothing
            # to validate against, and the broadcast path must NEVER
            # block on the step lock / pull device state on the event
            # loop (a first flush may be mid-executor right now). The
            # post-flush sweep covers these docs the moment a snapshot
            # exists.
            return doc
        if not plane.check_doc_health(
            name,
            doc,
            self._length_cache,
            self._overflow_cache,
            self._validated_cache,
            self._gen_cache,
        ):
            return None
        return doc

    def filter_healthy(self, names: "list[str]") -> "tuple[list[str], list[str]]":
        """(fast_ok, needs_check): one vectorized compare replaces the
        per-doc health loop for the common case (registered, supported,
        single-row doc whose cached device row matches its validated
        tally). A STALE-generation row fast-OKs — check_doc_health
        skips such slots too (the snapshot predates the binding; the
        next consistent snapshot covers it). needs_check gets the
        genuinely suspicious cases — unregistered, unsupported,
        mismatching current-generation row, multi-row trees, no
        snapshot yet — for the full doc_healthy treatment (which also
        performs the retire-on-failure side effects)."""
        plane = self.plane
        if self._length_cache is None or self._gen_cache is None:
            return [], list(names)
        candidates: list[str] = []
        slots: list[int] = []
        needs_check: list[str] = []
        for name in names:
            doc = plane.docs.get(name)
            if doc is None or doc.lowerer.unsupported:
                needs_check.append(name)
                continue
            doc_slots = list(doc.seqs.values())
            if len(doc_slots) == 0:
                candidates.append(name)
                slots.append(-1)
            elif len(doc_slots) == 1:
                candidates.append(name)
                slots.append(doc_slots[0])
            else:
                needs_check.append(name)  # multi-row trees: full check
        if not candidates:
            return [], needs_check
        arr = np.asarray(slots, np.int64)
        rowless = arr < 0
        safe = np.where(rowless, 0, arr)
        gen_current = self._gen_cache[safe] == plane.slot_gen[safe]
        mismatch = (
            (self._validated_cache[safe] != self._length_cache[safe])
            | self._overflow_cache[safe]
        )
        ok = rowless | ~gen_current | ~mismatch
        fast_ok = [name for name, good in zip(candidates, ok) if good]
        needs_check.extend(
            name for name, good in zip(candidates, ok) if not good
        )
        return fast_ok, needs_check

    def _local_sv(self, doc: PlaneDoc) -> dict:
        """The plane's integrated clocks for this doc (lane docs keep
        them natively; others in the Python lowerer)."""
        plane = self.plane
        if doc.lane_slot is not None and plane._lane is not None:
            return plane._lane_codec.lane_known(plane._lane, doc.lane_slot)
        return dict(doc.lowerer.known)

    def covers(self, name: str, document) -> bool:
        """Plane has integrated everything the CPU document has seen."""
        plane = self.plane
        doc = plane.docs.get(name)
        if doc is None:
            return False
        sv = document.store.get_state_vector()
        if doc.lane_slot is not None and plane._lane is not None:
            return bool(
                plane._lane_codec.lane_covers(
                    plane._lane, doc.lane_slot, list(sv.items())
                )
            )
        known = doc.lowerer.known
        for client, clock in sv.items():
            if clock > known.get(client, 0):
                return False
        return True

    # -- encoding -----------------------------------------------------------

    def _group_items(
        self,
        doc: PlaneDoc,
        records: list[LogRec],
        min_clock: Optional[dict[int, int]] = None,
    ) -> dict[int, list[Item]]:
        """Group serve-log records into per-client clock-sorted Items.

        min_clock trims fully-known items per client: an op is included
        when any part of it is at/above the client's cutoff (the first
        included item may overlap the cutoff — _write_structs emits it
        with an offset), and clients absent from min_clock are skipped.
        """
        by: dict[int, list[Item]] = {}
        unit_logs = self.plane.unit_logs
        for rec in records:
            op = rec.op
            if op.kind != KIND_INSERT:
                continue
            if min_clock is not None:
                cutoff = min_clock.get(op.client)
                if cutoff is None or op.clock + op.run_len <= cutoff:
                    continue
            by.setdefault(op.client, []).append(_make_item(rec, unit_logs))
        for items in by.values():
            items.sort(key=lambda item: item.id.clock)
        return by

    def _slot_deleted_ranges(self, slot: int) -> "list[tuple[int, int, int]]":
        """Sorted, merged (client, clock, length) ranges of the slot's
        device tombstones.

        Cached per (slot binding generation, flush epoch): tombstone
        rows only change when a flush integrates ops or the slot is
        cleared, so a catch-up storm hitting the same doc repeatedly —
        or many docs across waves — pays the device fetch once per
        epoch, not once per serve (~a full RTT per transfer on a
        remote-attached chip). The miss path fuses the row reads
        (deleted mask, ids — and lengths on the RLE arena) into ONE
        transfer.
        """
        plane = self.plane
        key = (int(plane.slot_gen[slot]), plane.flush_epoch)
        cached = self._tombstone_cache.get(slot)
        if cached is not None and cached[0] == key:
            return cached[1]
        self._fetch_slot_rows([slot], plane.flush_epoch)
        return self._tombstone_cache[slot][1]

    def prefetch_tombstones(self, docs: "list[PlaneDoc]") -> None:
        """Fill the tombstone cache for every slot of `docs` in ONE
        fused device transfer.

        A reconnect storm serves tens of docs in one drain; fetching
        each slot's rows individually costs ~a full RTT per slot on a
        remote-attached chip. One gathered (3, B, N) read costs one.
        """
        plane = self.plane
        epoch = plane.flush_epoch
        slots = sorted(
            {
                slot
                for doc in docs
                for slot in doc.seqs.values()
                if (
                    (cached := self._tombstone_cache.get(slot)) is None
                    or cached[0] != (int(plane.slot_gen[slot]), epoch)
                )
            }
        )
        if not slots:
            return
        # fixed gather widths: exactly two compiled programs (small
        # drains don't transfer a big batch; big storms chunk), instead
        # of one XLA compile (seconds, remote) per distinct slot count
        for pos_chunk in self._gather_chunks(slots):
            self._fetch_slot_rows(pos_chunk, epoch)

    def _gather_widths(self) -> "list[int]":
        """Fixed width ladder, capped at the plane size (pow2): a small
        drain transfers a small batch, a storm fuses into few big ones,
        and the compile count stays at len(ladder)."""
        cap = 1
        while cap < min(self.plane.num_docs, 256):
            cap *= 2
        widths = [w for w in (16, 64) if w < cap]
        widths.append(cap)
        return widths

    def _gather_chunks(self, slots: "list[int]") -> "list[list[int]]":
        biggest = self._gather_widths()[-1]
        chunks = []
        pos = 0
        while pos < len(slots):
            chunks.append(slots[pos : pos + biggest])
            pos += biggest
        return chunks

    def _gather_rows(self, slot_indices: "list[int]") -> np.ndarray:
        """One fused device read of the tombstone-relevant rows for the
        given slots. Caller holds the step lock. Unit arena: (3, B, N)
        [deleted, id_client, id_clock]. RLE arena: (4, B, R) [deleted,
        run_client, run_clock, run_len] — ranges come straight from
        deleted entries, no per-unit pair scan."""
        import jax.numpy as jnp

        state = self.plane.state
        idx = jnp.asarray(slot_indices, jnp.int32)
        if self.plane.arena == "rle":
            return np.asarray(
                jnp.stack(
                    [
                        state.run_deleted[idx].astype(jnp.int32),
                        state.run_client[idx].view(jnp.int32),
                        state.run_clock[idx],
                        state.run_len[idx],
                    ]
                )
            )
        return np.asarray(
            jnp.stack(
                [
                    state.deleted[idx].astype(jnp.int32),
                    state.id_client[idx].view(jnp.int32),
                    state.id_clock[idx],
                ]
            )
        )

    @staticmethod
    def _merge_ranges(
        raw: "list[tuple[int, int, int]]",
    ) -> "list[tuple[int, int, int]]":
        """Merge sorted id-adjacent (client, clock, length) ranges once
        at fetch time so every serve consumes ready ranges."""
        ranges: list[tuple[int, int, int]] = []
        for c, k, l in raw:
            if ranges and ranges[-1][0] == c and ranges[-1][1] + ranges[-1][2] == k:
                ranges[-1] = (c, ranges[-1][1], ranges[-1][2] + l)
            else:
                ranges.append((c, k, l))
        return ranges

    def _pack_width(self) -> int:
        """Tombstone-pack lane width: narrow enough that the packed
        readback (B + 2·B·W or B + 3·B·W uint32) stays far below the
        full-row read, wide enough for the overwhelming majority of
        rows. One static value = one compiled pack program per gather
        width."""
        state = self.plane.state
        dim = (
            state.run_client.shape[1]
            if self.plane.arena == "rle"
            else state.id_client.shape[1]
        )
        return min(128, int(dim))

    def _fetch_slot_rows(self, chunk: "list[int]", epoch: int) -> None:
        """Fill the tombstone cache for a slot chunk: the on-device
        packed read first, a full-row host gather for any slot whose
        tombstone count overflowed the pack width."""
        if self.device_pack_enabled:
            overflow = self._fetch_slot_rows_device(chunk, epoch)
            if overflow:
                self._fetch_slot_rows_host(overflow, epoch)
            return
        self._fetch_slot_rows_host(chunk, epoch)
        self.plane.counters["sync_encode_host"] += len(chunk)

    def _fetch_slot_rows_device(self, chunk: "list[int]", epoch: int) -> "list[int]":
        """Packed tombstone fetch: the device gathers the chunk's rows,
        masks live tombstones and prefix-sum-compacts them into a
        (B + planes·B·W) uint32 readback — O(tombstones) on the wire
        instead of O(arena width). Returns the slots whose tombstone
        count exceeded the pack width (the host full-row path re-reads
        exactly those). Tombstones arrive in arena order; the host
        sorts and merges identically to the full-row path, so the
        DeleteSet bytes emitted downstream are byte-identical."""
        import jax.numpy as jnp

        plane = self.plane
        width = next(w for w in self._gather_widths() if w >= len(chunk))
        pack_w = self._pack_width()
        padded = chunk + [chunk[0]] * (width - len(chunk))
        rle = plane.arena == "rle"
        with plane._step_lock:  # never gather donated buffers mid-flush
            t0 = time.perf_counter()
            slots_dev = jnp.asarray(padded, jnp.int32)
            shape_key = (width, pack_w)
            with plane.compile_watch.track("catchup_pack", shape_key):
                if rle:
                    from .kernels_rle import catchup_pack_rle

                    fused = np.asarray(
                        catchup_pack_rle(plane.state, slots_dev, pack_w)
                    )
                else:
                    from .kernels import catchup_pack

                    fused = np.asarray(catchup_pack(plane.state, slots_dev, pack_w))
            plane._note_dispatch("sync")
            gens = [int(plane.slot_gen[slot]) for slot in chunk]
            plane.device_stats["readback_stall_ms_total"] += (
                time.perf_counter() - t0
            ) * 1000.0
            plane.device_stats["readback_stalls"] += 1
        planes = 3 if rle else 2
        counts = fused[:width]
        body = fused[width:].reshape(planes, width, pack_w)
        overflow: list[int] = []
        for i, slot in enumerate(chunk):
            count = int(counts[i])
            if count > pack_w:
                overflow.append(slot)
                continue
            clients = body[0, i, :count]
            clocks = body[1, i, :count].astype(np.int64)
            if rle:
                lens = body[2, i, :count].astype(np.int64)
                raw = sorted(zip(clients.tolist(), clocks.tolist(), lens.tolist()))
            else:
                raw = [
                    (c, k, 1)
                    for c, k in sorted(zip(clients.tolist(), clocks.tolist()))
                ]
            self._tombstone_cache[slot] = (
                (gens[i], epoch),
                self._merge_ranges(raw),
            )
        plane.counters["sync_encode_device"] += len(chunk) - len(overflow)
        return overflow

    def _fetch_slot_rows_host(self, chunk: "list[int]", epoch: int) -> None:
        plane = self.plane
        width = next(w for w in self._gather_widths() if w >= len(chunk))
        with plane._step_lock:  # never gather donated buffers mid-flush
            t0 = time.perf_counter()
            fused = self._gather_rows(chunk + [chunk[0]] * (width - len(chunk)))
            gens = [int(plane.slot_gen[slot]) for slot in chunk]
            # tombstone gathers are serve-path device readbacks: count
            # them into the stall meter so /metrics shows how much host
            # time sync serving spends blocked on the device
            plane.device_stats["readback_stall_ms_total"] += (
                time.perf_counter() - t0
            ) * 1000.0
            plane.device_stats["readback_stalls"] += 1
        rle = plane.arena == "rle"
        for i, slot in enumerate(chunk):
            sel = np.nonzero(fused[0, i])[0]
            clients = fused[1, i][sel].view(np.uint32)
            clocks = fused[2, i][sel]
            if rle:
                lens = fused[3, i][sel]
                raw = sorted(
                    (c, k, l)
                    for c, k, l in zip(
                        clients.tolist(), clocks.tolist(), lens.tolist()
                    )
                    if l > 0
                )
            else:
                raw = [(c, k, 1) for c, k in sorted(zip(clients.tolist(), clocks.tolist()))]
            self._tombstone_cache[slot] = (
                (gens[i], epoch),
                self._merge_ranges(raw),
            )
        plane.counters["sync_encode_host"] += len(chunk)

    def warmup_gathers(self, width: Optional[int] = None) -> None:
        """Compile the tombstone-gather AND catch-up pack programs (one
        per fixed width) so the first reconnect storm pays data
        transfer, not XLA compile time. Run from the extension's
        listen-time warm task — which passes one `width` per call so
        interactive work (sync serves, lane-demote rebuilds) interleaves
        between compiles instead of waiting out the whole ladder."""
        import jax.numpy as jnp

        plane = self.plane
        pack_w = self._pack_width()
        widths = self._gather_widths() if width is None else [width]
        with plane._step_lock:
            for w in widths:
                self._gather_rows([0] * w)
                shape_key = (w, pack_w)
                with plane.compile_watch.track(
                    "catchup_pack", shape_key, warmup=True
                ):
                    slots_dev = jnp.asarray([0] * w, jnp.int32)
                    if plane.arena == "rle":
                        from .kernels_rle import catchup_pack_rle

                        np.asarray(catchup_pack_rle(plane.state, slots_dev, pack_w))
                    else:
                        from .kernels import catchup_pack

                        np.asarray(catchup_pack(plane.state, slots_dev, pack_w))
                plane.compile_watch.mark_covered("catchup_pack", shape_key)

    def _device_delete_set(self, doc: PlaneDoc) -> DeleteSet:
        """Tombstones as the DEVICE sees them, across every row of the
        doc, plus host-applied map-item tombstones."""
        lengths = self._lengths()
        ds = DeleteSet()
        for slot in doc.seqs.values():
            if int(lengths[slot]) == 0:
                continue
            for client, clock, length in self._slot_deleted_ranges(slot):
                ds.add(client, clock, length)
        for client, clock, length in doc.map_tombstones:
            ds.add(client, clock, length)
        ds.sort_and_merge()
        return ds

    def _encode_window_native(
        self,
        doc: PlaneDoc,
        records: list[LogRec],
        min_clock: Optional[dict[int, int]],
    ) -> Optional[bytes]:
        """Struct-section bytes via the native `encode_text_window`, or
        None = use the Python path.

        The semantic work of `_group_items` + `crdt/update._write_structs`
        — cutoff trimming (the record filter below), group ordering,
        the first-item offset with its origin rewrite and payload slice
        — happens HERE; the C++ side is pure byte emission. Only the
        shapes the plane serves hot qualify (string runs, deleted runs,
        GC ranges, root parents); any rich content (formats, embeds,
        maps, ID parents) returns None and the caller re-encodes via
        Items.
        """
        from ..native import get_codec

        codec = get_codec()
        if codec is None or not hasattr(codec, "encode_text_window"):
            return None
        unit_logs = self.plane.unit_logs
        by: dict[int, list[LogRec]] = {}
        for rec in records:
            op = rec.op
            if op.kind != KIND_INSERT:
                continue
            if min_clock is not None:
                cutoff = min_clock.get(op.client)
                if cutoff is None or op.clock + op.run_len <= cutoff:
                    continue
            if op.content is not None or op.parent_sub is not None:
                return None
            if op.parent is not None and op.parent[0] != "root":
                return None
            by.setdefault(op.client, []).append(rec)
        groups = []
        for client in sorted(by, reverse=True):
            recs = sorted(by[client], key=lambda r: r.op.clock)
            cutoff = 0 if min_clock is None else min_clock[client]
            # the filter above kept only records overlapping the cutoff,
            # so recs[0] is the group's first emitted struct
            write_clock = max(cutoff, recs[0].op.clock)
            items = []
            for j, rec in enumerate(recs):
                op = rec.op
                offset = max(write_clock - op.clock, 0) if j == 0 else 0
                if op.gc:
                    items.append((1, -1, 0, -1, 0, None, op.run_len - offset))
                    continue
                oc = -1 if op.left_client == NONE_CLIENT else op.left_client
                ok = op.left_clock
                rc = -1 if op.right_client == NONE_CLIENT else op.right_client
                rk = op.right_clock
                if offset > 0:
                    # emitting a tail of the run: its origin is the unit
                    # just before the cut (Item.write offset semantics)
                    oc, ok = client, write_clock - 1
                parent_name = None
                if oc < 0 and rc < 0:
                    if op.parent is None:
                        return None
                    parent_name = op.parent[1]
                if op.deleted_content:
                    items.append(
                        (2, oc, ok, rc, rk, parent_name, op.run_len - offset)
                    )
                    continue
                log = unit_logs[rec.slot]
                payload = units_to_text(
                    log[rec.unit_off + offset : rec.unit_off + op.run_len]
                )
                items.append((0, oc, ok, rc, rk, parent_name, payload))
            groups.append((client, write_clock, items))
        return codec.encode_text_window(groups)

    def _widen_surrogate_cutoffs(
        self, records: list[LogRec], sm: dict[int, int]
    ) -> None:
        """A stale-sync cutoff landing mid-surrogate-pair would slice a
        text run so its first transmitted unit is a lone low surrogate —
        units_to_text (errors='replace') bakes U+FFFD into the wire
        bytes while the CPU document still holds the real pair. Widen
        such cutoffs by one unit: the re-sent high surrogate is already
        known to the client and struct integration skips the known
        prefix (offset semantics), so the serve stays byte-faithful
        without leaving the device path.

        The pair's two units may live in DIFFERENT serve-log records
        (a remote update re-encoded as two structs split mid-pair), so
        the unit AT the cutoff and the unit BEFORE it are resolved
        independently across all of the client's records. A high
        surrogate can never be the second half of a pair, so one step
        suffices (no cascade)."""
        unit_logs = self.plane.unit_logs
        at_unit: dict[int, int] = {}
        prev_unit: dict[int, int] = {}
        for rec in records:
            op = rec.op
            if op.kind != KIND_INSERT or op.gc or op.deleted_content:
                continue
            if op.content is not None or op.parent_sub is not None or rec.slot is None:
                continue
            cutoff = sm.get(op.client)
            if cutoff is None or cutoff <= 0:
                continue
            log = unit_logs.get(rec.slot)
            if log is None:
                continue
            if op.clock <= cutoff < op.clock + op.run_len:
                pos = rec.unit_off + (cutoff - op.clock)
                if pos < len(log) and isinstance(log[pos], int):
                    at_unit[op.client] = log[pos]
            if op.clock <= cutoff - 1 < op.clock + op.run_len:
                pos = rec.unit_off + (cutoff - 1 - op.clock)
                if pos < len(log) and isinstance(log[pos], int):
                    prev_unit[op.client] = log[pos]
        for client, unit in at_unit.items():
            prev = prev_unit.get(client)
            if (
                0xDC00 <= unit <= 0xDFFF
                and prev is not None
                and 0xD800 <= prev <= 0xDBFF
            ):
                sm[client] = sm[client] - 1

    def _encode_path(self) -> str:
        """/metrics path label for sync-cache events: which delete-set
        read route serves on a miss."""
        return "device" if self.device_pack_enabled else "host"

    def _cache_lookup(self, doc: PlaneDoc, epoch_key, sv_key) -> Optional[bytes]:
        payload = self._sync_cache.get(doc.name, doc, epoch_key, sv_key)
        counters = self.plane.counters
        wire = get_wire_telemetry()
        if payload is not None:
            counters["sync_cache_hits"] += 1
            if wire.enabled:
                wire.record_sync_cache("hit", path=self._encode_path())
        else:
            counters["sync_cache_misses"] += 1
            if wire.enabled:
                wire.record_sync_cache("miss", path=self._encode_path())
        return payload

    def _cache_store(self, doc: PlaneDoc, epoch_key, sv_key, payload: bytes) -> None:
        before = self._sync_cache.evictions
        self._sync_cache.put(doc.name, doc, epoch_key, sv_key, payload)
        evicted = self._sync_cache.evictions - before
        if evicted:
            self.plane.counters["sync_cache_evictions"] += evicted
            wire = get_wire_telemetry()
            if wire.enabled:
                wire.record_sync_cache(
                    "eviction", evicted, path=self._encode_path()
                )

    def _encode_from_sm(self, doc: PlaneDoc, sm: dict[int, int]) -> bytes:
        """SyncStep2 bytes for a doc given the per-client cutoff map.

        Both paths consult the join-storm sync cache first: the payload
        is a pure function of (serve log, cutoff map) within one flush
        epoch, so N joiners sharing a state vector pay ONE encode."""
        plane = self.plane
        if doc.lane_slot is not None and plane._lane is not None:
            # native path: cutoff trimming, offset origin-rewrite and
            # surrogate widening all happen in C — no materialization,
            # so a reconnect storm never exports the log
            epoch_key = (
                plane._lane_codec.lane_log_len(plane._lane, doc.lane_slot),
                plane.flush_epoch,
            )
            sv_key = tuple(sorted(sm.items()))
            cached = self._cache_lookup(doc, epoch_key, sv_key)
            if cached is not None:
                plane.counters["sync_serves"] += 1
                return cached
            encoder = Encoder()
            encoder.write_bytes(
                plane._lane_codec.lane_window_sm(
                    plane._lane, doc.lane_slot, list(sm.items())
                )
            )
            self._device_delete_set(doc).write(encoder)
            plane.counters["sync_serves"] += 1
            payload = encoder.to_bytes()
            self._cache_store(doc, epoch_key, sv_key, payload)
            return payload
        self.plane.materialize_lane(doc)
        if any(clock > 0 for clock in sm.values()):
            # zero cutoffs can't slice a run, so cold serves skip the
            # widening walk entirely
            self._widen_surrogate_cutoffs(doc.serve_log, sm)
        epoch_key = (
            len(doc.serve_log),
            len(doc.map_tombstones),
            plane.flush_epoch,
        )
        sv_key = tuple(sorted(sm.items()))
        cached = self._cache_lookup(doc, epoch_key, sv_key)
        if cached is not None:
            plane.counters["sync_serves"] += 1
            return cached
        encoder = Encoder()
        body = self._encode_window_native(doc, doc.serve_log, sm)
        if body is not None:
            encoder.write_bytes(body)
        else:
            items_by_client = self._group_items(doc, doc.serve_log, sm)
            encoder.write_var_uint(len(items_by_client))
            for client in sorted(items_by_client, reverse=True):
                _write_structs(encoder, items_by_client[client], client, sm[client])
        self._device_delete_set(doc).write(encoder)
        self.plane.counters["sync_serves"] += 1
        payload = encoder.to_bytes()
        self._cache_store(doc, epoch_key, sv_key, payload)
        return payload

    def encode_state_as_update(
        self, name: str, document, sv_bytes: Optional[bytes] = None
    ) -> Optional[bytes]:
        """SyncStep2 payload from device state; None = CPU fallback.

        Synchronous path (tests, benches, the non-batched sync adapter):
        holds the plane's step lock across its own flush AND the state
        reads, so an extension-scheduled executor flush can neither
        donate the buffers mid-read nor interleave between the drain
        and the encode. The server core uses the async batched path.
        """
        if self.paused:
            return None  # supervisor drain: serve from the CPU document
        tracer = get_tracer()
        if tracer.enabled:
            with tracer.span("serving.sync_serve", document=name):
                return self._encode_state_as_update_inner(name, document, sv_bytes)
        return self._encode_state_as_update_inner(name, document, sv_bytes)

    def _encode_state_as_update_inner(
        self, name: str, document, sv_bytes: Optional[bytes] = None
    ) -> Optional[bytes]:
        plane = self.plane
        with plane._step_lock:  # reentrant: flush() re-acquires
            if plane.pending_ops() > 0:
                plane.flush()
                self.refresh()
            doc = self.doc_healthy(name)
            if doc is None or not self.covers(name, document):
                return None
            # plane-integrated clocks ARE the local state vector (queue
            # was just flushed), so the diff is computed before building
            # Items — a nearly-current reconnect pays for its tail, not
            # the full doc
            local_sv = self._local_sv(doc)
            target_sv = decode_state_vector(sv_bytes) if sv_bytes else {}
            sm: dict[int, int] = {}
            for client, clock in target_sv.items():
                if local_sv.get(client, 0) > clock:
                    sm[client] = clock
            for client in local_sv:
                if client not in target_sv:
                    sm[client] = 0
            return self._encode_from_sm(doc, sm)

    # -- batched catch-up (the storm path) -----------------------------------

    async def batched_sync(self, name: str, document, sv_bytes: Optional[bytes]):
        """Enqueue a SyncStep1 for device-triaged batch serving.

        Every request that lands in the same event-loop window shares
        ONE `state_vector_diff` kernel call (tpu/kernels.py) — the
        O(docs x clients) triage of a reconnect storm runs on the
        device, and only the per-request item encode stays host-side.
        Resolves to SyncStep2 bytes, or None = CPU fallback.
        """
        import asyncio

        if self.paused:
            return None  # supervisor drain: serve from the CPU document
        future = asyncio.get_event_loop().create_future()
        self._inflight.add(future)
        future.add_done_callback(self._inflight.discard)
        self._catchup_queue.append((name, document, sv_bytes, future))
        if not self._catchup_scheduled:
            self._catchup_scheduled = True
            # strong ref: a GC'd drain task would strand every waiter
            task = asyncio.ensure_future(self._drain_catchup())
            self._drain_tasks.add(task)
            task.add_done_callback(self._drain_tasks.discard)
        return await future

    def abort_pending(self) -> None:
        """Resolve every outstanding batched-sync waiter to CPU fallback.

        The supervisor's breaker-open drain: a wedge mid-flight leaves
        drain tasks blocked on the flush lock with their waiters'
        futures unresolved — clients would stall on SyncStep2 forever.
        The drain tasks' own `future.done() or set_result(...)` guards
        make the eventual (post-unwedge) resolution a no-op.
        """
        for future in list(self._inflight):
            if not future.done():
                future.set_result(None)

    async def _drain_catchup(self) -> None:
        self._catchup_scheduled = False
        batch, self._catchup_queue = self._catchup_queue, []
        if not batch:
            return
        plane = self.plane
        # device-lane admission (tpu/scheduler.py): the drain flushes
        # and runs the triage kernel — interactive class, a joiner is
        # blocked on the reply. A parked lane (breaker open) resolves
        # the batch to CPU fallback, exactly like abort_pending.
        ticket = None
        if plane.lane is not None:
            from .scheduler import CLASS_INTERACTIVE, LaneDeferred

            try:
                ticket = await plane.lane.admit(
                    CLASS_INTERACTIVE, site="sync"
                )
            except LaneDeferred:
                for *_rest, future in batch:
                    future.done() or future.set_result(None)
                return
        try:
            # the whole drain — flush, refresh, triage, item encode —
            # holds the flush lock: every step reads device state, and a
            # concurrent executor-side flush donates the buffers it reads
            async with plane.flush_lock:
                tracer = get_tracer()
                if tracer.enabled:
                    with tracer.span("serving.catchup_drain", batch=len(batch)):
                        await self._drain_catchup_locked(batch)
                else:
                    await self._drain_catchup_locked(batch)
        finally:
            if ticket is not None:
                ticket.release()

    async def _drain_catchup_locked(self, batch: list) -> None:
        import asyncio

        import jax.numpy as jnp

        from .kernels import state_vector_diff

        plane = self.plane
        try:
            if plane.pending_ops() > 0:
                try:
                    # device step off the loop (see _flush_now)
                    await asyncio.get_event_loop().run_in_executor(
                        None, plane.flush
                    )
                except Exception:
                    # the dead flush already consumed queued ops — the
                    # same fault TpuMergeExtension._flush handles by
                    # degrading every served doc with a full-state CPU
                    # broadcast; route through the same safety model
                    # instead of silently dropping captured updates
                    for *_rest, future in batch:
                        future.done() or future.set_result(None)
                    if self.flush_failure_handler is not None:
                        self.flush_failure_handler()
                    return
                self.refresh()
            # triage rows: healthy, covering docs only (the rest resolve
            # to None and fall back to the CPU path)
            rows: list[tuple] = []  # (local_sv, target_sv, columns, future)
            width = 1
            for name, document, sv_bytes, future in batch:
                doc = self.doc_healthy(name)
                if doc is None or not self.covers(name, document):
                    future.done() or future.set_result(None)
                    continue
                local_sv = self._local_sv(doc)
                try:
                    target_sv = decode_state_vector(sv_bytes) if sv_bytes else {}
                except Exception:
                    future.done() or future.set_result(None)
                    continue
                columns = sorted(set(local_sv) | set(target_sv))
                width = max(width, len(columns))
                rows.append((doc, local_sv, target_sv, columns, future))
            if not rows:
                return
            # one gathered device read covers every doc in the batch —
            # the storm's delete-set reads must not pay per-slot RTTs,
            # and the transfer runs OFF the loop like every device step
            batch_docs = [row[0] for row in rows]
            await asyncio.get_event_loop().run_in_executor(
                None, lambda: self.prefetch_tombstones(batch_docs)
            )
            if len(rows) == 1:
                # lone reconnect (the steady-state case): the host dict
                # diff costs microseconds — save the kernel dispatch and
                # the device round-trip for actual storms
                doc, local_sv, target_sv, _, future = rows[0]
                sm = {}
                for cid, clock in target_sv.items():
                    if local_sv.get(cid, 0) > clock:
                        sm[cid] = clock
                for cid in local_sv:
                    if cid not in target_sv:
                        sm[cid] = 0
                if not future.done():
                    try:
                        future.set_result(self._encode_from_sm(doc, sm))
                    except Exception:
                        future.set_result(None)
                return
            # pad to a power-of-two (B, C) so storm-size jitter doesn't
            # recompile the kernel per request count
            b = 1
            while b < len(rows):
                b *= 2
            c = 1
            while c < width:
                c *= 2
            server = np.zeros((b, c), np.int64)
            client = np.zeros((b, c), np.int64)
            for i, (doc, local_sv, target_sv, columns, _) in enumerate(rows):
                for j, cid in enumerate(columns):
                    server[i, j] = local_sv.get(cid, 0)
                    client[i, j] = target_sv.get(cid, 0)
            missing_from, missing_len = state_vector_diff(
                jnp.asarray(server, jnp.int32), jnp.asarray(client, jnp.int32)
            )
            plane._note_dispatch("sync")
            missing_from = np.asarray(missing_from)
            missing_len = np.asarray(missing_len)
            for i, (doc, local_sv, target_sv, columns, future) in enumerate(rows):
                if future.done():
                    continue
                try:
                    sm = {
                        cid: int(missing_from[i, j])
                        for j, cid in enumerate(columns)
                        if missing_len[i, j] > 0
                    }
                    future.set_result(self._encode_from_sm(doc, sm))
                except Exception:
                    future.set_result(None)  # degrade this request to CPU
        except Exception:
            for *_rest, future in batch:
                future.done() or future.set_result(None)

    def build_broadcast(self, name: str) -> Optional[bytes]:
        """Merged update for ops integrated since the last broadcast.

        Items come from the doc's serve log (everything consumed by the
        device or host-integrated since the cursor, minus presync
        records — receivers get pre-load state via sync). The delete
        set carries exactly the WINDOW's delete ranges: the kernel
        applies id-range tombstones unconditionally over ids the
        lowerer proved integrated, and a host/device divergence is
        caught by the health check (retire + full-state CPU fallback)
        before the next broadcast — so shipping the full device
        tombstone state every time (O(doc-lifetime deletes) per
        broadcast) is not needed for safety. Cold joiners still get the
        complete device-proved set via the sync path. The cursor only
        advances on a successfully encoded payload (or a genuinely
        empty window), so a bail-out never strands ops.
        """
        pair = self.build_broadcast_pair(name)
        return None if pair is None else pair[0]

    def _encode_window(self, doc: PlaneDoc, window: list[LogRec]) -> Optional[bytes]:
        """Update bytes for a record window, or None for an empty one."""
        window_ds = DeleteSet()
        has_inserts = False
        for rec in window:
            if rec.op.kind == KIND_DELETE:
                window_ds.add(rec.op.client, rec.op.clock, rec.op.run_len)
            elif rec.op.kind == KIND_INSERT:
                has_inserts = True
        if not has_inserts and not window_ds.clients:
            return None
        encoder = Encoder()
        body = self._encode_window_native(doc, window, None)
        if body is not None:
            encoder.write_bytes(body)
        else:
            by = self._group_items(doc, window)
            encoder.write_var_uint(len(by))
            for client in sorted(by, reverse=True):
                items = by[client]
                _write_structs(encoder, items, client, items[0].id.clock)
        window_ds.sort_and_merge()
        window_ds.write(encoder)
        return encoder.to_bytes()

    def build_broadcast_pairs(
        self, names: "list[str]"
    ) -> "tuple[list[tuple[str, Optional[tuple[bytes, Optional[bytes]]]]], list[str]]":
        """Batched window drain -> (pairs, failed_names).

        Lane docs resolve in ONE native call (the per-doc Python
        overhead dominates at 10k-doc widths; a missing slot yields a
        None entry, not an exception), Python-path docs fall back to
        build_broadcast_pair each — WITH per-doc isolation: one doc's
        encode failure lands it in failed_names instead of aborting
        the other 10k docs' windows."""
        plane = self.plane
        out: list = []
        failed: list[str] = []
        lane_names: list = []
        lane_args: list = []
        for name in names:
            doc = plane.docs.get(name)
            if doc is not None and doc.lane_slot is not None and plane._lane is not None:
                lane_names.append(name)
                lane_args.append(
                    (doc.lane_slot, self.broadcast_cursor.get(name, 0))
                )
            else:
                try:
                    out.append((name, self.build_broadcast_pair(name)))
                except Exception:
                    failed.append(name)
        if lane_args:
            results = plane._lane_codec.lane_windows_batch(plane._lane, lane_args)
            for name, (full, cross, new_idx) in zip(lane_names, results):
                self.broadcast_cursor[name] = new_idx
                if full is None:
                    out.append((name, None))
                else:
                    plane.counters["plane_broadcasts"] += 1
                    out.append((name, (full, cross)))
        return out, failed

    def build_broadcast_pair(
        self, name: str
    ) -> "Optional[tuple[bytes, Optional[bytes]]]":
        """(full_window_update, cross_instance_update or None).

        The full frame goes to local connections. The cross-instance
        frame excludes REMOTE-origin records (ops that arrived from a
        peer instance) — every peer already has them from the original
        publisher, and republishing would amplify traffic O(N^2) in
        instance count. It is None when the window holds no local ops.
        When the window is all-local the same bytes serve both.
        """
        plane = self.plane
        doc = plane.docs.get(name)
        if doc is None:
            return None
        if doc.lane_slot is not None:
            # native path: one C call builds both frames' update bytes
            full, cross, new_idx, _ = plane._lane_codec.lane_window(
                plane._lane, doc.lane_slot, self.broadcast_cursor.get(name, 0)
            )
            self.broadcast_cursor[name] = new_idx
            if full is None:
                return None
            plane.counters["plane_broadcasts"] += 1
            return full, cross
        log = doc.serve_log
        cursor = min(self.broadcast_cursor.get(name, 0), len(log))
        window = [rec for rec in log[cursor:] if not rec.op.presync]
        if not window:
            self.broadcast_cursor[name] = len(log)
            return None
        full = self._encode_window(doc, window)
        if full is None:
            self.broadcast_cursor[name] = len(log)
            return None
        local_window = [rec for rec in window if not rec.remote]
        if len(local_window) == len(window):
            local = full
        elif not local_window:
            local = None
        else:
            local = self._encode_window(doc, local_window)
        self.broadcast_cursor[name] = len(log)
        plane.counters["plane_broadcasts"] += 1
        return full, local


class TpuSyncSource:
    """`document.sync_source` adapter: SyncStep2 bytes from the plane.

    Any serving error degrades to the CPU path (return None) rather
    than failing the client's sync.
    """

    def __init__(self, serving: PlaneServing, name: str, document) -> None:
        self.serving = serving
        self.name = name
        self.document = document

    def encode_state_as_update(self, sv_bytes: Optional[bytes]) -> Optional[bytes]:
        try:
            return self.serving.encode_state_as_update(self.name, self.document, sv_bytes)
        except Exception:
            from ..server import logger as _logger_mod

            _logger_mod.log_error(
                f"plane sync serve failed for {self.name!r}; using CPU path"
            )
            return None

    async def encode_state_as_update_async(self, sv_bytes: Optional[bytes]) -> Optional[bytes]:
        """Batched (storm) variant: concurrent SyncStep1s share one
        device state-vector-diff triage — see PlaneServing.batched_sync."""
        try:
            return await self.serving.batched_sync(self.name, self.document, sv_bytes)
        except Exception:
            from ..server import logger as _logger_mod

            _logger_mod.log_error(
                f"plane batched sync failed for {self.name!r}; using CPU path"
            )
            return None
