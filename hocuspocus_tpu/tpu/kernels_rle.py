"""Run-length batched text-CRDT integration (prototype, JAX).

The unit-granular arena (`kernels.py`) spends one slot per UTF-16 unit
forever — tombstoned text keeps its slots, so a long-lived busy doc
exhausts cumulative capacity no matter its live size (the documented
limit in docs/tpu/merge-plane.md). This module is the run-length
answer: one arena entry per RUN of consecutively-typed units. Typing
bursts cost one entry; deletes tombstone whole entries; entry growth is
O(ops + splits), not O(units), so tombstone cost is O(fragmentation).

Same architecture as the unit kernel — APPEND-ONLY entries + dense
UNIT-rank ordering, elementwise compares/selects + masked reductions,
no gathers — with two structural insights:

- Within a run, unit i's left origin is unit i-1 (that is what makes
  it a run), so only run HEADS can block a YATA conflict scan; the one
  exception is the unit at rank left_rank+1 inside a run, which ties
  on client id. The scan stays a couple of masked reductions.
- Unit ranks are DENSE (0..total_units), so "how many window units are
  skipped" needs no counting reduction: the insertion rank is simply
  `min(first_block_rank, right_rank)`.

Inserting or deleting into the middle of a run SPLITS it; both cases
reduce to two primitives (`_split_at_rank`, `_split_at_clock`) that
append the run's tail as a fresh entry (≤2 appends per op, bounded).

Status: production. Wired into the plane via `MergePlane(arena="rle")`
(capacity = ENTRIES; serving resolves payloads through the host
serve-log index), with the Pallas/VMEM-resident variant in
`pallas_kernels_rle.py` and mesh sharding in `sharding.py`.
Equivalence suites: tests/tpu/test_kernels_rle.py (vs the unit
kernel), test_pallas_kernels_rle.py (Pallas vs scan),
test_plane_fuzz.py + test_rle_plane.py (vs the CPU engine through the
live serve path; churn survival).

Reference semantics mirrored: yjs Item.integrate via
`/root/reference/packages/server/src/MessageReceiver.ts` readUpdate.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .kernels import KIND_DELETE, KIND_INSERT, KIND_NOOP, NONE_CLIENT, OpBatch

_INF = 0x7FFFFFFF


class RleState(NamedTuple):
    """Run-length arena for a batch of documents. Leading axis = doc."""

    run_client: jax.Array  # (D, R) uint32 — author of the run
    run_clock: jax.Array  # (D, R) int32 — clock of the first unit
    run_len: jax.Array  # (D, R) int32 — units in this entry
    run_rank: jax.Array  # (D, R) int32 — UNIT rank of the first unit
    run_orank: jax.Array  # (D, R) int32 — origin UNIT rank of the first unit
    run_deleted: jax.Array  # (D, R) bool
    num_runs: jax.Array  # (D,) int32 — occupied entries
    total_units: jax.Array  # (D,) int32 — rank-space size (live + tombstones)
    overflow: jax.Array  # (D,) bool

    @property
    def length(self) -> jax.Array:
        """Alias: cumulative INSERTED units — the same accounting the
        unit arena's `length` reports, so the plane's health readback
        (_sync_health: validated dispatch tallies vs device length) is
        arena-agnostic. Not a pytree field (properties are not)."""
        return self.total_units


def make_empty_rle_state(num_docs: int, entries: int) -> RleState:
    shape = (num_docs, entries)
    return RleState(
        run_client=jnp.full(shape, NONE_CLIENT, jnp.uint32),
        run_clock=jnp.zeros(shape, jnp.int32),
        run_len=jnp.zeros(shape, jnp.int32),
        run_rank=jnp.full(shape, _INF, jnp.int32),
        run_orank=jnp.full(shape, -1, jnp.int32),
        run_deleted=jnp.zeros(shape, bool),
        num_runs=jnp.zeros((num_docs,), jnp.int32),
        total_units=jnp.zeros((num_docs,), jnp.int32),
        overflow=jnp.zeros((num_docs,), bool),
    )


def _append_entry(state: RleState, lane, do, client, clock, length, rank, orank, deleted):
    """Write one entry at `lane` when `do` (single doc, elementwise)."""
    r = state.run_client.shape[0]
    idx = jnp.arange(r, dtype=jnp.int32)
    at = do & (idx == lane)
    return state._replace(
        run_client=jnp.where(at, client, state.run_client),
        run_clock=jnp.where(at, clock, state.run_clock),
        run_len=jnp.where(at, length, state.run_len),
        run_rank=jnp.where(at, rank, state.run_rank),
        run_orank=jnp.where(at, orank, state.run_orank),
        run_deleted=jnp.where(at, deleted, state.run_deleted),
        num_runs=state.num_runs + do.astype(jnp.int32),
    )


def _split_at_rank(state: RleState, rank, do):
    """Split the entry strictly containing unit-rank `rank` (if any).

    The head keeps its lane (len shortened); the tail appends at
    num_runs with orank = rank-1 (within-run chaining). No entry
    contains `rank` strictly when it is a run boundary — no-op then.
    """
    idx = jnp.arange(state.run_client.shape[0], dtype=jnp.int32)
    occupied = idx < state.num_runs
    inside = (
        do
        & occupied
        & (state.run_rank < rank)
        & (rank < state.run_rank + state.run_len)
    )
    any_split = jnp.any(inside)
    # at most ONE entry strictly contains a given rank, so masked SUMS
    # extract its fields exactly (masked max would misread uint32
    # client ids with the high bit set through an int32 view)
    off = jnp.sum(jnp.where(inside, rank - state.run_rank, 0))
    t_client = jnp.sum(
        jnp.where(inside, state.run_client, jnp.uint32(0)), dtype=jnp.uint32
    )
    t_clock = jnp.sum(jnp.where(inside, state.run_clock + off, 0))
    t_len = jnp.sum(jnp.where(inside, state.run_len - off, 0))
    t_deleted = jnp.any(inside & state.run_deleted)
    shortened = jnp.where(inside, off, state.run_len)
    state = state._replace(run_len=shortened)
    return _append_entry(
        state, state.num_runs, any_split, t_client, t_clock, t_len, rank, rank - 1,
        t_deleted,
    )


def _split_at_clock(state: RleState, client, clock, do):
    """Split the entry of `client` strictly containing `clock` (if any)."""
    idx = jnp.arange(state.run_client.shape[0], dtype=jnp.int32)
    occupied = idx < state.num_runs
    inside = (
        do
        & occupied
        & (state.run_client == client)
        & (state.run_clock < clock)
        & (clock < state.run_clock + state.run_len)
    )
    any_split = jnp.any(inside)
    off = jnp.sum(jnp.where(inside, clock - state.run_clock, 0))
    t_rank = jnp.sum(jnp.where(inside, state.run_rank + off, 0))
    t_len = jnp.sum(jnp.where(inside, state.run_len - off, 0))
    t_deleted = jnp.any(inside & state.run_deleted)
    shortened = jnp.where(inside, off, state.run_len)
    state = state._replace(run_len=shortened)
    return _append_entry(
        state, state.num_runs, any_split, client, clock, t_len, t_rank, t_rank - 1,
        t_deleted,
    )


def _integrate_one_rle(state: RleState, op: OpBatch) -> RleState:
    """Integrate a single op into a single document (unbatched)."""
    r = state.run_client.shape[0]
    idx = jnp.arange(r, dtype=jnp.int32)
    occupied = idx < state.num_runs

    # -- resolve origin ids to UNIT ranks (range membership) ---------------
    in_left = (
        occupied
        & (state.run_client == op.left_client)
        & (op.left_clock >= state.run_clock)
        & (op.left_clock < state.run_clock + state.run_len)
    )
    has_left = op.left_client != jnp.uint32(NONE_CLIENT)
    left_found = jnp.any(in_left)
    left_rank = jnp.where(
        has_left,
        jnp.max(jnp.where(in_left, state.run_rank + (op.left_clock - state.run_clock), -1)),
        -1,
    )
    in_right = (
        occupied
        & (state.run_client == op.right_client)
        & (op.right_clock >= state.run_clock)
        & (op.right_clock < state.run_clock + state.run_len)
    )
    has_right = op.right_client != jnp.uint32(NONE_CLIENT)
    right_found = jnp.any(in_right)
    right_rank = jnp.where(
        has_right,
        jnp.max(
            jnp.where(in_right, state.run_rank + (op.right_clock - state.run_clock), -1)
        ),
        state.total_units,
    )

    # -- YATA conflict scan over run heads ---------------------------------
    # Only two unit shapes can BLOCK (see module docstring): an
    # in-window run head whose origin precedes the window, and the
    # non-head unit at rank left_rank+1 (its origin IS left), both
    # losing the client-id tie against op.client.
    client_ge = ~(state.run_client < op.client)
    head_in_window = occupied & (state.run_rank > left_rank) & (state.run_rank < right_rank)
    head_blocked = head_in_window & (
        (state.run_orank < left_rank)
        | ((state.run_orank == left_rank) & client_ge)
    )
    succ = left_rank + 1  # the unit right after left, when inside a run
    succ_nonhead = (
        occupied
        & (state.run_rank < succ)
        & (succ < state.run_rank + state.run_len)
        & (succ < right_rank)
    )
    succ_blocked = succ_nonhead & client_ge
    first_block = jnp.minimum(
        jnp.min(jnp.where(head_blocked, state.run_rank, _INF)),
        jnp.min(jnp.where(succ_blocked, succ, _INF)),
    )
    # dense rank space: skipped window units need no counting reduction
    ins_rank = jnp.minimum(first_block, right_rank)

    run = op.run_len
    fits = state.num_runs + 2 <= r
    deps_ok = (~has_left | left_found) & (~has_right | right_found)
    do_insert = (op.kind == KIND_INSERT) & fits & deps_ok

    # -- insert: split the straddled run, bump ranks, append ---------------
    state = _split_at_rank(state, ins_rank, do_insert)
    occupied2 = jnp.arange(r, dtype=jnp.int32) < state.num_runs
    bump_rank = do_insert & occupied2 & (state.run_rank >= ins_rank)
    bump_orank = do_insert & occupied2 & (state.run_orank >= ins_rank)
    state = state._replace(
        run_rank=jnp.where(bump_rank, state.run_rank + run, state.run_rank),
        run_orank=jnp.where(bump_orank, state.run_orank + run, state.run_orank),
    )
    state = _append_entry(
        state,
        state.num_runs,
        do_insert,
        op.client,
        op.clock,
        run,
        ins_rank,
        left_rank,
        False,
    )
    state = state._replace(
        total_units=state.total_units + jnp.where(do_insert, run, 0),
        overflow=state.overflow | ((op.kind == KIND_INSERT) & ~fits),
    )

    # -- delete: split at both boundaries, tombstone covered entries -------
    # capture the capacity verdict BEFORE the splits mutate num_runs
    # (like the insert path's `fits`): a delete that fit must not flag
    # sticky overflow just because its own splits consumed the margin
    del_fits = state.num_runs + 2 <= r
    do_delete = (op.kind == KIND_DELETE) & del_fits
    del_end = op.clock + op.run_len
    state = _split_at_clock(state, op.client, op.clock, do_delete)
    state = _split_at_clock(state, op.client, del_end, do_delete)
    occupied3 = jnp.arange(r, dtype=jnp.int32) < state.num_runs
    covered = (
        do_delete
        & occupied3
        & (state.run_client == op.client)
        & (state.run_clock >= op.clock)
        & (state.run_clock + state.run_len <= del_end)
    )
    state = state._replace(
        run_deleted=state.run_deleted | covered,
        overflow=state.overflow | ((op.kind == KIND_DELETE) & ~del_fits),
    )
    return state


_integrate_batch_rle = jax.vmap(_integrate_one_rle)


@partial(jax.jit, donate_argnums=(0,))
def integrate_ops_rle(state: RleState, ops: OpBatch) -> RleState:
    """Integrate one op per document (noop slots pass through)."""
    return _integrate_batch_rle(state, ops)


@partial(jax.jit, donate_argnums=(0,))
def integrate_op_slots_rle(state: RleState, ops: OpBatch):
    """Integrate (K, D)-shaped op slots via lax.scan, like the unit
    kernel's integrate_op_slots."""

    def step(carry, slot_ops):
        return _integrate_batch_rle(carry, slot_ops), None

    state, _ = jax.lax.scan(step, state, ops)
    count = jnp.sum(ops.kind != KIND_NOOP)
    count, _ = jax.lax.optimization_barrier((count, state.total_units))
    return state, count


@partial(jax.jit, donate_argnums=(0,))
def integrate_op_slots_rle_sparse(state: RleState, ops: OpBatch, slots):
    """Sparse busy-doc dispatch over the RLE arena: (K, B) op slots plus
    an int32 (B,) slot-routing vector (see kernels.integrate_op_slots_
    sparse — same gather/integrate/scatter contract, padding columns
    carry noops and the out-of-range sentinel)."""
    from .kernels import gather_doc_rows, scatter_doc_rows

    sub = gather_doc_rows(state, slots)
    sub, count = integrate_op_slots_rle.__wrapped__(sub, ops)
    state = scatter_doc_rows(state, sub, slots)
    count, _ = jax.lax.optimization_barrier((count, state.total_units))
    return state, count


# -- on-device compaction (defragmentation GC) --------------------------------
#
# RLE entry cost grows with fragmentation: every mid-run insert or
# delete splits a run into head+tail (and zero-length heads linger as
# dead lanes), so a churny doc's entry count creeps toward capacity even
# when its logical state is a handful of runs. The compact kernel is the
# id-PRESERVING defragmenter: drop zero-length lanes and merge entries
# that are rank-adjacent, id-consecutive, same-client and same-deleted —
# the exact fragments splitting created. No unit rank changes and no id
# range disappears, so origins keep resolving (range membership) and the
# host needs no serve-log or payload rewrite at all — unlike the unit
# arena's tombstone GC (kernels.compact_doc_rows), this one is pure
# housekeeping.


def _compact_one_rle(state: RleState) -> RleState:
    r = state.run_client.shape[0]
    idx = jnp.arange(r, dtype=jnp.int32)
    occupied = idx < state.num_runs
    keep = occupied & (state.run_len > 0)
    # rank-order the kept entries (dropped lanes sort to the back)
    order = jnp.argsort(jnp.where(keep, state.run_rank, _INF))
    cl = state.run_client[order]
    ck = state.run_clock[order]
    ln = state.run_len[order]
    rk = state.run_rank[order]
    ok = state.run_orank[order]
    dl = state.run_deleted[order]
    kept = keep[order]  # a prefix of size sum(keep)
    # an entry continues the previous one when splitting could have
    # produced the pair: same author, consecutive clocks AND ranks,
    # same tombstone verdict
    prev = lambda a: jnp.concatenate([a[:1], a[:-1]])
    merge = (
        kept
        & jnp.concatenate([jnp.zeros((1,), bool), kept[:-1]])
        & (cl == prev(cl))
        & (ck == prev(ck) + prev(ln))
        & (rk == prev(rk) + prev(ln))
        & (dl == prev(dl))
    )
    head = kept & ~merge
    seg = jnp.cumsum(head.astype(jnp.int32)) - 1  # segment index per entry
    num_segs = jnp.sum(head.astype(jnp.int32))
    seg_dst = jnp.where(kept, seg, r)  # r = drop
    seg_len = jnp.zeros((r,), jnp.int32).at[seg_dst].add(ln, mode="drop")
    head_dst = jnp.where(head, seg, r)  # unique: one head per segment

    def pack(vals, fill, dtype):
        return jnp.full((r,), fill, dtype).at[head_dst].set(vals, mode="drop")

    return RleState(
        run_client=pack(cl, NONE_CLIENT, jnp.uint32),
        run_clock=pack(ck, 0, jnp.int32),
        run_len=seg_len,
        run_rank=pack(rk, _INF, jnp.int32),
        run_orank=pack(ok, -1, jnp.int32),
        run_deleted=jnp.zeros((r,), bool).at[head_dst].set(dl, mode="drop"),
        num_runs=num_segs,
        total_units=state.total_units,  # rank space untouched
        overflow=jnp.zeros((), bool),
    )


_compact_batch_rle = jax.vmap(_compact_one_rle)


@partial(jax.jit, donate_argnums=(0,))
def compact_doc_rows_rle(state: RleState, slots) -> tuple[RleState, jax.Array]:
    """Defragment the B doc rows `slots` routes to (int32 (B,);
    num_docs = padding sentinel). Returns (state, packed entry counts
    (B,)) — data-dependent on the scattered state, the caller's
    completion barrier."""
    from .kernels import gather_doc_rows, scatter_doc_rows

    sub = gather_doc_rows(state, slots)
    sub = _compact_batch_rle(sub)
    state = scatter_doc_rows(state, sub, slots)
    counts, _ = jax.lax.optimization_barrier((sub.num_runs, state.total_units))
    return state, counts


# -- minimal-work run merge (the sequential fast path) ------------------------
#
# RLE twin of kernels.append_run_slots_sparse: the host classifier
# (merge_plane._classify_fast) routes a batch column here only when
# every drained op is a chained tail append (left origin = tracked
# rank-tail, right origin = NONE), for which the YATA window is empty
# and integration needs no conflict scan, no splits and no rank bumps.
# Two shapes of device work per coalesced run:
#
# - EXTEND: run 0 continues the arena's rank-tail entry (same client,
#   consecutive clock, entry not tombstoned) — run_len += len, zero new
#   entries. The scan path would append a fresh entry instead; the
#   fast path's layout is exactly the merge the RLE compactor
#   (_compact_one_rle) performs later, so unit expansion — and every
#   serve derived from it — is identical while entry pressure drops.
# - APPEND: one new entry at the next free lane with rank = old total
#   + chain offset and orank = rank - 1, the same fields the scan
#   path's _append_entry writes for an end-of-doc insert.
#
# Overflow semantics: a run that needs a lane when none is free flags
# overflow and kills the chain (later runs' origins would be missing).
# This admits strictly MORE work near capacity than the scan path's
# conservative `num_runs + 2 <= R` split margin (extensions need no
# lane at all) — a doc the fast path still fits would have overflowed
# under the slow path, never the reverse, so the retire/degrade story
# is unchanged and the equivalence fuzz compares unit expansions away
# from the capacity edge.


def _append_entries_one_rle(state: RleState, client, clock, run_len) -> tuple:
    """Apply up to K chained tail-append runs to one document row."""
    r = state.run_client.shape[0]
    idx = jnp.arange(r, dtype=jnp.int32)
    total = state.total_units
    entries = state.num_runs
    is_run = run_len > 0

    # the rank-tail entry: occupied entry spans are disjoint and cover
    # [0, total), so exactly one nonempty entry ends at `total` (none
    # when the doc is empty) — masked sums extract its fields
    occupied = (idx < entries) & (state.run_len > 0)
    tail = occupied & (state.run_rank + state.run_len == total) & (total > 0)
    tail_client = jnp.sum(jnp.where(tail, state.run_client, jnp.uint32(0)), dtype=jnp.uint32)
    tail_end_clock = jnp.sum(jnp.where(tail, state.run_clock + state.run_len, 0))
    tail_deleted = jnp.any(tail & state.run_deleted)
    ext0 = (
        is_run[0]
        & (total > 0)
        & jnp.any(tail)
        & (tail_client == client[0])
        & (clock[0] == tail_end_clock)
        & ~tail_deleted
    )

    def fit_step(carry, m):
        applied_units, new_entries, alive, over = carry
        extend = (m == 0) & ext0
        fits = extend | (entries + new_entries + 1 <= r)
        live = alive & fits & is_run[m]
        start = applied_units
        lane = entries + new_entries
        applied_units = applied_units + jnp.where(live, run_len[m], 0)
        new_entries = new_entries + jnp.where(live & ~extend, 1, 0)
        over = over | (is_run[m] & ~fits)
        alive = alive & (fits | ~is_run[m])
        return (applied_units, new_entries, alive, over), (
            start,
            lane,
            live & ~extend,
        )

    (applied_units, _new_entries, _alive, overflow), (starts, lanes, appends) = (
        jax.lax.scan(
            fit_step,
            (jnp.int32(0), jnp.int32(0), jnp.bool_(True), state.overflow),
            jnp.arange(client.shape[0]),
        )
    )

    # extension first (its own lane, disjoint from every appended lane)
    extend_applied = ext0  # an extension always fits
    run_len_out = jnp.where(
        tail & extend_applied, state.run_len + run_len[0], state.run_len
    )

    def write_step(carry, m):
        e_client, e_clock, e_len, e_rank, e_orank, e_deleted = carry
        at = appends[m] & (idx == lanes[m])
        e_client = jnp.where(at, client[m], e_client)
        e_clock = jnp.where(at, clock[m], e_clock)
        e_len = jnp.where(at, run_len[m], e_len)
        e_rank = jnp.where(at, total + starts[m], e_rank)
        e_orank = jnp.where(at, total + starts[m] - 1, e_orank)
        e_deleted = jnp.where(at, False, e_deleted)
        return (e_client, e_clock, e_len, e_rank, e_orank, e_deleted), None

    (e_client, e_clock, e_len, e_rank, e_orank, e_deleted), _ = jax.lax.scan(
        write_step,
        (
            state.run_client,
            state.run_clock,
            run_len_out,
            state.run_rank,
            state.run_orank,
            state.run_deleted,
        ),
        jnp.arange(client.shape[0]),
    )
    new_state = RleState(
        run_client=e_client,
        run_clock=e_clock,
        run_len=e_len,
        run_rank=e_rank,
        run_orank=e_orank,
        run_deleted=e_deleted,
        num_runs=entries + jnp.sum(appends.astype(jnp.int32)),
        total_units=total + applied_units,
        overflow=overflow,
    )
    applied_runs = jnp.sum(appends.astype(jnp.int32)) + extend_applied.astype(jnp.int32)
    return new_state, applied_runs


_append_entries_batch_rle = jax.vmap(_append_entries_one_rle, in_axes=(0, 1, 1, 1))


@partial(jax.jit, donate_argnums=(0,))
def append_run_slots_rle_sparse(
    state: RleState, client, clock, run_len, slots
) -> tuple[RleState, jax.Array]:
    """Fast-path integrate for B all-sequential busy docs (RLE arena).

    Same batch layout and padding contract as the unit arena's
    kernels.append_run_slots_sparse: (K, B) coalesced runs + int32
    (B,) slot routing (sentinel = num_docs)."""
    from .kernels import gather_doc_rows, scatter_doc_rows

    sub = gather_doc_rows(state, slots)
    sub, counts = _append_entries_batch_rle(sub, client, clock, run_len)
    state = scatter_doc_rows(state, sub, slots)
    count, _ = jax.lax.optimization_barrier((jnp.sum(counts), state.total_units))
    return state, count


# -- on-device catch-up support (SyncStep2 serving) ---------------------------


def _tail_probe_one_rle(state: RleState) -> tuple:
    """(client, clock) id of the rank-tail UNIT of one document row —
    the RLE twin of kernels._tail_probe_one (same host contract: an
    empty doc reads as (0, 0), keyed on total_units == 0)."""
    r = state.run_client.shape[0]
    idx = jnp.arange(r, dtype=jnp.int32)
    occupied = (idx < state.num_runs) & (state.run_len > 0)
    tail = occupied & (state.run_rank + state.run_len == state.total_units) & (
        state.total_units > 0
    )
    client = jnp.sum(jnp.where(tail, state.run_client, jnp.uint32(0)), dtype=jnp.uint32)
    clock = jnp.sum(jnp.where(tail, state.run_clock + state.run_len - 1, 0))
    return client, clock.astype(jnp.uint32)


@jax.jit
def tail_probe_rle(state: RleState, slots) -> jax.Array:
    """(2B,) uint32 [clients..., clocks...] rank-tail ids for the B
    requested rows (same contract as kernels.tail_probe)."""
    from .kernels import gather_doc_rows

    sub = gather_doc_rows(state, slots)
    clients, clocks = jax.vmap(_tail_probe_one_rle)(sub)
    return jnp.concatenate([clients, clocks])


@partial(jax.jit, static_argnames=("width",))
def catchup_pack_rle(state: RleState, slots, width: int) -> jax.Array:
    """Device-side delete-set pack for B requested rows (RLE arena):
    ONE (B + 3*B*width,) uint32 readback laid out [counts (B,),
    clients flat, clocks flat, lens flat] of the tombstoned entries in
    lane order — the host sorts/merges exactly as the full-row path
    did, so emitted DeleteSet bytes are identical. Rows with more than
    `width` tombstoned entries report the true count and fall back."""
    from .kernels import gather_doc_rows

    def one(row: RleState):
        r = row.run_client.shape[0]
        idx = jnp.arange(r, dtype=jnp.int32)
        dead = (idx < row.num_runs) & row.run_deleted & (row.run_len > 0)
        pos = jnp.cumsum(dead.astype(jnp.int32)) - 1
        dst = jnp.where(dead, pos, width)  # width = drop sentinel
        clients = (
            jnp.zeros((width,), jnp.uint32).at[dst].set(row.run_client, mode="drop")
        )
        clocks = jnp.zeros((width,), jnp.int32).at[dst].set(row.run_clock, mode="drop")
        lens = jnp.zeros((width,), jnp.int32).at[dst].set(row.run_len, mode="drop")
        return (
            jnp.sum(dead.astype(jnp.int32)),
            clients,
            clocks.astype(jnp.uint32),
            lens.astype(jnp.uint32),
        )

    sub = gather_doc_rows(state, slots)
    counts, clients, clocks, lens = jax.vmap(one)(sub)
    return jnp.concatenate(
        [
            counts.astype(jnp.uint32),
            clients.reshape(-1),
            clocks.reshape(-1),
            lens.reshape(-1),
        ]
    )


# -- host-side extraction ----------------------------------------------------


def expand_to_units(state: RleState, doc: int):
    """Document order as parallel unit arrays (client, clock, deleted),
    sorted by rank — the comparison form used by the equivalence tests
    and any host consumer."""
    import numpy as np

    n = int(np.asarray(state.num_runs)[doc])
    client = np.asarray(state.run_client)[doc][:n]
    clock = np.asarray(state.run_clock)[doc][:n]
    length = np.asarray(state.run_len)[doc][:n]
    rank = np.asarray(state.run_rank)[doc][:n]
    deleted = np.asarray(state.run_deleted)[doc][:n]
    keep = length > 0  # split heads shortened to zero never re-emit
    client, clock, length, rank, deleted = (
        client[keep], clock[keep], length[keep], rank[keep], deleted[keep],
    )
    order = np.argsort(rank)
    out_client = np.concatenate(
        [np.full(length[i], client[i], np.uint32) for i in order]
    ) if len(order) else np.zeros(0, np.uint32)
    out_clock = np.concatenate(
        [clock[i] + np.arange(length[i], dtype=np.int32) for i in order]
    ) if len(order) else np.zeros(0, np.int32)
    out_deleted = np.concatenate(
        [np.full(length[i], deleted[i], bool) for i in order]
    ) if len(order) else np.zeros(0, bool)
    return out_client, out_clock, out_deleted


def delete_ranges(state: RleState, doc: int):
    """Tombstones as sorted (client, clock, length) ranges — direct from
    deleted entries (the unit arena needs a per-unit pair scan here)."""
    import numpy as np

    n = int(np.asarray(state.num_runs)[doc])
    client = np.asarray(state.run_client)[doc][:n]
    clock = np.asarray(state.run_clock)[doc][:n]
    length = np.asarray(state.run_len)[doc][:n]
    deleted = np.asarray(state.run_deleted)[doc][:n]
    sel = deleted & (length > 0)
    ranges = sorted(zip(client[sel].tolist(), clock[sel].tolist(), length[sel].tolist()))
    merged: list[tuple] = []
    for c, k, l in ranges:
        if merged and merged[-1][0] == c and merged[-1][1] + merged[-1][2] == k:
            merged[-1] = (c, merged[-1][1], merged[-1][2] + l)
        else:
            merged.append((c, k, l))
    return merged
