"""Adaptive merge scheduling: the device-lane arbiter + batching governor.

The sharded plane (tpu/sharded_extension.py) runs N independent flush
pipelines with fixed timers that contend blindly for ONE device: an
interactive 2-doc flush can sit behind a 100k-row compaction sweep, a
hydration batch, or another shard's full microbatch. Serving-systems
practice (continuous batching under an SLO) and the CRDT-perf
literature (Eg-walker's minimal-work-per-merge, arXiv:2409.14252) both
say the same thing: batch size and dispatch order must follow measured
arrival rate and latency budget, not wall-clock timers. This module is
that scheduling layer, in three parts:

1. **`DeviceLane`** — a process-global admission arbiter every device
   client passes through before dispatching: shard flushes
   (interactive), hydration batches (catch-up), compaction/GC sweeps
   (background), canary probes and warm-grid compiles (lowest). One
   holder at a time (one chip); waiters are granted strictly by
   priority class, FIFO within a class. Background holders are expected
   to check `ticket.should_yield()` between microbatches and release —
   preemption at batch granularity, since a launched kernel is not
   interruptible. A starvation guard promotes waiters that have aged
   past `promote_after_s` so background work always progresses. The
   supervisor parks the lane on breaker-open (`pause()` — queued
   waiters defer, new admissions defer, only pause-exempt canary
   probes pass) and resumes it at re-attach.

2. **`BatchGovernor`** — per-shard arrival-aware batching: an EWMA of
   op-arrival rate plus the measured per-cycle device time pick the
   flush cadence and per-cycle batch count dynamically. Past the
   queue-depth watermark the tick collapses to an immediate full
   drain; when arrivals are sparse the tick stretches (up to
   `max_stretch`x — cheap, because broadcasts build from the HOST
   serve logs and never wait on the device flush); when the lane is
   congested batch growth is capped at one kernel call per admission
   so higher-priority work preempts between batches. Idle shards park
   their timers entirely (the flush timer is enqueue-driven and stops
   rescheduling at empty queues; the governor counts the parks).

3. **Cross-shard compile sharing** — the jitted step functions are
   module-level (pallas_kernels*.py), so XLA's compile cache is
   already process-wide for unsharded planes: N shards warming the
   same (k, b) grid pay N identical no-op dispatch sweeps for one
   real compile set. `shared_warm_filter` is the module-level registry
   of already-warmed (backend, arena, num_docs, capacity, (k, b))
   keys: the first shard's warm pass compiles, every other shard skips
   the covered shapes (seeding its CompileTracker so live flushes at
   those shapes classify as cache hits, which they are) — and the warm
   grid runs through the lane at the lowest priority, so it can never
   head-of-line-block an interactive flush at boot.

Invariants and tuning live in docs/guides/tpu-scheduling.md.
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Optional

from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import Counter, Gauge, Histogram

# -- priority classes --------------------------------------------------------
# Lower value = higher priority. Interactive flushes preempt everything;
# catch-up (hydration) outranks compaction/GC; canary probes and warm
# compiles ride last — a probe's job is to measure the device the real
# traffic sees, not to displace it.

CLASS_INTERACTIVE = 0
CLASS_CATCHUP = 1
CLASS_BACKGROUND = 2
CLASS_CANARY = 3

CLASS_NAMES = ("interactive", "catchup", "background", "canary")

# lane-wait buckets: sub-millisecond grants are the common case, parked
# background work can wait whole seconds behind an interactive burst
_WAIT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


class LaneDeferred(Exception):
    """Admission declined: the lane is parked (supervisor pause) or the
    waiter's queue-wait deadline passed. Carries the class + wait so the
    caller can record a `flush_deferred` flight event and reschedule."""

    def __init__(self, lane_class: int, waited_s: float, reason: str) -> None:
        super().__init__(f"{CLASS_NAMES[lane_class]} deferred ({reason})")
        self.lane_class = lane_class
        self.waited_s = waited_s
        self.reason = reason


class LaneTicket:
    """One granted (or queued) admission. Always release() in finally."""

    __slots__ = (
        "lane", "lane_class", "effective_class", "site", "ignore_pause",
        "enqueued_at", "granted_at", "seq", "future", "promoted", "weight",
    )

    def __init__(self, lane: "DeviceLane", lane_class: int, site: str,
                 ignore_pause: bool, seq: int, weight: int = 0) -> None:
        self.lane = lane
        self.lane_class = lane_class
        self.effective_class = lane_class
        self.site = site
        self.ignore_pause = ignore_pause
        self.enqueued_at = time.monotonic()
        self.granted_at: Optional[float] = None
        self.seq = seq
        self.future: Optional[asyncio.Future] = None
        self.promoted = False
        # tie-break within a class (lower first): canary probes pass
        # queued warm-grid shapes so the watchdog's latency signal stays
        # timely even mid-warmup
        self.weight = weight

    def should_yield(self) -> bool:
        """True when strictly-higher-priority work is waiting: a holder
        running multiple microbatches checks this between batches and
        releases (preemption at batch granularity)."""
        return self.lane.has_waiter(below_class=self.lane_class)

    def release(self, preempted: bool = False) -> None:
        self.lane._release(self, preempted=preempted)

    # context-manager sugar for synchronous client blocks
    def __enter__(self) -> "LaneTicket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class DeviceLane:
    """Priority-class admission arbiter for one device (capacity 1).

    Process-global by default (`get_device_lane()`): every shard of a
    sharded deployment — and every other device client in the process —
    contends for the same chip, so they must share one arbiter.
    Construct instances directly for tests/benches that need isolation.
    """

    def __init__(self, promote_after_s: float = 0.25) -> None:
        # a waiter older than this is promoted to the interactive class
        # (front of the queue): the starvation guard that keeps parked-
        # looking background work flowing under a sustained burst
        self.promote_after_s = float(promote_after_s)
        self.paused = False
        self._holder: Optional[LaneTicket] = None
        self._waiters: list[LaneTicket] = []
        self._seq = 0
        self._created_at = time.monotonic()
        self._busy_s = 0.0
        # accounting (snapshot() + the metric objects below)
        self.counters: dict[str, int] = {
            "admissions": 0,
            "preemptions": 0,
            "starved_promotions": 0,
            "deferrals": 0,
            "dispatches_in_lane": 0,
            "dispatches_bypass": 0,
        }
        self.class_admissions = [0] * len(CLASS_NAMES)
        self.class_wait_s = [0.0] * len(CLASS_NAMES)
        self.class_wait_max_s = [0.0] * len(CLASS_NAMES)
        # exposition objects (adopted by the Metrics registry via
        # metrics(), like the wire-telemetry collector)
        self.wait_seconds = Histogram(
            "hocuspocus_tpu_lane_wait_seconds",
            "Device-lane queue wait before admission, by priority class",
            buckets=_WAIT_BUCKETS,
        )
        self.admissions_total = Counter(
            "hocuspocus_tpu_lane_admissions_total",
            "Device-lane admissions granted, by priority class",
        )
        self.preemptions_total = Counter(
            "hocuspocus_tpu_lane_preemptions_total",
            "Holders that released between microbatches because "
            "higher-priority work was waiting",
        )
        self.starved_total = Counter(
            "hocuspocus_tpu_lane_starved_promotions_total",
            "Aged waiters promoted past the starvation guard",
        )
        self.deferrals_total = Counter(
            "hocuspocus_tpu_lane_deferrals_total",
            "Admissions deferred (lane parked or deadline passed), by class",
        )
        self.queue_depth = Gauge(
            "hocuspocus_tpu_lane_queue_depth",
            "Waiters queued for the device lane, by priority class",
        )
        self.occupancy = Gauge(
            "hocuspocus_tpu_lane_occupancy",
            "Fraction of wall time the device lane was held since start",
            fn=self._occupancy_fraction,
        )
        # overload control plane (server/overload.py): queued lane
        # waiters feed the ladder's lane_depth signal (weakly held —
        # test lanes fall out on their own). Lazy import: the scheduler
        # must stay importable without the server stack resident.
        try:
            from ..server.overload import get_overload_controller

            get_overload_controller().register_lane(self)
        except Exception:
            pass

    # -- admission -----------------------------------------------------------

    def metrics(self) -> tuple:
        return (
            self.wait_seconds, self.admissions_total, self.preemptions_total,
            self.starved_total, self.deferrals_total, self.queue_depth,
            self.occupancy,
        )

    def contended(self) -> bool:
        return bool(self._waiters)

    def holder_info(self) -> "Optional[tuple[str, int, float]]":
        """(site, class, held_seconds) of the active holder, None when
        idle — lets the supervisor's watchdog tell a lane busy with
        ACCOUNTED warm work apart from one camped on by a wedged flush,
        and bound how long a single warm hold earns that benefit."""
        holder = self._holder
        if holder is None:
            return None
        held = (
            0.0
            if holder.granted_at is None
            else time.monotonic() - holder.granted_at
        )
        return (holder.site, holder.lane_class, held)

    def has_waiter(self, below_class: int) -> bool:
        return any(w.effective_class < below_class for w in self._waiters)

    def queue_depths(self) -> "list[int]":
        depths = [0] * len(CLASS_NAMES)
        for waiter in self._waiters:
            depths[waiter.lane_class] += 1
        return depths

    async def admit(
        self,
        lane_class: int,
        site: str = "",
        ignore_pause: bool = False,
        deadline_s: Optional[float] = None,
        weight: int = 0,
    ) -> LaneTicket:
        """Wait for the device lane; returns the held ticket.

        Raises `LaneDeferred` immediately when the lane is parked (and
        the class is not pause-exempt), or after `deadline_s` of queue
        wait — the caller records the deferral and reschedules rather
        than pile blocked tasks onto a paused/wedged device.
        """
        if self.paused and not ignore_pause:
            self._defer(lane_class, 0.0)
            raise LaneDeferred(lane_class, 0.0, "parked")
        self._seq += 1
        ticket = LaneTicket(
            self, lane_class, site, ignore_pause, self._seq, weight=weight
        )
        if self._holder is None and not self._waiters:
            self._grant(ticket)
            return ticket
        ticket.future = asyncio.get_event_loop().create_future()
        self._waiters.append(ticket)
        self._refresh_depth_gauge()
        # the holder may have released between our check and the append
        # (same-task reentrancy cannot happen, but release() from a
        # completed executor callback can): re-run the grant scan
        self._grant_next()
        try:
            if deadline_s is None:
                await ticket.future
            else:
                await asyncio.wait_for(asyncio.shield(ticket.future), deadline_s)
        except asyncio.TimeoutError:
            waited = time.monotonic() - ticket.enqueued_at
            if ticket.granted_at is not None:
                # granted in the same tick the deadline fired: keep it
                return ticket
            self._discard(ticket)
            self._defer(lane_class, waited)
            raise LaneDeferred(lane_class, waited, "deadline") from None
        except LaneDeferred:
            raise
        except asyncio.CancelledError:
            if ticket.granted_at is not None:
                # granted and cancelled in the same tick: hand the lane on
                self._release(ticket)
            else:
                self._discard(ticket)
            raise
        return ticket

    def _grant(self, ticket: LaneTicket) -> None:
        now = time.monotonic()
        waited = now - ticket.enqueued_at
        ticket.granted_at = now
        self._holder = ticket
        self.counters["admissions"] += 1
        self.class_admissions[ticket.lane_class] += 1
        self.class_wait_s[ticket.lane_class] += waited
        if waited > self.class_wait_max_s[ticket.lane_class]:
            self.class_wait_max_s[ticket.lane_class] = waited
        cls = CLASS_NAMES[ticket.lane_class]
        self.wait_seconds.observe(waited, **{"class": cls})
        self.admissions_total.inc(**{"class": cls})

    def _release(self, ticket: LaneTicket, preempted: bool = False) -> None:
        if self._holder is not ticket:
            return  # already released (idempotent: finally-blocks double up)
        now = time.monotonic()
        if ticket.granted_at is not None:
            self._busy_s += now - ticket.granted_at
        self._holder = None
        if preempted:
            self.counters["preemptions"] += 1
            self.preemptions_total.inc()
            get_flight_recorder().record(
                "__plane__",
                "lane_preempted",
                lane_class=CLASS_NAMES[ticket.lane_class],
                held_ms=round((now - (ticket.granted_at or now)) * 1000.0, 3),
            )
        self._grant_next()

    def _grant_next(self) -> None:
        if self._holder is not None or not self._waiters:
            return
        now = time.monotonic()
        # starvation guard: promote aged waiters before picking
        for waiter in self._waiters:
            if (
                not waiter.promoted
                and waiter.effective_class > CLASS_INTERACTIVE
                and now - waiter.enqueued_at > self.promote_after_s
            ):
                waiter.promoted = True
                waiter.effective_class = CLASS_INTERACTIVE
                self.counters["starved_promotions"] += 1
                self.starved_total.inc()
                get_flight_recorder().record(
                    "__plane__",
                    "lane_starved_promoted",
                    lane_class=CLASS_NAMES[waiter.lane_class],
                    wait_ms=round((now - waiter.enqueued_at) * 1000.0, 3),
                )
        eligible = [
            w for w in self._waiters if not self.paused or w.ignore_pause
        ]
        if not eligible:
            return
        best = min(eligible, key=lambda w: (w.effective_class, w.weight, w.seq))
        self._waiters.remove(best)
        self._refresh_depth_gauge()
        self._grant(best)
        if best.future is not None and not best.future.done():
            best.future.set_result(None)

    def _discard(self, ticket: LaneTicket) -> None:
        try:
            self._waiters.remove(ticket)
        except ValueError:
            pass
        self._refresh_depth_gauge()

    def _defer(self, lane_class: int, waited_s: float) -> None:
        self.counters["deferrals"] += 1
        self.deferrals_total.inc(**{"class": CLASS_NAMES[lane_class]})

    def _refresh_depth_gauge(self) -> None:
        depths = self.queue_depths()
        for i, name in enumerate(CLASS_NAMES):
            self.queue_depth.set(depths[i], **{"class": name})

    # -- park / drain (supervisor seam) --------------------------------------

    def pause(self) -> None:
        """Park the lane (breaker open / pause serving): queued waiters
        that are not pause-exempt defer immediately — their tasks
        reschedule instead of stacking onto a wedged device — and new
        admissions defer at the door. The active holder is untouched
        (its kernel is already launched; it releases on its own)."""
        if self.paused:
            return
        self.paused = True
        for waiter in list(self._waiters):
            if waiter.ignore_pause:
                continue
            self._waiters.remove(waiter)
            waited = time.monotonic() - waiter.enqueued_at
            self._defer(waiter.lane_class, waited)
            if waiter.future is not None and not waiter.future.done():
                waiter.future.set_exception(
                    LaneDeferred(waiter.lane_class, waited, "parked")
                )
        self._refresh_depth_gauge()

    def resume(self) -> None:
        if not self.paused:
            return
        self.paused = False
        self._grant_next()

    # -- dispatch accounting -------------------------------------------------

    def note_dispatch(self, site: str, batches: int = 1) -> None:
        """Called by the plane at every device dispatch site (flush
        cycle, warm compile, canary, compact). A dispatch while no
        ticket is held bypassed the arbiter — counted, and pinned to
        zero by the scheduler-accounting test for every scheduled
        pipeline path."""
        if self._holder is not None:
            self.counters["dispatches_in_lane"] += batches
        else:
            self.counters["dispatches_bypass"] += batches

    def _occupancy_fraction(self) -> float:
        wall = time.monotonic() - self._created_at
        busy = self._busy_s
        if self._holder is not None and self._holder.granted_at is not None:
            busy += time.monotonic() - self._holder.granted_at
        return round(busy / wall, 6) if wall > 0 else 0.0

    def snapshot(self) -> dict:
        """JSON-able state for /debug/scheduler."""
        depths = self.queue_depths()
        per_class = {}
        for i, name in enumerate(CLASS_NAMES):
            admits = self.class_admissions[i]
            per_class[name] = {
                "queued": depths[i],
                "admissions": admits,
                "wait_ms_mean": (
                    round(self.class_wait_s[i] / admits * 1000.0, 3)
                    if admits
                    else 0.0
                ),
                "wait_ms_max": round(self.class_wait_max_s[i] * 1000.0, 3),
            }
        return {
            "paused": self.paused,
            "held": self._holder is not None,
            "holder_class": (
                None
                if self._holder is None
                else CLASS_NAMES[self._holder.lane_class]
            ),
            "occupancy": self._occupancy_fraction(),
            "promote_after_ms": round(self.promote_after_s * 1000.0, 3),
            "classes": per_class,
            "counters": dict(self.counters),
        }


_default_lanes: "dict[int, DeviceLane]" = {}


def get_device_lane(device_index: int = 0) -> DeviceLane:
    """The process-global arbiter for one chip.

    One `DeviceLane` per DEVICE, not per process: a single-chip
    deployment calls this with no argument (index 0, the historical
    behavior), while the multi-device cell plane (tpu/cells.py) passes
    each cell's device index — eight chips are eight independent
    dispatch queues, and serializing them through one arbiter would
    throw away exactly the parallelism the cells exist to buy. Clients
    of the SAME chip (shards, residency, canaries) must still share
    that chip's lane."""
    lane = _default_lanes.get(device_index)
    if lane is None:
        lane = _default_lanes[device_index] = DeviceLane()
    return lane


def reset_device_lane() -> None:
    """Drop the global lanes (tests): the next get builds fresh ones."""
    _default_lanes.clear()


# -- arrival-aware batching governor -----------------------------------------


class BatchGovernor:
    """Per-shard flush cadence + batch-count policy from measured load.

    Replaces the fixed `flush_interval_ms` timer with three regimes,
    decided at schedule time from the op-arrival EWMA, the queue depth
    and the lane's congestion signal:

    - **drain**: queue depth at/past `drain_watermark` — flush NOW
      (zero delay) and let the cycle run unbounded batches (unless the
      lane is congested, where one batch per admission keeps the shard
      preemptible).
    - **steady**: arrivals fast enough that a base tick collects at
      least ~one op — keep the configured base cadence.
    - **sparse**: arrivals slower than one per tick — stretch the tick
      (up to `max_stretch`x base) so dispatches amortize; free for the
      edit->observe path because broadcasts build from host serve logs
      and never wait on the device flush (docs/guides/tpu-merge-
      pipeline.md).

    The governor never changes WHAT is flushed — only when and in how
    many kernel calls — so governor-on/off doc state is byte-identical
    (pinned by the differential fuzz in tests/tpu/test_scheduler.py).
    """

    def __init__(
        self,
        base_interval_ms: float = 5.0,
        max_stretch: float = 4.0,
        drain_watermark: int = 256,
        target_batch_ops: int = 32,
        halflife_s: float = 0.5,
    ) -> None:
        self.base_s = max(base_interval_ms, 0.01) / 1000.0
        self.max_stretch = max(float(max_stretch), 1.0)
        self.drain_watermark = max(int(drain_watermark), 1)
        self.target_batch_ops = max(int(target_batch_ops), 1)
        self.halflife_s = max(float(halflife_s), 0.01)
        self._rate = 0.0  # ops/s EWMA
        self._last_arrival: Optional[float] = None
        self.device_ms_ewma = 0.0  # per-batch device time
        self.counters: dict[str, int] = {
            "drains": 0,
            "stretches": 0,
            "steady_ticks": 0,
            "congested_ticks": 0,
            "congestion_caps": 0,
            "parks": 0,
        }
        self.last_delay_s = self.base_s

    # -- inputs --------------------------------------------------------------

    def note_arrival(self, ops: int, now: Optional[float] = None) -> None:
        if ops <= 0:
            return
        now = time.monotonic() if now is None else now
        if self._last_arrival is None:
            self._rate = float(ops) / self.halflife_s
        else:
            dt = max(now - self._last_arrival, 1e-6)
            inst = float(ops) / dt
            alpha = 1.0 - math.exp(-dt / self.halflife_s)
            self._rate += alpha * (inst - self._rate)
        self._last_arrival = now

    def arrival_rate(self, now: Optional[float] = None) -> float:
        """Decayed ops/s: silence since the last arrival discounts the
        EWMA, so a burst that stopped doesn't keep the tick short."""
        if self._last_arrival is None:
            return 0.0
        now = time.monotonic() if now is None else now
        idle = max(now - self._last_arrival, 0.0)
        return self._rate * math.exp(-idle / self.halflife_s)

    def note_cycle(self, flush_stats: dict) -> None:
        """Fold one completed flush cycle's measured device time into
        the per-batch EWMA (feeds max_batches' burst cap). Empty cycles
        are skipped — flush_stats only updates when batches ran, so
        folding it again would just re-count the last real cycle."""
        batches = int(flush_stats.get("batches", 0))
        if batches <= 0:
            return
        device_ms = (
            float(flush_stats.get("dispatch_ms", 0.0))
            + float(flush_stats.get("device_sync_ms", 0.0))
        ) / batches
        self.device_ms_ewma += 0.25 * (device_ms - self.device_ms_ewma)

    def note_park(self) -> None:
        """The shard went idle (empty queues, timer not rescheduled)."""
        self.counters["parks"] += 1

    # -- policy --------------------------------------------------------------

    def flush_delay_s(self, pending_ops: int, congested: bool = False) -> float:
        if congested:
            # congestion outranks the watermark: queued lane clients
            # (hydration rounds, compaction) are about to drain their
            # own backlog — an eager interactive tick would only do
            # their work at interactive priority and deepen the queue
            # it then waits in
            self.counters["congested_ticks"] += 1
            self.last_delay_s = self.base_s
            return self.base_s
        if pending_ops >= self.drain_watermark:
            self.counters["drains"] += 1
            self.last_delay_s = 0.0
            return 0.0
        rate = self.arrival_rate()
        expected = rate * self.base_s  # ops a base tick would collect
        if expected >= 1.0:
            self.counters["steady_ticks"] += 1
            self.last_delay_s = self.base_s
            return self.base_s
        if expected <= 0.0:
            # first op after idle: full stretch — nothing else is
            # coming, and the broadcast path doesn't wait on this tick
            delay = self.base_s * self.max_stretch
        else:
            # stretch toward one-op-per-tick, capped at max_stretch
            delay = min(self.base_s / expected, self.base_s * self.max_stretch)
        if delay > self.base_s:
            self.counters["stretches"] += 1
        else:
            self.counters["steady_ticks"] += 1
        self.last_delay_s = delay
        return delay

    def max_batches(
        self, pending_ops: int, congested: bool = False
    ) -> Optional[int]:
        """Kernel calls the cycle may run under one lane admission.

        Always BOUNDED: past the watermark the cycle takes a burst of
        batches and reschedules at zero delay — an unbounded inline
        drain would run the whole background backlog at interactive
        priority inside one lane hold (the exact head-of-line blocking
        the arbiter exists to prevent)."""
        if congested:
            # one batch per admission: the lane re-arbitrates between
            # microbatches, so waiting interactive work preempts here
            self.counters["congestion_caps"] += 1
            return 1
        if pending_ops >= self.drain_watermark:
            return self._burst_cap(8)
        if pending_ops > self.target_batch_ops * 4:
            return self._burst_cap(4)
        return 1

    def _burst_cap(self, ceiling: int) -> int:
        """Burst size bounded by MEASURED device time: the batches of
        one admission should fit roughly one base interval of device
        work, so a slow backend stays preemptible between admissions
        while a fast one drains in fewer lane round-trips."""
        if self.device_ms_ewma <= 0.0:
            return ceiling
        budget_ms = self.base_s * 1000.0
        return max(1, min(ceiling, int(budget_ms / self.device_ms_ewma)))

    def snapshot(self) -> dict:
        return {
            "base_interval_ms": round(self.base_s * 1000.0, 3),
            "max_stretch": self.max_stretch,
            "drain_watermark": self.drain_watermark,
            "arrival_rate_ops_s": round(self.arrival_rate(), 3),
            "device_ms_ewma": round(self.device_ms_ewma, 3),
            "last_delay_ms": round(self.last_delay_s * 1000.0, 3),
            "counters": dict(self.counters),
        }


# -- cross-shard compile sharing ---------------------------------------------
# The plane's jitted steps are module-level functions, so XLA's compile
# cache is process-wide for unsharded planes: identical (arena geometry,
# batch shape) keys compile exactly once per process. This registry
# records which keys a warm pass has already covered so shard 2..N skip
# the redundant no-op dispatch sweeps at boot (mesh-backed planes build
# per-plane jitted closures and never share).

_warmed_keys: "set[tuple]" = set()


def _backend_name() -> str:
    try:
        import jax

        return str(jax.default_backend())
    except Exception:
        return "unknown"


def warm_key(
    arena: str, num_docs: int, capacity: int, shape, device: str = ""
) -> tuple:
    """`device` is the pinned-device discriminator (tpu/cells.py): XLA
    caches executables per device placement, so a shape warmed on chip
    0 is NOT a cache hit for an identically-shaped plane pinned to chip
    3 — per-device cells must each run their own warm pass."""
    return (_backend_name(), device, arena, num_docs, capacity, tuple(shape))


def shared_warm_filter(
    arena: str,
    num_docs: int,
    capacity: int,
    shapes: "list[tuple]",
    device: str = "",
) -> "tuple[list[tuple], list[tuple]]":
    """Split `shapes` into (to_compile, covered) against the registry.
    The caller compiles the first list and marks its CompileTracker
    covered for the second (the process jit cache already holds them)."""
    to_compile: "list[tuple]" = []
    covered: "list[tuple]" = []
    for shape in shapes:
        key = warm_key(arena, num_docs, capacity, shape, device)
        if key in _warmed_keys:
            covered.append(shape)
        else:
            to_compile.append(shape)
    return to_compile, covered


def note_warmed(
    arena: str, num_docs: int, capacity: int, shape, device: str = ""
) -> None:
    _warmed_keys.add(warm_key(arena, num_docs, capacity, shape, device))


def reset_warm_registry() -> None:
    """Tests: make every plane warm from scratch again."""
    _warmed_keys.clear()
