"""Pallas TPU kernel for the batched CRDT integrate step.

The XLA-scan path (kernels.integrate_op_slots) re-reads and re-writes
every (D, N) state array from HBM once per op slot — K slots means K
full passes over ~20 bytes/unit of arena state. This kernel instead
grids over doc blocks and keeps each block's arena resident in VMEM
while a fori_loop applies all K op slots, so HBM sees exactly one read
and one write of the state per flush regardless of K. The YATA math per
op is identical to kernels._integrate_one (reference semantics:
`/root/reference/packages/server/src/MessageReceiver.ts` readUpdate →
yjs Item.integrate), restated over (DB, N) blocks.

Client ids are uint32 at the API boundary; inside the kernel they are
int32 bit patterns (equality is bit-equality; the single ordered
compare — the YATA client-id tiebreak — uses the sign-bias trick).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kernels import KIND_DELETE, KIND_INSERT, DocState, OpBatch

_INF = 0x7FFFFFFF  # plain ints: jnp scalars would be captured consts
_SIGN = -0x80000000
_NONE = -1  # NONE_CLIENT (0xFFFFFFFF) as an int32 bit pattern


def _integrate_block_kernel(
    # ops (DB, K) int32 — doc-major so the K axis is the (full) lane
    # dim, satisfying Mosaic's block-shape rule for any K
    kind_ref,
    client_ref,
    clock_ref,
    run_len_ref,
    left_client_ref,
    left_clock_ref,
    right_client_ref,
    right_clock_ref,
    # state (DB, N) int32 / (DB, 1) int32 — aliased in/out
    idc_ref,
    idk_ref,
    rank_ref,
    orank_ref,
    del_ref,
    len_ref,
    ovf_ref,
    # outputs (aliases of the state refs)
    idc_out,
    idk_out,
    rank_out,
    orank_out,
    del_out,
    len_out,
    ovf_out,
    *,
    num_slots: int,
):
    db, n = idc_ref.shape
    idx = jax.lax.broadcasted_iota(jnp.int32, (db, n), 1)

    # load the op columns once; extract column k inside the loop with a
    # broadcast-compare + row-sum (dynamic lane slices don't tile on
    # TPU, and a static unroll would blow the VMEM stack with per-
    # iteration temporaries)
    lane = jax.lax.broadcasted_iota(jnp.int32, (db, num_slots), 1)
    all_kind = kind_ref[:]
    all_client = client_ref[:]
    all_clock = clock_ref[:]
    all_run = run_len_ref[:]
    all_lc = left_client_ref[:]
    all_lk = left_clock_ref[:]
    all_rc = right_client_ref[:]
    all_rk = right_clock_ref[:]

    def apply_op(k, _):
        sel = lane == k

        def col(vals, none=0):
            return jnp.sum(jnp.where(sel, vals, none), axis=1, keepdims=True)

        op_kind = col(all_kind)
        op_client = col(all_client)
        op_clock = col(all_clock)
        run = col(all_run)
        lc = col(all_lc)
        lk = col(all_lk)
        rc = col(all_rc)
        rk = col(all_rk)

        idc = idc_out[:]
        idk = idk_out[:]
        rank = rank_out[:]
        orank = orank_out[:]
        dele = del_out[:]
        length = len_out[:]
        ovf = ovf_out[:]

        occupied = idx < length

        # resolve origin ids to ranks (masked row reductions); found-ness
        # falls out of the max (occupied ranks are >= 0), saving two
        # any-reductions per op
        is_left = occupied & (idc == lc) & (idk == lk)
        has_left = lc != _NONE
        left_raw = jnp.max(jnp.where(is_left, rank, -1), axis=1, keepdims=True)
        left_found = left_raw >= 0
        left_rank = jnp.where(has_left, left_raw, -1)
        is_right = occupied & (idc == rc) & (idk == rk)
        has_right = rc != _NONE
        right_raw = jnp.max(jnp.where(is_right, rank, -1), axis=1, keepdims=True)
        right_found = right_raw >= 0
        right_rank = jnp.where(has_right, right_raw, length)

        # YATA conflict scan over the (left, right) rank window
        in_window = occupied & (rank > left_rank) & (rank < right_rank)
        client_lt = (idc ^ _SIGN) < (op_client ^ _SIGN)  # unsigned compare
        skip_cond = (orank > left_rank) | ((orank == left_rank) & client_lt)
        blocked = in_window & ~skip_cond
        first_block = jnp.min(
            jnp.where(blocked, rank, _INF), axis=1, keepdims=True
        )
        skipped = jnp.sum(
            (in_window & (rank < first_block)).astype(jnp.int32),
            axis=1,
            keepdims=True,
        )
        ins_rank = left_rank + 1 + skipped

        fits = length + run <= n
        deps_ok = (~has_left | left_found) & (~has_right | right_found)
        do_insert = (op_kind == KIND_INSERT) & fits & deps_ok

        # elementwise insert: bump ranks, fill the appended slots
        bump = do_insert & occupied
        rank_b = jnp.where(bump & (rank >= ins_rank), rank + run, rank)
        orank_b = jnp.where(bump & (orank >= ins_rank), orank + run, orank)
        slot_off = idx - length
        in_new = do_insert & (slot_off >= 0) & (slot_off < run)
        is_first = slot_off == 0

        idc_out[:] = jnp.where(in_new, op_client, idc)
        idk_out[:] = jnp.where(in_new, op_clock + slot_off, idk)
        rank_out[:] = jnp.where(in_new, ins_rank + slot_off, rank_b)
        orank_out[:] = jnp.where(
            in_new, jnp.where(is_first, left_rank, ins_rank + slot_off - 1), orank_b
        )

        # delete: id-range tombstones
        in_del = (
            (op_kind == KIND_DELETE)
            & occupied
            & (idc == op_client)
            & (idk >= op_clock)
            & (idk < op_clock + run)
        )
        del_out[:] = jnp.where(in_new, 0, dele) | in_del.astype(jnp.int32)

        len_out[:] = jnp.where(do_insert, length + run, length)
        ovf_out[:] = ovf | ((op_kind == KIND_INSERT) & ~fits).astype(jnp.int32)
        return 0

    # copy aliased inputs through once, then iterate in VMEM
    idc_out[:] = idc_ref[:]
    idk_out[:] = idk_ref[:]
    rank_out[:] = rank_ref[:]
    orank_out[:] = orank_ref[:]
    del_out[:] = del_ref[:]
    len_out[:] = len_ref[:]
    ovf_out[:] = ovf_ref[:]
    jax.lax.fori_loop(0, num_slots, apply_op, 0)


# Mosaic's default scoped-VMEM cap is 16MB; a v5e core has 128MB of
# physical VMEM. We raise the cap and keep our own budget under it so
# the block choice — not the compiler's default — is the binding limit.
_VMEM_LIMIT = 100 * 1024 * 1024
_VMEM_BUDGET = 96 * 1024 * 1024

# Measured live set of the block kernel, in (db, N) int32 buffers: the
# 5 aliased arena outputs, their 5 re-reads inside apply_op, plus
# Mosaic's per-iteration temporaries for the masked reductions and the
# elementwise rewrite (~17 more). r02's OOM pinned this empirically:
# "scoped allocation 19.68M" at db=32, N=5632 => 19.68e6/(32*5632*4)
# ~ 27.3 buffers. 28 gives margin; tests/tpu/test_pallas_kernels.py
# asserts the model against that shape so a regression fails in CI.
_LIVE_BUFFERS = 28


def _pick_block(num_docs: int, capacity: int = 2048) -> int:
    """Largest doc-block that divides D and fits VMEM.

    Budget model: ~_LIVE_BUFFERS live (db, N) int32 buffers (see above;
    op blocks are (db, K) with K<=64 — noise by comparison). Measured
    best on v5e at N=2048 is db=64 (HBM-pass-bound beyond).
    """
    for db in (64, 32, 16, 8):
        if num_docs % db == 0 and _LIVE_BUFFERS * db * capacity * 4 <= _VMEM_BUDGET:
            return db
    return 0


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))
def _integrate_pallas(state: DocState, ops: OpBatch, interpret: bool):
    """Layout conversion + pallas_call as ONE jitted program.

    Doing the int32 views, the (K, D) -> doc-major transposes, and the
    bool conversions inside the jit lets XLA fuse them into the kernel's
    input pipeline instead of dispatching ~15 eager ops per flush; the
    count is also produced here so callers get a single program whose
    outputs all depend on the device step.
    """
    idc = state.id_client.view(jnp.int32)
    idk = state.id_clock
    rank = state.rank
    orank = state.origin_rank
    dele = state.deleted.astype(jnp.int32)
    length = state.length[:, None]
    ovf = state.overflow.astype(jnp.int32)[:, None]
    ops_i32 = (  # (K, D) -> doc-major (D, K) for lane-dim K blocks
        ops.kind.T,
        ops.client.view(jnp.int32).T,
        ops.clock.T,
        ops.run_len.T,
        ops.left_client.view(jnp.int32).T,
        ops.left_clock.T,
        ops.right_client.view(jnp.int32).T,
        ops.right_clock.T,
    )
    num_docs, capacity = idc.shape
    num_slots = ops_i32[0].shape[1]
    db = _pick_block(num_docs, capacity)

    grid = (num_docs // db,)
    op_spec = pl.BlockSpec((db, num_slots), lambda i: (i, 0), memory_space=pltpu.VMEM)
    arena_spec = pl.BlockSpec((db, capacity), lambda i: (i, 0), memory_space=pltpu.VMEM)
    scalar_spec = pl.BlockSpec((db, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        functools.partial(_integrate_block_kernel, num_slots=num_slots),
        grid=grid,
        in_specs=[op_spec] * 8 + [arena_spec] * 5 + [scalar_spec] * 2,
        out_specs=tuple([arena_spec] * 5 + [scalar_spec] * 2),
        out_shape=tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            for a in (idc, idk, rank, orank, dele, length, ovf)
        ),
        # state tensors update in place (inputs 8..14 -> outputs 0..6)
        input_output_aliases={8 + i: i for i in range(7)},
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(*ops_i32, idc, idk, rank, orank, dele, length, ovf)
    idc, idk, rank, orank, dele, length, ovf = out
    from .kernels import KIND_NOOP

    new_state = DocState(
        id_client=idc.view(jnp.uint32),
        id_clock=idk,
        rank=rank,
        origin_rank=orank,
        deleted=dele.astype(bool),
        length=length[:, 0],
        overflow=ovf[:, 0].astype(bool),
    )
    count = jnp.sum(ops.kind != KIND_NOOP)
    # tie the count to a kernel output so fetching it is a completion
    # barrier for the integrate step by DATA DEPENDENCE, not by runtime
    # program-atomicity assumptions (see bench.py sync() on why buffer
    # readiness cannot be trusted here)
    count, _ = jax.lax.optimization_barrier((count, new_state.length))
    return new_state, count


# Shapes whose Pallas compile failed on this process's backend. r02's
# bench died because a Mosaic VMEM OOM propagated out of the flush; a
# kernel failure must cost one fallback, not the server. Keyed by the
# full (D, N, K) problem shape since any of them can change the
# compiled program.
_pallas_broken_shapes: set[tuple[int, int, int]] = set()


def integrate_op_slots_pallas(
    state: DocState, ops: OpBatch, *, interpret: bool = False
) -> tuple[DocState, jax.Array]:
    """Drop-in equivalent of kernels.integrate_op_slots via Pallas.

    Ops fields have shape (K, D). Falls back to the XLA scan path when
    the doc count has no valid block factor, or — permanently for that
    shape — when Mosaic rejects the kernel (e.g. a VMEM regression),
    so a compile failure degrades throughput instead of availability.
    """
    from .kernels import integrate_op_slots

    shape = (state.id_client.shape[0], state.id_client.shape[1], ops.kind.shape[0])
    if _pick_block(shape[0], shape[1]) == 0 or shape in _pallas_broken_shapes:
        return integrate_op_slots(state, ops)
    try:
        return _integrate_pallas(state, ops, interpret)
    except Exception as error:  # Mosaic/XLA compile or launch failure
        _pallas_broken_shapes.add(shape)
        import logging

        logging.getLogger("hocuspocus_tpu.tpu").warning(
            "pallas integrate failed at shape %s; falling back to XLA scan: %s",
            shape,
            str(error)[:500],
        )
        return integrate_op_slots(state, ops)


def integrate_op_slots_fast(state: DocState, ops: OpBatch) -> tuple[DocState, jax.Array]:
    """Backend dispatcher: Pallas on TPU, XLA scan elsewhere."""
    from .kernels import integrate_op_slots

    if jax.default_backend() == "tpu":
        return integrate_op_slots_pallas(state, ops)
    return integrate_op_slots(state, ops)


# -- sparse (busy-doc) dispatch ----------------------------------------------


@functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
def _integrate_sparse_pallas(state: DocState, ops: OpBatch, slots, interpret: bool):
    """Gather the B busy rows, run the VMEM-resident block kernel over
    the (B, N) sub-arena, scatter back in place — one jitted program, so
    XLA fuses the gather into the kernel's input pipeline and aliases
    the (D, N) arenas through the scatter (the state is donated)."""
    from .kernels import gather_doc_rows, scatter_doc_rows

    sub = gather_doc_rows(state, slots)
    sub, count = _integrate_pallas.__wrapped__(sub, ops, interpret)
    state = scatter_doc_rows(state, sub, slots)
    count, _ = jax.lax.optimization_barrier((count, state.length))
    return state, count


def integrate_op_slots_sparse_pallas(
    state: DocState, ops: OpBatch, slots, *, interpret: bool = False
) -> tuple[DocState, jax.Array]:
    """Sparse dispatch via Pallas; ops fields are (K, B), slots (B,).

    Falls back to the sparse XLA scan when B has no valid doc-block
    factor (B < 8) or — permanently per shape — when Mosaic rejects
    the kernel."""
    from .kernels import integrate_op_slots_sparse

    b = int(slots.shape[0])
    capacity = state.id_client.shape[1]
    shape = (b, capacity, ops.kind.shape[0])
    if _pick_block(b, capacity) == 0 or shape in _pallas_broken_shapes:
        return integrate_op_slots_sparse(state, ops, slots)
    try:
        return _integrate_sparse_pallas(state, ops, slots, interpret)
    except Exception as error:  # Mosaic/XLA compile or launch failure
        _pallas_broken_shapes.add(shape)
        import logging

        logging.getLogger("hocuspocus_tpu.tpu").warning(
            "pallas sparse integrate failed at shape %s; falling back to XLA scan: %s",
            shape,
            str(error)[:500],
        )
        return integrate_op_slots_sparse(state, ops, slots)


def integrate_op_slots_sparse_fast(
    state: DocState, ops: OpBatch, slots
) -> tuple[DocState, jax.Array]:
    """Backend dispatcher for the sparse step: Pallas on TPU, XLA scan
    elsewhere."""
    from .kernels import integrate_op_slots_sparse

    if jax.default_backend() == "tpu":
        return integrate_op_slots_sparse_pallas(state, ops, slots)
    return integrate_op_slots_sparse(state, ops, slots)


# -- minimal-work run merge (sequential fast path) -----------------------------


def append_run_slots_sparse_fast(
    state: DocState, client, clock, run_len, slots
) -> tuple[DocState, jax.Array]:
    """Backend dispatcher for the run-append fast path.

    The integrate scan needs Mosaic because every op slot re-reads the
    whole (B, N) sub-arena from HBM — K passes of conflict scanning.
    The append program has no conflict scan at all: one fit pass over a
    (K,) carry and one fused masked fill of each gathered row, so the
    XLA lowering is already a single read + write of the touched rows
    on every backend. This wrapper keeps the plane's call seam uniform
    with the integrate/compact dispatchers so a future VMEM-resident
    variant slots in without touching the plane."""
    from .kernels import append_run_slots_sparse

    return append_run_slots_sparse(state, client, clock, run_len, slots)


# -- on-device compaction ------------------------------------------------------


def compact_doc_rows_fast(state: DocState, slots) -> tuple[DocState, jax.Array]:
    """Backend dispatcher for the compact (tombstone-GC) step, the seam
    the plane calls through like every other kernel entry point.

    Unlike the integrate hot loop — where the XLA scan re-reads the
    whole arena from HBM once per op slot and the VMEM-resident Mosaic
    kernel is the fix — compaction is a single-pass permutation
    (scatter + cumsum + gather) with no K-pass HBM amplification to
    kill, so the XLA lowering is already one read and one write of the
    gathered rows on every backend. A handwritten Mosaic kernel would
    buy nothing here; this wrapper exists so a future VMEM-resident
    variant slots in without touching the plane."""
    from .kernels import compact_doc_rows

    return compact_doc_rows(state, slots)
