"""CLI entrypoint (reference `packages/cli`): `hocuspocus-tpu --port 1234`."""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="hocuspocus-tpu",
        description="Run a TPU-native collaboration backend server.",
    )
    parser.add_argument("--port", "-p", type=int, default=1234, help="port to listen on")
    parser.add_argument("--host", default="0.0.0.0", help="host to bind")
    # edge tier + cell router (docs/guides/edge-routing.md): split the
    # million-connection front door from the merge cells. An 'edge'
    # terminates websockets, authenticates/admits at the door and
    # relays frames to each doc's owning cell over the pipelined RESP
    # lane; a 'cell' is a normal serving instance that also accepts
    # relayed edge sessions and announces its lifecycle (up/draining/
    # down) on the relay control channel; 'monolith' (default) is the
    # classic single-role server.
    parser.add_argument(
        "--role",
        choices=("monolith", "edge", "cell"),
        default="monolith",
        help="process role: 'monolith' (default) terminates sockets AND "
        "merges; 'edge' is a stateless front door relaying to cells; "
        "'cell' merges docs and serves relayed edge sessions "
        "(docs/guides/edge-routing.md)",
    )
    parser.add_argument(
        "--cell-id",
        help="stable cell identity on the relay bus (role=cell; default "
        "cell-<port>) — the rendezvous-hash key docs map to, so keep it "
        "stable across restarts",
    )
    parser.add_argument(
        "--edge-id",
        help="edge identity on the relay bus (role=edge; default a "
        "random edge-<hex> — edges are stateless, identity is per-boot)",
    )
    parser.add_argument(
        "--relay-redis-host",
        default="127.0.0.1",
        help="redis host backing the edge<->cell relay lane (default "
        "127.0.0.1)",
    )
    parser.add_argument(
        "--relay-redis-port", type=int, default=6379, help="relay redis port"
    )
    parser.add_argument(
        "--relay-prefix",
        default="hocuspocus-edge",
        help="channel prefix for the relay lane + control channel",
    )
    parser.add_argument(
        "--relay-queue-limit",
        type=int,
        default=1024,
        help="frames a parked/re-establishing edge doc channel may "
        "buffer before the oldest is shed (accounted, healed by the "
        "rebind resync; default 1024)",
    )
    # elastic fleet (docs/guides/elastic-fleet.md): cross-host cell
    # admission + the autoscaling controller over warm-spare cells.
    parser.add_argument(
        "--host-id",
        help="host identity on the relay bus: qualifies this process's "
        "cell id as <host-id>/<cell-id> so cells from DIFFERENT hosts "
        "can share one control channel, and (role=edge) marks which "
        "cells are local — foreign cells are admitted only once their "
        "clock offset resolves (docs/guides/elastic-fleet.md)",
    )
    parser.add_argument(
        "--fleet-autoscale",
        action="store_true",
        help="run the fleet autoscaling controller over the multi-device "
        "cell plane (requires --tpu-devices != 1): scale-up activates "
        "warm-spare cells, scale-down drains the coldest cell over the "
        "migration rail; all scaling parks while the overload ladder is "
        "at BROWNOUT-1+ (docs/guides/elastic-fleet.md)",
    )
    parser.add_argument(
        "--fleet-interval",
        type=float,
        default=2.0,
        help="autoscaler decision cadence in seconds (default 2)",
    )
    parser.add_argument(
        "--fleet-min-cells",
        type=int,
        default=1,
        help="floor the autoscaler may never scale below (default 1)",
    )
    parser.add_argument(
        "--fleet-warm-spares",
        type=int,
        default=0,
        help="cells parked as pre-warmed spares at boot — arena and "
        "registry stay built, so activation is one placement-epoch "
        "bump (default 0 = start with every cell active)",
    )
    parser.add_argument(
        "--fleet-up",
        type=float,
        default=0.75,
        help="mean fleet-load signal that (held for --fleet-hold-ticks) "
        "activates a warm spare (default 0.75)",
    )
    parser.add_argument(
        "--fleet-down",
        type=float,
        default=0.35,
        help="mean fleet-load signal that (held, and only when the "
        "survivors' projected load stays in band) parks the coldest "
        "cell (default 0.35)",
    )
    parser.add_argument(
        "--fleet-hold-ticks",
        type=int,
        default=3,
        help="consecutive out-of-band decision ticks before the "
        "autoscaler acts — the anti-flap hysteresis hold (default 3)",
    )
    parser.add_argument(
        "--fleet-work-target",
        type=float,
        default=150.0,
        help="dispatched merge units/second that count as a fully "
        "loaded cell in the fleet-load signal (default 150)",
    )
    parser.add_argument("--webhook", "-w", help="webhook URL to POST document changes to")
    parser.add_argument(
        "--sqlite",
        "-s",
        nargs="?",
        const=":memory:",
        help="store documents in SQLite (optional path, default in-memory)",
    )
    parser.add_argument("--s3", action="store_true", help="store documents in S3")
    # durability plane (docs/guides/durability.md): per-doc write-ahead
    # log + crash recovery, store retry/quarantine, graceful drain
    parser.add_argument(
        "--wal-dir",
        help="enable the write-ahead log: append every update to a "
        "segmented CRC-framed per-document log under this directory "
        "BEFORE broadcast, and replay the log suffix over the stored "
        "snapshot at load — a kill -9 between debounced stores loses "
        "nothing (docs/guides/durability.md)",
    )
    parser.add_argument(
        "--wal-fsync",
        choices=("tick", "always", "off"),
        default="tick",
        help="WAL durability mode: 'tick' group-commits with one fsync "
        "per doc per event-loop tick (default), 'always' fsyncs every "
        "record, 'off' writes without fsync (OS-decided durability)",
    )
    parser.add_argument(
        "--store-retries",
        type=int,
        default=2,
        help="retries (after the first attempt) for a failing "
        "on_store_document chain, with exponential backoff + jitter; "
        "after exhaustion the doc is quarantined — kept loaded, WAL "
        "retained, periodically re-stored, /healthz degraded — instead "
        "of silently dropping data (default 2)",
    )
    parser.add_argument(
        "--drain-timeout-secs",
        type=float,
        default=20.0,
        help="SIGTERM drain deadline: stop accepting connections, flush "
        "the WAL, store every dirty doc concurrently within this many "
        "seconds, then close clients with 1012 Service Restart; docs "
        "still storing at the deadline are quarantined, never lost "
        "(default 20)",
    )
    parser.add_argument("--s3-bucket", help="S3 bucket")
    parser.add_argument("--s3-region", default="us-east-1", help="S3 region")
    parser.add_argument("--s3-prefix", default="", help="S3 key prefix")
    parser.add_argument("--s3-endpoint", help="S3 endpoint override")
    parser.add_argument(
        "--tpu-merge",
        action="store_true",
        help="enable the TPU batched merge plane extension (shadow mode)",
    )
    parser.add_argument(
        "--tpu-serve",
        action="store_true",
        help="serve sync replies and broadcasts FROM the TPU plane (implies --tpu-merge)",
    )
    parser.add_argument(
        "--tpu-docs",
        type=int,
        default=1024,
        help="merge plane arena rows (sequences), default 1024",
    )
    parser.add_argument(
        "--tpu-capacity",
        type=int,
        default=4096,
        help="merge plane arena capacity per row (units), default 4096",
    )
    parser.add_argument(
        "--tpu-flush-interval",
        type=float,
        default=5.0,
        help="device flush cadence in ms (validation pipeline), default 5",
    )
    parser.add_argument(
        "--tpu-broadcast-interval",
        type=float,
        default=2.0,
        help="broadcast coalescing window in ms (edits within the window "
        "share one frame per doc; idle edits broadcast immediately), "
        "default 2",
    )
    parser.add_argument(
        "--tpu-shards",
        type=int,
        default=1,
        help="doc-partitioned merge planes (serve mode): each shard "
        "flushes its own arena, keeping microbatch latency bounded at "
        "large doc populations; --tpu-docs is the per-shard width. "
        "Default 1 (single plane)",
    )
    # multi-device merge cells (docs/guides/multi-device.md): one full
    # merge cell — arena, device lane, governor, warm grid, residency
    # clock — per chip, with rendezvous doc placement and load-aware
    # rebalancing over the evict-snapshot→hydrate migration rail.
    parser.add_argument(
        "--tpu-devices",
        type=int,
        default=1,
        help="per-device merge cells: 0 = one cell per visible chip, "
        "N > 1 = exactly N cells (wrapping the device roster), 1 = the "
        "classic single-plane layout (default). --tpu-docs/--tpu-capacity "
        "are PER-CELL sizes; mutually exclusive with --tpu-shards "
        "(docs/guides/multi-device.md)",
    )
    parser.add_argument(
        "--tpu-rebalance-interval",
        type=float,
        default=5.0,
        help="seconds between load-aware placement sweeps on the cell "
        "plane (0 disables rebalancing — placement stays pure "
        "rendezvous); default 5",
    )
    parser.add_argument(
        "--tpu-rebalance-ratio",
        type=float,
        default=2.0,
        help="a cell hotter than this multiple of the mean (dispatched "
        "work, lane depth, HBM) sheds docs to its coldest peer via the "
        "evict-snapshot->hydrate migration rail (default 2.0)",
    )
    parser.add_argument(
        "--tpu-migrate-batch",
        type=int,
        default=8,
        help="docs migrated per rebalance sweep — bounds migration "
        "churn under a skewed storm (default 8)",
    )
    parser.add_argument(
        "--tpu-arena",
        choices=("unit", "rle"),
        default="unit",
        help="device arena layout: 'unit' (one slot per UTF-16 unit) or "
        "'rle' (one entry per run — survives churny long-lived docs; "
        "--tpu-capacity then counts entries)",
    )
    # arena residency (docs/guides/tpu-residency.md): slots are a
    # managed cache — idle docs evict to host snapshots, cold docs
    # re-admit through a bounded hydration queue, pressured rows
    # compact on-device instead of retiring to the CPU path forever.
    parser.add_argument(
        "--tpu-evict-idle-secs",
        type=float,
        default=0.0,
        help="evict a doc's arena rows after this many seconds without "
        "an edit (serve mode; 0 disables eviction). Evicted docs serve "
        "from the CPU path and re-enter via batched hydration on their "
        "next edit or load (default 0)",
    )
    parser.add_argument(
        "--tpu-hydrate-batch",
        type=int,
        default=64,
        help="cold/evicted docs admitted back onto the plane per "
        "hydration round — the catch-up storm's concurrency bound "
        "(default 64)",
    )
    parser.add_argument(
        "--tpu-compact-threshold",
        type=float,
        default=0.75,
        help="row occupancy fraction that triggers on-device tombstone "
        "compaction; also enables compact-based recycling of "
        "capacity/overflow-retired docs (serve mode; 0 disables, "
        "default 0.75)",
    )
    # adaptive merge scheduling (docs/guides/tpu-scheduling.md): the
    # device-lane arbiter orders every dispatch by priority class
    # (interactive > catch-up > compaction > canary/warmup) and the
    # arrival-aware governor picks flush cadence + batch count from
    # measured load instead of the fixed timer.
    parser.add_argument(
        "--tpu-scheduler",
        choices=("on", "off"),
        default="on",
        help="adaptive merge scheduling: 'on' (default) runs every "
        "device dispatch through the priority-class lane arbiter and "
        "drives flush cadence from the op-arrival EWMA; 'off' restores "
        "the fixed flush timer with unarbitrated dispatches",
    )
    parser.add_argument(
        "--tpu-drain-watermark",
        type=int,
        default=256,
        help="queued-op depth at which the governor collapses the flush "
        "tick to an immediate full drain (default 256)",
    )
    parser.add_argument(
        "--tpu-flush-stretch",
        type=float,
        default=4.0,
        help="max factor the governor may stretch the flush tick under "
        "sparse arrivals — cheap, since broadcasts build from host "
        "serve logs and never wait on the device flush (default 4)",
    )
    parser.add_argument(
        "--tpu-lane-promote-ms",
        type=float,
        default=250.0,
        help="device-lane starvation guard: a queued background "
        "admission older than this is promoted to the interactive "
        "class so aged work always progresses (default 250)",
    )
    # plane supervisor (docs/guides/tpu-supervisor.md): the TPU runtime
    # is an accelerator the server may acquire, never a boot dependency
    # — a wedged/absent runtime degrades to CPU-merge mode, the server
    # keeps serving, and the plane hot-(re)attaches on recovery.
    parser.add_argument(
        "--tpu-init-timeout",
        type=float,
        default=30.0,
        help="seconds to wait for TPU runtime init (device discovery + "
        "first compile) before booting in CPU-merge fallback; the plane "
        "hot-attaches if init completes later (default 30)",
    )
    parser.add_argument(
        "--tpu-watchdog-interval",
        type=float,
        default=5.0,
        help="seconds between plane watchdog canary merges; also the "
        "half-open recovery probe cadence (default 5)",
    )
    parser.add_argument(
        "--tpu-breaker-threshold",
        type=int,
        default=3,
        help="consecutive canary failures/overruns that open the circuit "
        "breaker, draining served docs to the CPU path until a recovery "
        "probe passes (default 3; see docs/guides/tpu-supervisor.md)",
    )
    # overload control plane (docs/guides/overload.md): the hysteresis
    # degradation ladder (GREEN -> BROWNOUT-1 -> BROWNOUT-2 -> RED)
    # driven by live load signals, plus per-tenant token-bucket
    # admission at connect/auth and message ingress.
    parser.add_argument(
        "--overload",
        choices=("on", "off"),
        default="on",
        help="overload control plane: 'on' (default) samples load "
        "signals (event-loop lag, send queues, device-lane depth, WAL "
        "commit latency, replication inbox) into a brownout ladder — "
        "park maintenance, stretch awareness, defer catch-up, reject "
        "new work at RED with 503 + Retry-After; 'off' disables all "
        "shedding and admission",
    )
    parser.add_argument(
        "--overload-hold-secs",
        type=float,
        default=2.0,
        help="hysteresis hold: the ladder steps DOWN one rung only "
        "after this many seconds of sustained calm (escalation is "
        "always immediate); prevents rung flapping (default 2)",
    )
    parser.add_argument(
        "--overload-retry-after",
        type=float,
        default=1.0,
        help="Retry-After seconds on 503 rejections (RED state, tenant "
        "quota, and the drain path share the same rejection; default 1)",
    )
    parser.add_argument(
        "--tenant-connect-rate",
        type=float,
        default=0.0,
        help="per-tenant connect/auth admission rate, document channels "
        "per second (token bucket; 0 = unlimited, the default). A "
        "tenant over quota is refused without touching other tenants' "
        "buckets",
    )
    parser.add_argument(
        "--tenant-connect-burst",
        type=float,
        default=8.0,
        help="per-tenant connect bucket burst capacity (default 8)",
    )
    parser.add_argument(
        "--tenant-msg-rate",
        type=float,
        default=0.0,
        help="per-tenant message-ingress admission rate, frames per "
        "second (0 = unlimited, the default); over-quota frames are "
        "counted, and at RED the channel closes 1013 Try Again Later",
    )
    parser.add_argument(
        "--tenant-msg-burst",
        type=float,
        default=256.0,
        help="per-tenant message bucket burst capacity (default 256)",
    )
    # observability (docs/guides/observability.md): Prometheus /metrics,
    # end-to-end update lifecycle tracing with Perfetto export
    # (/debug/trace), on-demand device profiles (/debug/profile) and the
    # per-doc flight recorder (/debug/docs).
    parser.add_argument(
        "--metrics",
        action="store_true",
        help="serve Prometheus metrics at /metrics plus the /debug "
        "endpoints (trace export, profiler capture, per-doc flight "
        "recorder); implied by --trace",
    )
    parser.add_argument(
        "--trace",
        action="store_true",
        help="enable end-to-end update lifecycle tracing: stage spans "
        "(queue-wait/build/upload/device/readback/broadcast) share one "
        "trace id per sampled update, exported as Chrome/Perfetto JSON "
        "at /debug/trace and as hocuspocus_tpu_update_e2e_seconds{stage=} "
        "histograms on /metrics",
    )
    parser.add_argument(
        "--trace-max-spans",
        type=int,
        default=4096,
        help="span ring capacity (oldest spans drop first), default 4096",
    )
    parser.add_argument(
        "--trace-slow-ms",
        type=float,
        default=0.0,
        help="promote spans at/above this duration to structured log "
        "lines and the hocuspocus_tpu_slow_spans_total{site=} counter — "
        "survives ring wrap (0 disables, the default)",
    )
    parser.add_argument(
        "--trace-sample",
        type=int,
        default=1,
        help="trace 1 in N captured updates (default 1 = every update); "
        "raise under load so tracing stays viable at 100k docs",
    )
    parser.add_argument(
        "--profile-hz",
        type=float,
        default=99.0,
        help="sampling rate of the always-on host CPU profiler "
        "(/debug/profile/cpu collapsed stacks, per-frame cost "
        "attribution context for /debug/costs); default 99 Hz, "
        "measured overhead <1%% — 0 disables the sampler",
    )
    # SLO engine (docs/guides/observability.md): multi-window burn
    # rates over the e2e-latency and wire-error-rate objectives, served
    # at /debug/slo and folded into /healthz
    parser.add_argument(
        "--slo-e2e-ms",
        type=float,
        default=50.0,
        help="e2e latency objective: 99%% of traced updates must "
        "complete socket->broadcast within this many ms (default 50, "
        "the BASELINE p99 budget)",
    )
    parser.add_argument(
        "--slo-error-rate",
        type=float,
        default=0.001,
        help="error budget for the wire error-rate objective: the "
        "allowed fraction of inbound messages that may fail (default "
        "0.001 = 99.9%% succeed)",
    )
    parser.add_argument(
        "--slo-fleet-e2e-ms",
        type=float,
        default=250.0,
        help="fleet cross-tier latency objective: 99%% of traced "
        "edge->cell->edge updates must complete within this many ms "
        "(default 250; fed by the hocuspocus_fleet_e2e_seconds "
        "histogram — docs/guides/observability.md fleet view)",
    )
    return parser


async def run(args: argparse.Namespace) -> None:
    from .extensions import Logger, SQLite, S3, Webhook
    from .server import Configuration, Server

    extensions: list = [Logger()]
    if args.trace:
        from .observability import enable_tracing

        tracer = enable_tracing(max_spans=args.trace_max_spans)
        tracer.slow_ms = args.trace_slow_ms if args.trace_slow_ms > 0 else None
        tracer.sample = max(args.trace_sample, 1)
    if args.metrics or args.trace:
        # /metrics + /debug/{trace,profile,docs,slo}: tracing without
        # the exporter would be write-only, so --trace implies it
        from .observability import Metrics, get_profiler

        # sampler rate must land before Metrics.on_configure calls
        # ensure_started(); 0 keeps the profiler thread off entirely
        get_profiler().configure(hz=args.profile_hz)
        extensions.append(
            Metrics(
                slo_e2e_p99_ms=args.slo_e2e_ms,
                slo_error_rate=args.slo_error_rate,
                slo_fleet_e2e_ms=args.slo_fleet_e2e_ms,
            )
        )
    if args.overload == "on":
        # the process-global degradation ladder + tenant admission
        # (docs/guides/overload.md); priority 990 so it configures
        # right after Metrics lights the wire collector
        from .server.overload import OverloadExtension

        extensions.append(
            OverloadExtension(
                hold_s=args.overload_hold_secs,
                retry_after_s=args.overload_retry_after,
                connect_rate=args.tenant_connect_rate,
                connect_burst=args.tenant_connect_burst,
                message_rate=args.tenant_msg_rate,
                message_burst=args.tenant_msg_burst,
            )
        )
    if args.wal_dir:
        from .storage import Durability

        extensions.append(Durability(wal_dir=args.wal_dir, fsync=args.wal_fsync))
    if args.sqlite is not None:
        extensions.append(SQLite(database=args.sqlite))
    if args.s3:
        if not args.s3_bucket:
            print("--s3 requires --s3-bucket", file=sys.stderr)
            sys.exit(2)
        extensions.append(
            S3(
                bucket=args.s3_bucket,
                region=args.s3_region,
                prefix=args.s3_prefix,
                endpoint=args.s3_endpoint,
            )
        )
    if args.webhook:
        extensions.append(Webhook(url=args.webhook))
    if args.role == "cell":
        from .edge import CellIngressExtension

        extensions.append(
            CellIngressExtension(
                cell_id=args.cell_id or f"cell-{args.port}",
                host_id=args.host_id,
                host=args.relay_redis_host,
                port=args.relay_redis_port,
                prefix=args.relay_prefix,
            )
        )
    if args.tpu_merge or args.tpu_serve:
        # importing .tpu pins the backend to CPU when JAX_PLATFORMS=cpu
        # (see hocuspocus_tpu/tpu/__init__.py). The supervised extension
        # defers ALL device work (kernel imports, discovery, compiles)
        # to a deadline-bounded worker thread: a wedged or absent TPU
        # runtime can no longer hang boot — the server serves in
        # CPU-merge mode and the plane hot-attaches when the runtime
        # comes up (docs/guides/tpu-supervisor.md).
        from .tpu import SupervisedTpuMergeExtension

        if args.tpu_devices != 1 and args.tpu_shards > 1:
            print(
                "--tpu-devices and --tpu-shards are mutually exclusive "
                "(per-chip cells subsume doc-sharding across chips)",
                file=sys.stderr,
            )
            sys.exit(2)
        cell_kwargs = (
            {
                "devices": args.tpu_devices,
                "rebalance_interval_s": args.tpu_rebalance_interval,
                "rebalance_ratio": args.tpu_rebalance_ratio,
                "migrate_batch": args.tpu_migrate_batch,
            }
            if args.tpu_devices != 1
            else {}
        )
        extensions.append(
            SupervisedTpuMergeExtension(
                shards=args.tpu_shards,
                **cell_kwargs,
                init_timeout=args.tpu_init_timeout,
                watchdog_interval=args.tpu_watchdog_interval,
                breaker_threshold=args.tpu_breaker_threshold,
                num_docs=args.tpu_docs,
                capacity=args.tpu_capacity,
                serve=args.tpu_serve,
                flush_interval_ms=args.tpu_flush_interval,
                broadcast_interval_ms=args.tpu_broadcast_interval,
                arena=args.tpu_arena,
                evict_idle_secs=args.tpu_evict_idle_secs,
                hydrate_batch=args.tpu_hydrate_batch,
                compact_threshold=args.tpu_compact_threshold,
                governor=args.tpu_scheduler == "on",
                lane=None if args.tpu_scheduler == "on" else False,
                drain_watermark=args.tpu_drain_watermark,
                flush_stretch=args.tpu_flush_stretch,
                lane_promote_ms=args.tpu_lane_promote_ms,
            )
        )
    if args.fleet_autoscale:
        if args.tpu_devices == 1 or not (args.tpu_merge or args.tpu_serve):
            print(
                "--fleet-autoscale requires the multi-device cell plane "
                "(--tpu-serve with --tpu-devices != 1)",
                file=sys.stderr,
            )
            sys.exit(2)
        from .fleet import FleetControllerExtension

        extensions.append(
            FleetControllerExtension(
                interval_s=args.fleet_interval,
                warm_spares=args.fleet_warm_spares,
                min_cells=args.fleet_min_cells,
                up_threshold=args.fleet_up,
                down_threshold=args.fleet_down,
                hold_ticks=args.fleet_hold_ticks,
                work_target=args.fleet_work_target,
            )
        )

    configuration = Configuration(
        extensions=extensions,
        quiet=False,
        store_retries=max(args.store_retries, 0),
        drain_timeout_secs=args.drain_timeout_secs,
        # the drain/RED/edge 503 paths share one Retry-After knob even
        # with the overload controller off (three-way wire parity)
        retry_after_s=args.overload_retry_after,
    )
    if args.role == "edge":
        # the stateless front door: no documents, no merge plane — just
        # door auth/admission and the relay fabric. Doc-serving flags
        # (--sqlite/--wal-dir/--tpu-*) are inert here by construction.
        from .edge import EdgeGatewayExtension, EdgeServer

        extensions.append(
            EdgeGatewayExtension(
                edge_id=args.edge_id,
                host_id=args.host_id,
                host=args.relay_redis_host,
                port=args.relay_redis_port,
                prefix=args.relay_prefix,
                relay_queue_limit=args.relay_queue_limit,
            )
        )
        server = EdgeServer(configuration)
    else:
        server = Server(configuration)
    await server.listen(port=args.port, host=args.host)

    stop = asyncio.Event()
    drain_requested = False
    loop = asyncio.get_running_loop()

    def request_stop(graceful: bool) -> None:
        nonlocal drain_requested
        drain_requested = drain_requested or graceful
        stop.set()

    for sig, graceful in ((signal.SIGINT, False), (signal.SIGTERM, True)):
        try:
            loop.add_signal_handler(sig, request_stop, graceful)
        except NotImplementedError:
            pass
    await stop.wait()
    if drain_requested:
        # SIGTERM = orchestrated shutdown: drain first (flush WAL, store
        # dirty docs under the deadline, 1012 the clients), then tear down
        await server.drain(args.drain_timeout_secs)
    await server.destroy()


def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    args = build_parser().parse_args()
    try:
        asyncio.run(run(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
