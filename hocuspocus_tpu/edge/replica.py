"""Hot-doc scale-out: follower cells + read-replica fan-out.

PR 13 made connection capacity an edge-replica count, but one owner per
doc means a single viral mega-doc (100k+ viewers, a handful of writers)
still saturates ONE cell's fan-out and catch-up path no matter how many
chips the fleet has. CRDT strong eventual convergence (Shapiro et al.)
makes read replication coordination-free: any cell holding a converged
copy of the doc can serve SyncStep2 catch-up and broadcast fan-out, and
state-based resync heals every delivery fault. This module is the cell
half of that subsystem (the edge half — audience watermark, follower
spread, promotion — lives in `gateway.py` + `router.py`):

- **A follower is an ordinary cell.** `ReplicaManager` keeps the
  follower's local `Document` converged by applying the owner's
  per-tick coalesced update stream (`REPLICA_TICK`, applied under
  `REPLICA_ORIGIN` so it can never echo back into a replication seam).
  Everything else — session ingress, the encode-once broadcast tick,
  the join-storm sync cache (naturally keyed per replica: each cell
  owns its own plane + serving cache), WAL gates, catch-up tiering —
  is the unmodified serving pipeline, which is the point: the read
  storm spreads across cells with zero new read-path code.

- **The owner keeps the write path.** Writers' updates ride the normal
  tick; the fanout's `replica_sink` seam hands each tick's local-origin
  updates to this manager, which streams ONE coalesced, seq-numbered
  `REPLICA_TICK` to every follower (plane-served docs deliver the same
  through the `on_plane_broadcast` window hook). A follower with local
  writers forwards them up as `REPLICA_PUSH`; the owner applies pushes
  under a replicable origin so the next tick re-streams them to every
  follower — including, idempotently, the pusher — and across the
  Redis instance boundary.

- **Gaps heal loudly, never silently.** Ticks are seq-numbered per doc.
  A follower seeing a gap counts a resync and re-FOLLOWs with its local
  state vector; the owner answers with the SV-diff plus its OWN state
  vector, and the follower pushes back anything the owner lacks — the
  symmetric exchange is what makes promotion lossless: whichever side
  has more state, one round trip converges both.

- **Bootstrap rides the PR-14 migration rail.** A cold follower's first
  FOLLOW gets the owner's full-state snapshot through the residency
  serving path (`replica_snapshot` — the eviction encode WITHOUT the
  evict), and the follower seeds its own arena via `adopt_snapshot` +
  `request_hydration`, exactly like a migration target, so replica
  serving is device-backed from the first frame it serves.

- **Promotion is an edge decision.** On owner death the gateway picks
  the freshest follower (digest-carried tick seqs, HRW tie-break),
  clears the doc's stale router entries (`CellRouter.promote`), and
  sends a FOLLOW hint naming the new owner to every survivor; the
  promoted cell flips role in place and the re-FOLLOW SV exchange
  merges any fresher follower state into it — zero acked-update loss,
  no client-visible disconnect (channels heal through the ordinary
  Auth + SyncStep1 handoff replay).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

from ..aio import spawn_tracked
from ..crdt import apply_update, encode_state_as_update, encode_state_vector
from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import Counter, Gauge
from ..protocol.sync import coalesce_updates
from ..server import logger
from ..server.hocuspocus import RequestInfo
from ..server.types import ConnectionConfiguration, REPLICA_ORIGIN
from . import relay

# Owner-side transaction origin for REPLICA_PUSH applies. Unlike
# REPLICA_ORIGIN these stay REPLICABLE: a follower's pushed writes must
# re-stream to every follower on the next tick and cross the Redis
# instance boundary like any local write. At a follower (stale-hint
# race, chained topologies) the same origin makes the apply forward UP
# through that follower's own push seam instead of dead-ending.
PUSH_ORIGIN = "__hocuspocus__replica_push__origin__"


class ReplicaManager:
    """Per-cell replication roles: which docs this cell OWNS (streams
    ticks for) and which it FOLLOWS (applies ticks for). One instance
    per `CellIngressExtension`; all sends ride the cell's pipelined
    relay lane."""

    def __init__(self, ext) -> None:
        self.ext = ext  # CellIngressExtension
        self.cell_id: str = ext.cell_id
        # doc -> {"seq": int, "followers": {cell_id: {"since": float}}}
        self.owned: "dict[str, dict]" = {}
        # doc -> {"owner": str, "last_seq": Optional[int], "synced":
        #         bool, "resyncing": bool, "last_tick_at": float}
        self.following: "dict[str, dict]" = {}
        # per-doc apply/bootstrap serialization: FIFO lock so envelope
        # handling (which may await document creation) stays in relay
        # order per doc
        self._locks: "dict[str, asyncio.Lock]" = {}
        self._tasks: set = set()
        self.counters = {
            "ticks_out": 0,
            "ticks_in": 0,
            "pushes_out": 0,
            "pushes_in": 0,
            "follows_in": 0,
            "bootstraps": 0,
            "resyncs": 0,
            "promotions": 0,
            "unfollows": 0,
        }
        self._metrics = (
            Gauge(
                "hocuspocus_replica_followers",
                "Follower cells subscribed to docs owned by this cell",
                fn=lambda: float(
                    sum(len(s["followers"]) for s in self.owned.values())
                ),
            ),
            Gauge(
                "hocuspocus_replica_following",
                "Docs this cell follows as a read replica",
                fn=lambda: float(len(self.following)),
            ),
            Gauge(
                "hocuspocus_replica_tick_lag_seconds",
                "Oldest time since a followed doc's last replica tick",
                fn=self._max_tick_lag,
            ),
            Counter(
                "hocuspocus_replica_ticks_total",
                "Replica tick envelopes, by direction",
            ),
            Counter(
                "hocuspocus_replica_resyncs_total",
                "Lost-tick state-vector resyncs initiated by this cell",
            ),
            Counter(
                "hocuspocus_replica_promotions_total",
                "Follower-to-owner promotions performed by this cell",
            ),
        )
        (
            self._m_followers,
            self._m_following,
            self._m_lag,
            self._m_ticks,
            self._m_resyncs,
            self._m_promotions,
        ) = self._metrics

    # -- wiring ---------------------------------------------------------------

    def metrics(self) -> tuple:
        return self._metrics

    def _max_tick_lag(self) -> float:
        now = time.monotonic()
        lags = [
            now - state["last_tick_at"] for state in self.following.values()
        ]
        return round(max(lags), 3) if lags else 0.0

    def _spawn(self, coro) -> None:
        spawn_tracked(self._tasks, coro)

    def _lock(self, doc_name: str) -> asyncio.Lock:
        lock = self._locks.get(doc_name)
        if lock is None:
            lock = self._locks[doc_name] = asyncio.Lock()
        return lock

    def _send(self, cell_id: str, kind: int, aux: str, payload: bytes = b"") -> None:
        self.ext.publish_to_cell(
            cell_id, relay.encode_envelope(kind, self.cell_id, aux, payload)
        )

    async def _ensure_document(self, doc_name: str):
        instance = self.ext.instance
        document = instance.documents.get(doc_name)
        if document is not None:
            return document
        return await instance.create_document(
            doc_name,
            RequestInfo(
                headers={"x-hocuspocus-replica": self.cell_id},
                url="/__replica__",
                remote=self.cell_id,
            ),
            f"replica:{self.cell_id}",
            ConnectionConfiguration(is_authenticated=True),
            {"replica": self.cell_id},
        )

    def _residency(self, doc_name: str):
        """The local residency manager covering `doc_name`, or None —
        duck-typed over the instance's merge extensions (multi-device
        `residency_for`, single-plane `plane.residency`)."""
        instance = self.ext.instance
        if instance is None:
            return None
        extensions = getattr(instance, "_extensions", None) or getattr(
            instance.configuration, "extensions", []
        )
        for extension in extensions:
            residency_for = getattr(extension, "residency_for", None)
            if callable(residency_for):
                try:
                    return residency_for(doc_name)
                except Exception:
                    return None
            plane = getattr(extension, "plane", None)
            residency = getattr(plane, "residency", None)
            if residency is not None:
                return residency
        return None

    def _attach_sink(self, doc_name: str, document) -> None:
        """Point the doc's fanout replication seam at this manager.
        Role-agnostic at attach time: the sink dispatches per the
        CURRENT role on every call, so a promotion flips behavior
        without re-wiring the fanout."""

        def sink(updates: list) -> None:
            self._on_tick_updates(doc_name, updates)

        document.fanout.replica_sink = sink

    def on_document_loaded(self, doc_name: str, document) -> None:
        """`after_load_document` seam: a doc this cell owns or follows
        was (re)loaded — a reload dropped the fanout seam with the old
        fanout, so re-attach."""
        if doc_name in self.owned or doc_name in self.following:
            self._attach_sink(doc_name, document)

    # -- tick sources ---------------------------------------------------------

    def _on_tick_updates(self, doc_name: str, updates: list) -> None:
        """One broadcast tick's replicable (local-origin) updates — from
        the fanout's `replica_sink` seam, or a plane window's merged
        cross-update via `on_plane_broadcast`."""
        if doc_name in self.owned:
            update = coalesce_updates(updates)
            # merge failure must not lose updates: per-update ticks
            payloads = [update] if update is not None else list(updates)
            for payload in payloads:
                self._stream_tick(doc_name, payload)
        elif doc_name in self.following:
            state = self.following[doc_name]
            update = coalesce_updates(updates)
            payloads = [update] if update is not None else list(updates)
            for payload in payloads:
                self._send(
                    state["owner"],
                    relay.REPLICA_PUSH,
                    relay.encode_replica_aux(d=doc_name),
                    payload,
                )
                self.counters["pushes_out"] += 1

    def on_plane_broadcast(self, doc_name: str, update: bytes) -> None:
        """Plane-served docs bypass the fanout tick; their merged window
        (already stripped of remote/replica-origin ops by the capture
        seam) arrives here instead."""
        if update:
            self._on_tick_updates(doc_name, [update])

    def _stream_tick(self, doc_name: str, payload: bytes) -> None:
        state = self.owned.get(doc_name)
        if not state or not state["followers"]:
            return
        state["seq"] += 1
        aux = relay.encode_replica_aux(d=doc_name, s=state["seq"])
        for follower_id in state["followers"]:
            self._send(follower_id, relay.REPLICA_TICK, aux, payload)
        self.counters["ticks_out"] += 1
        self._m_ticks.inc(direction="out")

    # -- relay dispatch -------------------------------------------------------

    def dispatch(self, kind: int, sender: str, aux_raw: str, payload: bytes) -> None:
        """Entry from the cell's `_on_message` for the four replica
        envelope kinds. `sender` is the envelope's session field: the
        peer cell id (or the edge id, for FOLLOW hints)."""
        aux = relay.decode_replica_aux(aux_raw)
        doc_name = str(aux.get("d") or "")
        if not doc_name:
            return
        if kind == relay.FOLLOW:
            owner = aux.get("o")
            if owner is not None:
                # edge routing hint: "this doc's owner is `o`"
                self._spawn(self._handle_owner_hint(doc_name, str(owner)))
            else:
                follower = str(aux.get("f") or "")
                if follower:
                    self._spawn(
                        self._handle_follow(doc_name, follower, aux.get("sv"))
                    )
        elif kind == relay.UNFOLLOW:
            follower = str(aux.get("f") or "") or sender
            state = self.owned.get(doc_name)
            if state is not None and state["followers"].pop(follower, None):
                self.counters["unfollows"] += 1
                get_flight_recorder().record(
                    "__replica__", "unfollow", doc=doc_name, follower=follower
                )
        elif kind == relay.REPLICA_TICK:
            self._spawn(self._handle_tick(doc_name, aux, payload))
        elif kind == relay.REPLICA_PUSH:
            self._spawn(self._handle_push(doc_name, payload))

    # -- owner side -----------------------------------------------------------

    async def _handle_follow(
        self, doc_name: str, follower_id: str, follower_sv: Optional[bytes]
    ) -> None:
        """A follower subscribed (or is resyncing after a gap). Reply
        with a REPLICA_TICK bootstrap: the SV-diff (or a full residency
        snapshot for a cold follower) plus our OWN state vector so the
        follower can push back anything we lack — the symmetric exchange
        behind the zero-acked-loss promotion guarantee."""
        async with self._lock(doc_name):
            try:
                document = await self._ensure_document(doc_name)
            except Exception as error:
                logger.log_error(
                    f"[replica] owner load of {doc_name!r} failed: {error!r}"
                )
                return
            state = self.owned.get(doc_name)
            if state is None:
                state = self.owned[doc_name] = {"seq": 0, "followers": {}}
            self._attach_sink(doc_name, document)
            state["followers"][follower_id] = {"since": time.monotonic()}
            self.counters["follows_in"] += 1
            # cold follower (empty/absent state vector): full-state
            # snapshot through the residency serving path, flagged so
            # the follower seeds its arena via adopt_snapshot
            cold = not follower_sv or len(follower_sv) <= 1
            payload = None
            bootstrap = False
            if cold:
                residency = self._residency(doc_name)
                if residency is not None:
                    try:
                        payload = residency.replica_snapshot(doc_name, document)
                        bootstrap = payload is not None
                    except Exception:
                        payload = None
            if payload is None and not cold:
                # warm follower: the plane serves the SV-diff (device
                # tombstone pack, no host serve-log walk) when healthy
                residency = self._residency(doc_name)
                if residency is not None:
                    try:
                        payload = residency.replica_catchup(
                            doc_name, document, follower_sv
                        )
                    except Exception:
                        payload = None
            if payload is None:
                try:
                    payload = encode_state_as_update(
                        document, follower_sv if not cold else None
                    )
                except Exception:
                    payload = encode_state_as_update(document)
            aux = relay.encode_replica_aux(
                d=doc_name,
                s=state["seq"],
                r=1,
                b=1 if bootstrap else None,
                sv=encode_state_vector(document),
            )
            self._send(follower_id, relay.REPLICA_TICK, aux, payload)
            self.counters["bootstraps"] += 1
            get_flight_recorder().record(
                "__replica__",
                "follow",
                doc=doc_name,
                follower=follower_id,
                seq=state["seq"],
                bootstrap=bootstrap,
            )

    async def _handle_push(self, doc_name: str, payload: bytes) -> None:
        """A follower forwarded its local writers' coalesced updates.
        Applied under the replicable push origin: the next tick streams
        them to every follower (idempotent at the pusher), and at a
        non-owner (stale hint race) the same origin forwards them up
        through OUR push seam instead of dead-ending."""
        if not payload:
            return
        async with self._lock(doc_name):
            try:
                document = await self._ensure_document(doc_name)
                apply_update(document, payload, PUSH_ORIGIN)
            except Exception as error:
                logger.log_error(
                    f"[replica] push apply on {doc_name!r} failed: {error!r}"
                )
                return
            self.counters["pushes_in"] += 1

    # -- follower side --------------------------------------------------------

    async def _ensure_following(self, doc_name: str, owner_id: str) -> None:
        state = self.following.get(doc_name)
        if (
            state is not None
            and state["owner"] == owner_id
            and not state.get("resyncing")
        ):
            return
        was_owner = self.owned.pop(doc_name, None)
        try:
            document = await self._ensure_document(doc_name)
        except Exception as error:
            logger.log_error(
                f"[replica] follower load of {doc_name!r} failed: {error!r}"
            )
            return
        self.following[doc_name] = {
            "owner": owner_id,
            "last_seq": None,
            "synced": False,
            "resyncing": True,  # cleared by the bootstrap reply
            "last_tick_at": time.monotonic(),
        }
        self._attach_sink(doc_name, document)
        self._send(
            owner_id,
            relay.FOLLOW,
            relay.encode_replica_aux(
                d=doc_name, f=self.cell_id, sv=encode_state_vector(document)
            ),
        )
        get_flight_recorder().record(
            "__replica__",
            "follow",
            doc=doc_name,
            owner=owner_id,
            demoted=was_owner is not None,
        )

    async def _handle_owner_hint(self, doc_name: str, owner_id: str) -> None:
        """An edge declared the doc's owner. Us: become (or stay) the
        owner — a follower flips role in place (promotion). Another
        cell: follow it."""
        async with self._lock(doc_name):
            if owner_id != self.cell_id:
                await self._ensure_following(doc_name, owner_id)
                return
            prior = self.following.pop(doc_name, None)
            if prior is not None:
                # promotion: role flips, the doc's state stays — every
                # surviving follower re-FOLLOWs us with its SV and the
                # symmetric exchange merges anything fresher
                self.counters["promotions"] += 1
                self._m_promotions.inc()
                # best-effort: the old owner is usually dead, but a
                # drained one is still listening
                self._send(
                    prior["owner"],
                    relay.UNFOLLOW,
                    relay.encode_replica_aux(d=doc_name, f=self.cell_id),
                )
                get_flight_recorder().record(
                    "__replica__",
                    "promoted",
                    doc=doc_name,
                    old_owner=prior["owner"],
                    last_seq=prior.get("last_seq"),
                )
            if doc_name not in self.owned:
                self.owned[doc_name] = {"seq": 0, "followers": {}}
                try:
                    document = await self._ensure_document(doc_name)
                    self._attach_sink(doc_name, document)
                except Exception:
                    pass

    async def _handle_tick(self, doc_name: str, aux: dict, payload: bytes) -> None:
        async with self._lock(doc_name):
            state = self.following.get(doc_name)
            if state is None:
                return  # stale tick after unfollow/promotion
            try:
                seq = int(aux.get("s", -1))
            except Exception:
                return
            resync = bool(aux.get("r"))
            try:
                document = await self._ensure_document(doc_name)
            except Exception as error:
                logger.log_error(
                    f"[replica] follower load of {doc_name!r} failed: {error!r}"
                )
                return
            if payload:
                try:
                    apply_update(document, payload, REPLICA_ORIGIN)
                except Exception as error:
                    logger.log_error(
                        f"[replica] tick apply on {doc_name!r} failed: "
                        f"{error!r}"
                    )
                    return
                if resync and aux.get("b"):
                    # bootstrap snapshot: seed the local arena through
                    # the migration rail so replica serving is
                    # device-backed from the first frame
                    residency = self._residency(doc_name)
                    if residency is not None:
                        try:
                            residency.adopt_snapshot(doc_name, payload)
                            residency.request_hydration(doc_name, document)
                        except Exception:
                            pass  # CPU-path serving still converges
            self.counters["ticks_in"] += 1
            self._m_ticks.inc(direction="in")
            state["last_tick_at"] = time.monotonic()
            if resync:
                state["last_seq"] = seq
                state["synced"] = True
                state["resyncing"] = False
                owner_sv = aux.get("sv")
                if owner_sv:
                    # symmetric exchange: push back anything we hold
                    # that the owner lacks (promotion's freshest-state
                    # merge and the write-through for follower-local
                    # edits made while partitioned)
                    try:
                        back = encode_state_as_update(document, owner_sv)
                    except Exception:
                        back = None
                    if back and len(back) > 2:
                        self._send(
                            state["owner"],
                            relay.REPLICA_PUSH,
                            relay.encode_replica_aux(d=doc_name),
                            back,
                        )
                        self.counters["pushes_out"] += 1
                return
            previous = state["last_seq"]
            gap = previous is None or seq != previous + 1
            state["last_seq"] = seq
            if gap and not state.get("resyncing"):
                # lost tick: heal via the SV resync exchange — loudly
                # (counted + recorded), never silently
                state["resyncing"] = True
                self.counters["resyncs"] += 1
                self._m_resyncs.inc()
                get_flight_recorder().record(
                    "__replica__",
                    "lag_resync",
                    doc=doc_name,
                    expected=(previous + 1) if previous is not None else 0,
                    got=seq,
                )
                self._send(
                    state["owner"],
                    relay.FOLLOW,
                    relay.encode_replica_aux(
                        d=doc_name,
                        f=self.cell_id,
                        sv=encode_state_vector(document),
                    ),
                )

    # -- peer lifecycle -------------------------------------------------------

    def on_peer_down(self, cell_id: str) -> None:
        """A peer cell left (CELL_DOWN / CELL_DRAINING): drop it from
        every follower set. Docs we FOLLOW from it keep serving their
        last converged state — reads stay available through owner death
        — until the edge's promotion hint re-homes them."""
        for doc_name, state in self.owned.items():
            if state["followers"].pop(cell_id, None):
                get_flight_recorder().record(
                    "__replica__", "unfollow", doc=doc_name, follower=cell_id
                )
        for state in self.following.values():
            if state["owner"] == cell_id:
                state["synced"] = False

    def close(self) -> None:
        """Cell teardown: tell every owner we follow that we're gone
        (best-effort — owners also clean up on our CELL_DOWN)."""
        for doc_name, state in self.following.items():
            self._send(
                state["owner"],
                relay.UNFOLLOW,
                relay.encode_replica_aux(d=doc_name, f=self.cell_id),
            )
        self.following.clear()
        self.owned.clear()
        for task in list(self._tasks):
            task.cancel()
        self._tasks.clear()

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        """Digest + /debug payload: the replication topology as this
        cell sees it (fleet digests carry this under "replica")."""
        now = time.monotonic()
        return {
            "owned": {
                doc: {
                    "seq": state["seq"],
                    "followers": sorted(state["followers"]),
                }
                for doc, state in sorted(self.owned.items())
            },
            "following": {
                doc: {
                    "owner": state["owner"],
                    "seq": state["last_seq"],
                    "synced": state["synced"],
                    "lag_s": round(now - state["last_tick_at"], 3),
                }
                for doc, state in sorted(self.following.items())
            },
            "counters": dict(self.counters),
        }
