"""Cell-side edge-session ingress.

A merge cell is an ordinary server (planes, WAL, overload ladder — the
whole stack) whose clients arrive over the relay lane instead of
websockets. `CellIngressExtension` subscribes to the cell's relay
channel and turns each OPEN envelope into a real session through
`Hocuspocus.handle_connection`: the same `ClientConnection` auth
handshake, the same per-doc `Connection`s, and — the point — the same
`DocumentFanout`, so the PR-7 encode-once broadcast tick serves edge
sessions as plain audience members (one merged frame, one audience
snapshot, catch-up tiering for a slow edge, WAL delivery gates intact).

Outbound frames ride a `CallbackWebSocketTransport` whose writer
enqueues onto the pipelined RESP client — N frames in one event-loop
tick leave as ONE write+drain, the PR-8 lane economics applied to the
edge hop.

Lifecycle on the control channel: `CELL_UP` announces (and re-announces
on a heartbeat cadence — the router's liveness signal), the PR-9
graceful drain fires the new `on_drain` hook which announces
`CELL_DRAINING` BEFORE stores begin (edges remap and re-establish while
the old cell is still flushing), and `on_destroy` announces
`CELL_DOWN`.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

import json
import time

from ..aio import spawn_tracked
from ..net.resp import PipelinedRedisClient, RedisSubscriber
from ..observability.costs import get_cost_ledger
from ..observability.fleet import build_digest, get_fleet_view
from ..observability.flight_recorder import get_flight_recorder
from ..observability.tracing import get_tracer
from ..server import logger
from ..server.hocuspocus import RequestInfo
from ..server.transports import CallbackWebSocketTransport
from ..server.types import Extension, Payload
from ..fleet.roster import PeerRoster, qualify_cell_id
from . import relay
from .relay import DEFAULT_PREFIX
from .replica import ReplicaManager


class _CellEdgeSession:
    """One relay session: a synthetic transport + the real server-side
    session pipeline, with an ordered inbound pump (frames must apply
    in relay order or the auth/sync handshake interleaves)."""

    def __init__(
        self, ext: "CellIngressExtension", session_id: str, edge_id: str, aux: dict
    ) -> None:
        self.ext = ext
        self.session_id = session_id
        self.edge_id = edge_id
        self._closed = False
        # (payload, fleet trace context or None) — the context must ride
        # the queue so the pump can scope it to exactly its frame
        self._queue: "asyncio.Queue[Optional[tuple]]" = asyncio.Queue()
        headers = {"x-hocuspocus-edge": edge_id}
        context: dict = {"edge": edge_id}
        tenant = aux.get("tenant")
        if tenant:
            headers["x-tenant"] = str(tenant)
            context["tenant"] = str(tenant)
        self.transport = CallbackWebSocketTransport(
            send_async=self._send_to_edge,
            close_async=self._closed_by_server,
        )
        self.client = ext.instance.handle_connection(
            self.transport,
            RequestInfo(headers=headers, url="/__edge__", remote=edge_id),
            context,
        )
        self._pump_task = asyncio.ensure_future(self._pump())

    # -- inbound (edge -> cell) --------------------------------------------

    def feed(self, payload: bytes, trace_ctx: Optional[dict] = None) -> None:
        if not self._closed:
            self._queue.put_nowait((payload, trace_ctx))

    async def _pump(self) -> None:
        tracer = get_tracer()
        while True:
            item = await self._queue.get()
            if item is None:
                return
            payload, trace_ctx = item
            try:
                if trace_ctx is not None:
                    # cross-tier trace context (edge-sampled): visible
                    # to UpdateTraceBook.stamp for exactly this dispatch
                    tracer.fleet_context = trace_ctx
                try:
                    await self.client.handle_message(payload)
                finally:
                    if trace_ctx is not None:
                        tracer.fleet_context = None
            except Exception as error:
                logger.log_error(
                    f"[edge-cell] session {self.session_id} frame failed: {error!r}"
                )
                self.close(1011, "internal error")
                return

    def detach(self, document_name: str) -> None:
        """Close ONE doc channel (the edge remapped it elsewhere); the
        rest of the session keeps flowing."""
        connection = self.client.document_connections.get(document_name)
        if connection is not None:
            connection.close()

    # -- outbound (cell -> edge) -------------------------------------------

    async def _send_to_edge(self, data: bytes) -> None:
        # zero-copy: the broadcast frame (encode-once, shared by the
        # whole audience) rides as a memoryview segment — the pipelined
        # publish lane joins header+payload straight into the socket
        # write, so the frame bytes are copied exactly once
        self.ext.publish_to_edge(
            self.edge_id,
            relay.encode_envelope_view(relay.FRAME, self.session_id, "", data),
        )
        self.ext.counters["frames_out"] += 1

    async def _closed_by_server(self, code: int, reason: str) -> None:
        """The server side closed the session (drain 1012, overflow
        1013, destroy): tell the edge so it can re-establish on another
        cell instead of waiting on a dead channel."""
        self.ext.publish_to_edge(
            self.edge_id,
            relay.encode_envelope(
                relay.CLOSED, self.session_id, f"{code}:{reason}"
            ),
        )
        self._finish(code, reason)

    # -- teardown ----------------------------------------------------------

    def close(self, code: int = 1000, reason: str = "edge closed") -> None:
        self.transport.abort()
        self._finish(code, reason)

    def _finish(self, code: int, reason: str) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put_nowait(None)
        self.ext.sessions.pop(self.session_id, None)
        spawn_tracked(
            self.ext._tasks, self.client.handle_transport_close(code, reason)
        )


class CellIngressExtension(Extension):
    """Makes this server a merge cell: relay-session ingress + the
    control-channel lifecycle (announce/heartbeat/drain/down)."""

    # before ordinary extensions so the announce machinery configures
    # early, after Metrics (1000) so telemetry is lit first
    priority = 950

    def __init__(
        self,
        cell_id: str,
        host: str = "127.0.0.1",
        port: int = 6379,
        prefix: str = DEFAULT_PREFIX,
        create_client: Optional[Any] = None,
        create_subscriber: Optional[Any] = None,
        announce_interval_s: float = 2.0,
        host_id: Optional[str] = None,
    ) -> None:
        # cross-host fleets (fleet/roster.py): a host qualifier turns
        # the cell id into "host/cell" — rendezvous hashes the full
        # string, so qualified cells are first-class placement targets
        # and edges can tell foreign announcers from local ones
        self.cell_id = qualify_cell_id(host_id, cell_id)
        self.host_id = host_id
        self.prefix = prefix
        self.announce_interval_s = announce_interval_s
        self.instance = None
        self.draining = False
        self.sessions: "dict[str, _CellEdgeSession]" = {}
        self.counters = {
            "sessions_opened": 0,
            "sessions_closed": 0,
            "frames_in": 0,
            "frames_out": 0,
            "detaches": 0,
            "refused_draining": 0,
            "trace_returns_sent": 0,
        }
        self._tasks: set = set()
        # fleet-membership mirror: every control-channel lifecycle
        # transition (our own announce echo included — all subscribers
        # count the same stream) bumps roster.epoch, published in the
        # digest so /debug/fleet can flag cell-vs-cell roster skew
        self.roster = PeerRoster()
        # hot-doc replication roles (edge/replica.py): which docs this
        # cell owns (streams ticks for) vs follows (applies ticks for)
        self.replicas = ReplicaManager(self)
        self._announce_handle: Optional[asyncio.TimerHandle] = None
        # cross-tier trace-return drain: deposits may land from the
        # flush executor thread, so the wake-up crosses via
        # call_soon_threadsafe onto the loop captured at listen time
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._trace_flush_scheduled = False
        if create_client is not None:
            self.pub = create_client()
        else:
            self.pub = PipelinedRedisClient(host, port)
        if create_subscriber is not None:
            self.sub = create_subscriber(self._on_message)
        else:
            self.sub = RedisSubscriber(host, port, on_message=self._on_message)

    # -- wiring -------------------------------------------------------------

    def _publish(self, channel: str, envelope) -> None:
        """Publish one envelope (bytes, or a zero-copy segment list from
        `relay.encode_envelope_view`), preferring the pipelined
        enqueue-only path (per-tick coalesced lane) over a spawned
        await."""
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            nowait(channel, envelope)
        else:
            if isinstance(envelope, (list, tuple)):
                envelope = b"".join(envelope)
            spawn_tracked(self._tasks, self.pub.publish(channel, envelope))

    def publish_to_edge(self, edge_id: str, envelope) -> None:
        self._publish(relay.edge_channel(self.prefix, edge_id), envelope)

    def publish_to_cell(self, cell_id: str, envelope) -> None:
        """Cell → cell (the replica lane: FOLLOW/REPLICA_TICK/…)."""
        self._publish(relay.cell_channel(self.prefix, cell_id), envelope)

    def _announce(self, kind: int) -> None:
        self._publish(
            relay.control_channel(self.prefix),
            relay.encode_envelope(kind, self.cell_id),
        )

    def _schedule_announce(self) -> None:
        if self.draining:
            return
        loop = asyncio.get_event_loop()
        self._announce_handle = loop.call_later(
            self.announce_interval_s, self._heartbeat
        )

    def _heartbeat(self) -> None:
        self._announce_handle = None
        if self.draining:
            return
        self._announce(relay.CELL_UP)
        self._publish_digest()
        self._schedule_announce()

    def _publish_digest(self) -> None:
        """Telemetry federation (docs/guides/observability.md fleet
        view): one compact digest per heartbeat on the control channel,
        ingested locally too so this cell's own /debug/fleet includes
        itself. Gated on the fleet view being lit (by Metrics) — like
        every other collector, zero cost until observability is on."""
        view = get_fleet_view()
        if not view.enabled:
            return
        try:
            digest = build_digest(
                role="cell",
                node_id=self.cell_id,
                instance=self.instance,
                interval_s=self.announce_interval_s,
                extra={
                    "cell": {
                        "cell_id": self.cell_id,
                        "draining": self.draining,
                        "edge_sessions": len(self.sessions),
                    },
                    # dynamic-roster epoch: cells that watched the same
                    # control stream agree; divergence IS the skew
                    # /debug/fleet flags for the cell role
                    "roster_epoch": self.roster.epoch,
                    # replication topology: per-doc follower sets +
                    # tick seqs — edges harvest the seqs to pick the
                    # FRESHEST follower at promotion time, /debug/fleet
                    # renders the followers column off the same key
                    "replica": self.replicas.stats(),
                },
            )
        except Exception:
            return  # a digest must never fail the heartbeat
        view.ingest(digest)
        self._publish(
            relay.control_channel(self.prefix),
            relay.encode_envelope(
                relay.DIGEST,
                self.cell_id,
                "",
                json.dumps(digest, separators=(",", ":")).encode(),
            ),
        )

    # -- hooks ---------------------------------------------------------------

    async def on_configure(self, data: Payload) -> None:
        self.instance = data.instance
        # fleet identity: debug payload headers + cross-tier span lanes
        get_fleet_view().set_identity("cell", self.cell_id)
        # hocuspocus_replica_* metrics: adopted by a co-installed
        # Metrics extension's registry (same pattern as the edge's
        # hocuspocus_edge_* family)
        for extension in getattr(data.instance, "_extensions", []):
            registry = getattr(extension, "registry", None)
            if registry is not None and callable(
                getattr(registry, "register", None)
            ):
                for metric in self.replicas.metrics():
                    try:
                        registry.register(metric)
                    except ValueError:
                        pass  # already adopted (shared registry)
                break
        # pin THIS cell's id onto its planes' trace books: the
        # process-global identity is last-writer, so in a multi-cell
        # process the deposit-site fallback would attribute every
        # trace to whichever role configured last (the edge picks its
        # clock-offset estimator by this id). Supervised planes whose
        # runtime attaches later fall back to the process identity.
        extensions = getattr(data.instance, "_extensions", None) or getattr(
            data.instance.configuration, "extensions", []
        )
        for ext in extensions:
            planes = []
            plane = getattr(ext, "plane", None)
            if plane is not None:
                planes.append(plane)
            for shard in getattr(ext, "shards", None) or ():
                planes.append(shard.plane)
            for plane in planes:
                book = getattr(plane, "update_traces", None)
                if book is not None:
                    book.node_id = self.cell_id

    async def on_listen(self, data: Payload) -> None:
        await self.sub.subscribe(relay.cell_channel(self.prefix, self.cell_id))
        # the control channel too: peer digests (and peer lifecycle)
        # feed this cell's own FleetView, so /debug/fleet answers the
        # same on every role
        await self.sub.subscribe(relay.control_channel(self.prefix))
        # cross-tier trace returns: the trace book deposits a return
        # context when a traced relayed update closes; this cell ships
        # them back to the stamping edge as TRACE_RET envelopes
        self._loop = asyncio.get_running_loop()
        get_fleet_view().trace_returns.add_waker(self._wake_trace_flush)
        self._announce(relay.CELL_UP)
        self._publish_digest()
        self._schedule_announce()
        get_flight_recorder().record("__edge__", "cell_up", cell=self.cell_id)

    # -- cross-tier trace returns -------------------------------------------

    def _wake_trace_flush(self) -> None:
        """Outbox deposit seam — may fire on the flush executor thread,
        so the actual drain hops onto the event loop. The scheduled
        flag is a benign race: worst case two wakes drain once."""
        if self._trace_flush_scheduled or self._loop is None:
            return
        self._trace_flush_scheduled = True
        try:
            self._loop.call_soon_threadsafe(self._flush_trace_returns)
        except RuntimeError:
            self._trace_flush_scheduled = False  # loop already closed

    def _flush_trace_returns(self) -> None:
        self._trace_flush_scheduled = False
        by_edge: "dict[str, list]" = {}
        for _doc, contexts in get_fleet_view().trace_returns.take_all().items():
            for context in contexts:
                edge_id = str(context.get("e", ""))
                if edge_id:
                    by_edge.setdefault(edge_id, []).append(context)
        for edge_id, contexts in by_edge.items():
            self.publish_to_edge(
                edge_id,
                relay.encode_envelope(
                    relay.TRACE_RET,
                    self.cell_id,
                    relay.encode_trace_aux({"r": contexts}),
                ),
            )
            self.counters["trace_returns_sent"] += len(contexts)

    async def on_drain(self, data: Payload) -> None:
        """PR-9 graceful drain announces departure FIRST: edges remap
        this cell's docs and re-establish sessions elsewhere while the
        stores below are still flushing (the handoff half of the drain
        contract — docs/guides/edge-routing.md)."""
        self.draining = True
        if self._announce_handle is not None:
            self._announce_handle.cancel()
            self._announce_handle = None
        self._announce(relay.CELL_DRAINING)
        get_flight_recorder().record("__edge__", "cell_draining", cell=self.cell_id)
        # give the announcement its flush tick before stores monopolize
        # the loop (publish_nowait ships on the next tick)
        await asyncio.sleep(0)

    async def after_load_document(self, data: Payload) -> None:
        # a doc this cell owns/follows was (re)loaded: the fresh fanout
        # has no replica seam yet — re-attach before its first tick
        self.replicas.on_document_loaded(data.document_name, data.document)

    async def on_plane_broadcast(self, data: Payload) -> None:
        """Plane-served docs bypass the fanout tick; the merged window
        (remote/replica-origin ops already stripped) feeds the replica
        lane here — owner ticks it to followers, a follower pushes it
        up to its owner."""
        self.replicas.on_plane_broadcast(data.document_name, data.update)

    async def on_destroy(self, data: Payload) -> None:
        self.replicas.close()
        if self._announce_handle is not None:
            self._announce_handle.cancel()
            self._announce_handle = None
        get_fleet_view().trace_returns.remove_waker(self._wake_trace_flush)
        self._announce(relay.CELL_DOWN)
        for session in list(self.sessions.values()):
            session.close(1001, "cell shutdown")
        # bounded: let the CELL_DOWN/CLOSED envelopes flush before the
        # lane closes (peers heal via re-route even if this races)
        flush_task = getattr(self.pub, "_flush_task", None)
        if flush_task is not None and not flush_task.done():
            try:
                await asyncio.wait_for(asyncio.shield(flush_task), timeout=0.5)
            except Exception:
                pass
        self.pub.close()
        self.sub.close()

    def health_status(self) -> dict:
        return {
            "state": "draining" if self.draining else "serving",
            "degraded": False,
            "cell_id": self.cell_id,
            "edge_sessions": len(self.sessions),
            "replica_owned": len(self.replicas.owned),
            "replica_following": len(self.replicas.following),
        }

    # -- relay dispatch ------------------------------------------------------

    def _on_message(self, channel: bytes, data: bytes) -> None:
        try:
            t0 = time.perf_counter_ns()
            kind, session_id, aux, payload = relay.decode_envelope(data)
        except Exception:
            return  # malformed envelope: nothing safe to act on
        ledger = get_cost_ledger()
        if ledger.enabled:
            ledger.record(
                "envelope_decode", "Relay", time.perf_counter_ns() - t0, len(data)
            )
        if kind == relay.PING:
            # clock-offset probe (cross-tier tracing): echo the edge's
            # stamp plus our own clock, immediately — any queueing here
            # inflates the RTT and widens the edge's offset bound
            try:
                t_sent = float(json.loads(aux).get("t"))
            except Exception:
                return
            self.publish_to_edge(
                session_id,  # the pinging edge's id rides the session field
                relay.encode_envelope(
                    relay.PONG,
                    self.cell_id,
                    json.dumps(
                        {"t": t_sent, "tc": time.perf_counter()},
                        separators=(",", ":"),
                    ),
                ),
            )
            return
        if kind == relay.DIGEST:
            # a peer's telemetry digest off the control channel
            view = get_fleet_view()
            if view.enabled and session_id != self.cell_id:
                try:
                    view.ingest(json.loads(payload))
                except Exception:
                    pass
            return
        if kind == relay.CELL_DOWN:
            self.roster.note(session_id, "down")
            if session_id != self.cell_id:
                get_fleet_view().mark_down(session_id)
                self.replicas.on_peer_down(session_id)
            return
        if kind in (relay.CELL_UP, relay.CELL_DRAINING):
            # fold the membership transition into the roster mirror
            # (heartbeat re-announces are no-ops; only real transitions
            # bump the epoch) — routing stays the edges' job
            self.roster.note(
                session_id,
                "healthy" if kind == relay.CELL_UP else "draining",
            )
            if kind == relay.CELL_DRAINING and session_id != self.cell_id:
                # a draining peer stops serving its follower role
                self.replicas.on_peer_down(session_id)
            return
        if kind in (
            relay.FOLLOW,
            relay.UNFOLLOW,
            relay.REPLICA_TICK,
            relay.REPLICA_PUSH,
        ):
            # hot-doc replication lane (edge/replica.py): the sender's
            # id — peer cell, or the edge for FOLLOW hints — rides the
            # session field
            self.replicas.dispatch(kind, session_id, aux, payload)
            return
        if kind == relay.OPEN:
            if self.draining:
                # stale route: the edge hasn't seen CELL_DRAINING yet —
                # answer CLOSED so it re-routes instead of waiting
                self.counters["refused_draining"] += 1
                self.publish_to_edge(
                    relay.decode_open_aux(aux).get("edge", ""),
                    relay.encode_envelope(
                        relay.CLOSED, session_id, "1012:draining"
                    ),
                )
                return
            if session_id in self.sessions:
                return  # duplicate OPEN (edge retry): session exists
            open_aux = relay.decode_open_aux(aux)
            edge_id = str(open_aux.get("edge", ""))
            if not edge_id:
                return
            self.counters["sessions_opened"] += 1
            self.sessions[session_id] = _CellEdgeSession(
                self, session_id, edge_id, open_aux
            )
            return
        session = self.sessions.get(session_id)
        if session is None:
            return  # frames for a session that never opened / already died
        if kind == relay.FRAME:
            self.counters["frames_in"] += 1
            # optional versioned trace-context aux (edge-sampled update):
            # absent/foreign aux decodes to None and the frame relays
            # exactly as before — old envelopes keep parsing
            trace_ctx = relay.decode_trace_aux(aux) if aux else None
            session.feed(payload, trace_ctx)
        elif kind == relay.DETACH:
            self.counters["detaches"] += 1
            session.detach(aux)
        elif kind == relay.CLOSE:
            self.counters["sessions_closed"] += 1
            session.close(1000, "edge closed")
