"""Cell-side edge-session ingress.

A merge cell is an ordinary server (planes, WAL, overload ladder — the
whole stack) whose clients arrive over the relay lane instead of
websockets. `CellIngressExtension` subscribes to the cell's relay
channel and turns each OPEN envelope into a real session through
`Hocuspocus.handle_connection`: the same `ClientConnection` auth
handshake, the same per-doc `Connection`s, and — the point — the same
`DocumentFanout`, so the PR-7 encode-once broadcast tick serves edge
sessions as plain audience members (one merged frame, one audience
snapshot, catch-up tiering for a slow edge, WAL delivery gates intact).

Outbound frames ride a `CallbackWebSocketTransport` whose writer
enqueues onto the pipelined RESP client — N frames in one event-loop
tick leave as ONE write+drain, the PR-8 lane economics applied to the
edge hop.

Lifecycle on the control channel: `CELL_UP` announces (and re-announces
on a heartbeat cadence — the router's liveness signal), the PR-9
graceful drain fires the new `on_drain` hook which announces
`CELL_DRAINING` BEFORE stores begin (edges remap and re-establish while
the old cell is still flushing), and `on_destroy` announces
`CELL_DOWN`.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from ..aio import spawn_tracked
from ..net.resp import PipelinedRedisClient, RedisSubscriber
from ..observability.flight_recorder import get_flight_recorder
from ..server import logger
from ..server.hocuspocus import RequestInfo
from ..server.transports import CallbackWebSocketTransport
from ..server.types import Extension, Payload
from . import relay
from .relay import DEFAULT_PREFIX


class _CellEdgeSession:
    """One relay session: a synthetic transport + the real server-side
    session pipeline, with an ordered inbound pump (frames must apply
    in relay order or the auth/sync handshake interleaves)."""

    def __init__(
        self, ext: "CellIngressExtension", session_id: str, edge_id: str, aux: dict
    ) -> None:
        self.ext = ext
        self.session_id = session_id
        self.edge_id = edge_id
        self._closed = False
        self._queue: "asyncio.Queue[Optional[bytes]]" = asyncio.Queue()
        headers = {"x-hocuspocus-edge": edge_id}
        context: dict = {"edge": edge_id}
        tenant = aux.get("tenant")
        if tenant:
            headers["x-tenant"] = str(tenant)
            context["tenant"] = str(tenant)
        self.transport = CallbackWebSocketTransport(
            send_async=self._send_to_edge,
            close_async=self._closed_by_server,
        )
        self.client = ext.instance.handle_connection(
            self.transport,
            RequestInfo(headers=headers, url="/__edge__", remote=edge_id),
            context,
        )
        self._pump_task = asyncio.ensure_future(self._pump())

    # -- inbound (edge -> cell) --------------------------------------------

    def feed(self, payload: bytes) -> None:
        if not self._closed:
            self._queue.put_nowait(payload)

    async def _pump(self) -> None:
        while True:
            payload = await self._queue.get()
            if payload is None:
                return
            try:
                await self.client.handle_message(payload)
            except Exception as error:
                logger.log_error(
                    f"[edge-cell] session {self.session_id} frame failed: {error!r}"
                )
                self.close(1011, "internal error")
                return

    def detach(self, document_name: str) -> None:
        """Close ONE doc channel (the edge remapped it elsewhere); the
        rest of the session keeps flowing."""
        connection = self.client.document_connections.get(document_name)
        if connection is not None:
            connection.close()

    # -- outbound (cell -> edge) -------------------------------------------

    async def _send_to_edge(self, data: bytes) -> None:
        self.ext.publish_to_edge(
            self.edge_id, relay.encode_envelope(relay.FRAME, self.session_id, "", data)
        )
        self.ext.counters["frames_out"] += 1

    async def _closed_by_server(self, code: int, reason: str) -> None:
        """The server side closed the session (drain 1012, overflow
        1013, destroy): tell the edge so it can re-establish on another
        cell instead of waiting on a dead channel."""
        self.ext.publish_to_edge(
            self.edge_id,
            relay.encode_envelope(
                relay.CLOSED, self.session_id, f"{code}:{reason}"
            ),
        )
        self._finish(code, reason)

    # -- teardown ----------------------------------------------------------

    def close(self, code: int = 1000, reason: str = "edge closed") -> None:
        self.transport.abort()
        self._finish(code, reason)

    def _finish(self, code: int, reason: str) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put_nowait(None)
        self.ext.sessions.pop(self.session_id, None)
        spawn_tracked(
            self.ext._tasks, self.client.handle_transport_close(code, reason)
        )


class CellIngressExtension(Extension):
    """Makes this server a merge cell: relay-session ingress + the
    control-channel lifecycle (announce/heartbeat/drain/down)."""

    # before ordinary extensions so the announce machinery configures
    # early, after Metrics (1000) so telemetry is lit first
    priority = 950

    def __init__(
        self,
        cell_id: str,
        host: str = "127.0.0.1",
        port: int = 6379,
        prefix: str = DEFAULT_PREFIX,
        create_client: Optional[Any] = None,
        create_subscriber: Optional[Any] = None,
        announce_interval_s: float = 2.0,
    ) -> None:
        self.cell_id = cell_id
        self.prefix = prefix
        self.announce_interval_s = announce_interval_s
        self.instance = None
        self.draining = False
        self.sessions: "dict[str, _CellEdgeSession]" = {}
        self.counters = {
            "sessions_opened": 0,
            "sessions_closed": 0,
            "frames_in": 0,
            "frames_out": 0,
            "detaches": 0,
            "refused_draining": 0,
        }
        self._tasks: set = set()
        self._announce_handle: Optional[asyncio.TimerHandle] = None
        if create_client is not None:
            self.pub = create_client()
        else:
            self.pub = PipelinedRedisClient(host, port)
        if create_subscriber is not None:
            self.sub = create_subscriber(self._on_message)
        else:
            self.sub = RedisSubscriber(host, port, on_message=self._on_message)

    # -- wiring -------------------------------------------------------------

    def publish_to_edge(self, edge_id: str, envelope: bytes) -> None:
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            nowait(relay.edge_channel(self.prefix, edge_id), envelope)
        else:
            spawn_tracked(
                self._tasks,
                self.pub.publish(relay.edge_channel(self.prefix, edge_id), envelope),
            )

    def _announce(self, kind: int) -> None:
        envelope = relay.encode_envelope(kind, self.cell_id)
        channel = relay.control_channel(self.prefix)
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            nowait(channel, envelope)
        else:
            spawn_tracked(self._tasks, self.pub.publish(channel, envelope))

    def _schedule_announce(self) -> None:
        if self.draining:
            return
        loop = asyncio.get_event_loop()
        self._announce_handle = loop.call_later(
            self.announce_interval_s, self._heartbeat
        )

    def _heartbeat(self) -> None:
        self._announce_handle = None
        if self.draining:
            return
        self._announce(relay.CELL_UP)
        self._schedule_announce()

    # -- hooks ---------------------------------------------------------------

    async def on_configure(self, data: Payload) -> None:
        self.instance = data.instance

    async def on_listen(self, data: Payload) -> None:
        await self.sub.subscribe(relay.cell_channel(self.prefix, self.cell_id))
        self._announce(relay.CELL_UP)
        self._schedule_announce()
        get_flight_recorder().record("__edge__", "cell_up", cell=self.cell_id)

    async def on_drain(self, data: Payload) -> None:
        """PR-9 graceful drain announces departure FIRST: edges remap
        this cell's docs and re-establish sessions elsewhere while the
        stores below are still flushing (the handoff half of the drain
        contract — docs/guides/edge-routing.md)."""
        self.draining = True
        if self._announce_handle is not None:
            self._announce_handle.cancel()
            self._announce_handle = None
        self._announce(relay.CELL_DRAINING)
        get_flight_recorder().record("__edge__", "cell_draining", cell=self.cell_id)
        # give the announcement its flush tick before stores monopolize
        # the loop (publish_nowait ships on the next tick)
        await asyncio.sleep(0)

    async def on_destroy(self, data: Payload) -> None:
        if self._announce_handle is not None:
            self._announce_handle.cancel()
            self._announce_handle = None
        self._announce(relay.CELL_DOWN)
        for session in list(self.sessions.values()):
            session.close(1001, "cell shutdown")
        # bounded: let the CELL_DOWN/CLOSED envelopes flush before the
        # lane closes (peers heal via re-route even if this races)
        flush_task = getattr(self.pub, "_flush_task", None)
        if flush_task is not None and not flush_task.done():
            try:
                await asyncio.wait_for(asyncio.shield(flush_task), timeout=0.5)
            except Exception:
                pass
        self.pub.close()
        self.sub.close()

    def health_status(self) -> dict:
        return {
            "state": "draining" if self.draining else "serving",
            "degraded": False,
            "cell_id": self.cell_id,
            "edge_sessions": len(self.sessions),
        }

    # -- relay dispatch ------------------------------------------------------

    def _on_message(self, channel: bytes, data: bytes) -> None:
        try:
            kind, session_id, aux, payload = relay.decode_envelope(data)
        except Exception:
            return  # malformed envelope: nothing safe to act on
        if kind == relay.OPEN:
            if self.draining:
                # stale route: the edge hasn't seen CELL_DRAINING yet —
                # answer CLOSED so it re-routes instead of waiting
                self.counters["refused_draining"] += 1
                self.publish_to_edge(
                    relay.decode_open_aux(aux).get("edge", ""),
                    relay.encode_envelope(
                        relay.CLOSED, session_id, "1012:draining"
                    ),
                )
                return
            if session_id in self.sessions:
                return  # duplicate OPEN (edge retry): session exists
            open_aux = relay.decode_open_aux(aux)
            edge_id = str(open_aux.get("edge", ""))
            if not edge_id:
                return
            self.counters["sessions_opened"] += 1
            self.sessions[session_id] = _CellEdgeSession(
                self, session_id, edge_id, open_aux
            )
            return
        session = self.sessions.get(session_id)
        if session is None:
            return  # frames for a session that never opened / already died
        if kind == relay.FRAME:
            self.counters["frames_in"] += 1
            session.feed(payload)
        elif kind == relay.DETACH:
            self.counters["detaches"] += 1
            session.detach(aux)
        elif kind == relay.CLOSE:
            self.counters["sessions_closed"] += 1
            session.close(1000, "edge closed")
