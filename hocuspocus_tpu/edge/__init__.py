"""Stateless websocket edge tier + cell router (docs/guides/edge-routing.md).

Splits connection termination from merge capacity: `EdgeServer`
terminates websockets, authenticates and admits at the door, and relays
each document's frames to its owning merge cell over the pipelined RESP
lane; `CellIngressExtension` makes any server a cell whose edge
sessions ride the normal `Connection`/`DocumentFanout` pipeline; the
`CellRouter` (rendezvous hashing + override table + health states)
decides placement, and graceful drain hands a cell's docs off with a
transparent SyncStep1 resync — "millions of users" becomes an
edge-replica count.

Hot docs scale past one cell too: when a doc's audience crosses the
replica watermark the router grows an owner + follower placement, the
`ReplicaManager` on each cell keeps follower copies converged off the
owner's seq-numbered tick stream, and the edge spreads the read storm
across the whole set (docs/guides/hot-doc-replication.md).
"""

from .cell import CellIngressExtension
from .gateway import EdgeClientSession, EdgeGateway
from .replica import ReplicaManager
from .router import CellRouter
from .server import EdgeGatewayExtension, EdgeServer
from . import relay

__all__ = [
    "CellIngressExtension",
    "CellRouter",
    "EdgeClientSession",
    "EdgeGateway",
    "EdgeGatewayExtension",
    "EdgeServer",
    "ReplicaManager",
    "relay",
]
