"""The edge process role: `EdgeServer` + `EdgeGatewayExtension`.

An edge is a normal aiohttp host (same upgrade path, same drain/RED
503s through `service_unavailable_response`, same `/healthz`,
`/metrics` and hook chain) whose websocket sessions are
`EdgeClientSession`s instead of document-owning `ClientConnection`s —
the `Server._create_session` seam is the only server-layer difference
between the roles. Run one per front-door replica:

    gateway_ext = EdgeGatewayExtension(host=redis_host, port=redis_port)
    server = EdgeServer(Configuration(extensions=[
        Metrics(), OverloadExtension(), gateway_ext,
    ]))
    await server.listen(port=80)

`/debug/edge` serves the live route table, session registry and relay
counters (docs/guides/edge-routing.md).
"""

from __future__ import annotations

from typing import Any, Optional

from ..server.server import Server
from ..server.types import Configuration, Extension, Payload
from .gateway import EdgeClientSession, EdgeGateway


class _ServeResponse(Exception):
    """Short-circuits the on_request chain with a ready response (the
    same mechanism the Metrics extension uses)."""

    def __str__(self) -> str:  # suppress hook-chain error logging
        return ""


class EdgeGatewayExtension(Extension):
    """Owns the gateway lifecycle on an edge server: starts the relay
    subscriber at listen time, serves `/debug/edge`, folds relay health
    into `/healthz`, and registers the `hocuspocus_edge_*` metrics with
    a co-installed Metrics extension."""

    priority = 900

    def __init__(self, gateway: Optional[EdgeGateway] = None, **gateway_options: Any) -> None:
        self.gateway = gateway or EdgeGateway(**gateway_options)

    async def on_configure(self, data: Payload) -> None:
        for extension in getattr(data.instance, "_extensions", []):
            registry = getattr(extension, "registry", None)
            if registry is not None and callable(getattr(registry, "register", None)):
                for metric in self.gateway.metrics():
                    try:
                        registry.register(metric)
                    except ValueError:
                        pass  # already adopted (shared registry, repeat bind)
                break

    async def on_listen(self, data: Payload) -> None:
        await self.gateway.start()

    async def on_request(self, data: Payload) -> None:
        request = data.request
        path = getattr(getattr(request, "rel_url", None), "path", None) or getattr(
            request, "path", ""
        )
        if path == "/debug/edge":
            import json

            from aiohttp import web

            from ..observability.fleet import stamp_header

            data.response = web.Response(
                # the consistent attributable header every /debug
                # endpoint carries: {"generated_utc", "role", "node_id"}
                text=json.dumps(stamp_header(self.gateway.status())),
                content_type="application/json",
            )
            error = _ServeResponse()
            error.response = data.response
            raise error

    def health_status(self) -> dict:
        return self.gateway.health_brief()

    async def on_destroy(self, data: Payload) -> None:
        self.gateway.close()


class EdgeServer(Server):
    """A `Server` whose websocket sessions relay to merge cells instead
    of terminating in a local document registry."""

    def __init__(
        self,
        configuration: Optional[Configuration] = None,
        gateway: Optional[EdgeGateway] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(configuration, **kwargs)
        if gateway is None:
            for extension in self.configuration.extensions:
                if isinstance(extension, EdgeGatewayExtension):
                    gateway = extension.gateway
                    break
        if gateway is None:
            raise ValueError(
                "EdgeServer needs an EdgeGateway (pass gateway= or add an "
                "EdgeGatewayExtension to the configuration)"
            )
        self.gateway = gateway

    def _create_session(self, transport, request_info, context):
        return EdgeClientSession(
            transport, request_info, self.hocuspocus, self.gateway, context
        )
