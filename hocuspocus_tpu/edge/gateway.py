"""The stateless websocket edge tier: terminate sockets, route docs.

An edge terminates client websockets, speaks the wire protocol far
enough to AUTHENTICATE each document channel at the door (the full
on_connect/on_authenticate hook chain plus the PR-12 per-tenant
admission quotas and RED-rung refusal — floods die here, cells never
see them), and relays everything else verbatim to the doc's owning
merge cell over the relay lane (edge/relay.py). The edge holds NO
document state: CRDT sync is order-insensitive and state-based, so the
only per-channel memory is two cached frames —

- the client's **Auth frame** (replayed to a new cell so a handed-off
  session re-authenticates without the client's involvement), and
- the client's latest **SyncStep1 frame** (replayed to a new cell as
  the resync exchange: the cell answers SyncStep2 — a superset diff,
  idempotent — plus its own SyncStep1, which makes the client re-offer
  everything the handoff window might have dropped).

**Connection handoff.** When a cell announces drain (or dies), the
router remaps its docs and every affected channel rebinds: DETACH from
the old session where still reachable, OPEN/reuse a session on the new
cell, replay Auth + SyncStep1, flush the channel's relay buffer. The
client keeps its socket the whole time — the only client-visible
traffic is the resync exchange. Frames still arriving from the OLD
session (late broadcasts, the drain's 1012 close) are dropped by the
current-session check, so a handoff can never leak a stale close or a
duplicate Authenticated to the client.

**Bounded relay queue.** A channel whose cell is unreachable (or not
yet routed) buffers outbound frames in a bounded deque; overflow drops
the OLDEST frame with accounting (`hocuspocus_edge_relay_overflow_total`
plus the shared `hocuspocus_wire_send_queue_overflow_total` family) —
a slow or dead cell can never OOM an edge, and everything dropped is
re-offered by the rebind's resync exchange.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import time
import uuid
from collections import deque
from typing import Any, Optional

from ..aio import spawn_tracked
from ..net.resp import PipelinedRedisClient, RedisSubscriber
from ..observability.costs import get_cost_ledger
from ..observability.fleet import build_digest, get_fleet_view
from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import Counter, Gauge
from ..observability.tracing import get_tracer
from ..observability.wire import get_wire_telemetry
from ..protocol.auth import AuthMessageType
from ..protocol.frames import parse_frame_header
from ..protocol.message import IncomingMessage, MessageType, OutgoingMessage
from ..protocol.sync import (
    MESSAGE_YJS_SYNC_STEP1,
    MESSAGE_YJS_SYNC_STEP2,
    MESSAGE_YJS_UPDATE,
)
from ..crdt.encoding import Decoder
from ..fleet.roster import AdmissionGate
from ..server import logger
from ..server.overload import RED, get_overload_controller, resolve_tenant
from ..server.types import ConnectionConfiguration, Payload
from . import relay
from .relay import DEFAULT_PREFIX
from .router import CellRouter

# frames a parked/re-establishing doc channel may buffer before the
# oldest is shed (accounted; healed by the rebind resync)
DEFAULT_RELAY_QUEUE_LIMIT = 1024

# audience watermark for hot-doc replication
# (docs/guides/hot-doc-replication.md): a doc whose local established
# channels reach the watermark grows one follower cell per further
# watermark's worth of audience (capped at healthy-1) and this edge
# spreads its channels across owner + followers. Below the watermark
# routing is byte-identical to the single-owner path.
DEFAULT_REPLICA_WATERMARK = 256


class RelaySession:
    """One (client socket, cell) lane multiplexing that client's doc
    channels routed to that cell."""

    __slots__ = ("gateway", "session_id", "cell_id", "owner", "docs", "closed")

    def __init__(self, gateway: "EdgeGateway", session_id: str, cell_id: str, owner) -> None:
        self.gateway = gateway
        self.session_id = session_id
        self.cell_id = cell_id
        self.owner = owner
        self.docs: "set[str]" = set()
        self.closed = False

    def send(self, frame: bytes, aux: str = "") -> None:
        if self.closed:
            return
        # zero-copy: the client frame rides as a memoryview segment
        # through the pipelined publish lane (joined once, straight
        # into the socket write) instead of being re-copied into a
        # fresh envelope buffer per publish
        self.gateway.publish_to_cell(
            self.cell_id,
            relay.encode_envelope_view(relay.FRAME, self.session_id, aux, frame),
        )
        self.gateway.counters["frames_to_cell"] += 1
        self.gateway.frames_total.inc(direction="to_cell")


class EdgeDocChannel:
    """Per-(socket, document) relay state. The whole point of the edge
    being stateless is how little lives here."""

    __slots__ = (
        "name",
        "tenant",
        "established",
        "authenticated_seen",
        "auth_frame",
        "step1_frame",
        "session",
        "buffer",
        "heal_handle",
    )

    def __init__(self, name: str, tenant: str) -> None:
        self.name = name
        # admission identity is PER CHANNEL (one socket can multiplex
        # docs whose auth hooks stamp different tenants — a per-socket
        # tenant would bill one tenant's flood to another's bucket)
        self.tenant = tenant
        self.established = False
        self.authenticated_seen = False
        self.auth_frame: Optional[bytes] = None
        self.step1_frame: Optional[bytes] = None
        self.session: Optional[RelaySession] = None
        self.buffer: "deque[bytes]" = deque()
        self.heal_handle: Optional[asyncio.TimerHandle] = None


class EdgeClientSession:
    """Per-socket session manager on the edge (the `ClientConnection`
    of the edge role): door auth, admission, relay, handoff."""

    def __init__(
        self,
        transport,
        request,
        hocuspocus,
        gateway: "EdgeGateway",
        context: Optional[dict] = None,
    ) -> None:
        self.transport = transport
        self.request = request
        self.hocuspocus = hocuspocus
        self.gateway = gateway
        self.default_context = dict(context or {})
        self.socket_id = str(uuid.uuid4())
        self.channels: "dict[str, EdgeDocChannel]" = {}
        self.cell_sessions: "dict[str, RelaySession]" = {}
        self.hook_payloads: "dict[str, Payload]" = {}
        self._auth_pending: "set[str]" = set()
        self.tenant = resolve_tenant(request=request, context=self.default_context)
        self._closed = False
        gateway.client_sessions.add(self)
        wire = get_wire_telemetry()
        if wire.enabled:
            wire.record_socket_opened()

    # -- inbound from the client -------------------------------------------

    async def handle_message(self, data: bytes) -> None:
        # edge ingress stamp (cross-tier tracing): taken at the frame
        # receive so a sampled update's trace opens where the monolith's
        # would — one attribute read when tracing is off
        t_receive = time.perf_counter() if get_tracer().enabled else None
        try:
            document_name, message_type, offset = parse_frame_header(data)
        except Exception as error:
            logger.log_error(f"[edge] invalid client frame: {error!r}")
            self.transport.close(4401, "Unauthorized")
            return
        channel = self.channels.get(document_name)
        if channel is not None and channel.established:
            overload = get_overload_controller()
            if overload.enabled and not overload.admit_message(channel.tenant):
                # edge-local ingress quota: the flood dies HERE — the
                # cell never sees the frame. Same rung-gated policy as
                # the monolith's Connection.handle_message: 1013 at
                # RED, below RED drop + one deferred resync heal
                if overload.rung >= RED:
                    self._close_channel(channel, 1013, "Try again later")
                    return
                self._schedule_quota_heal(channel)
                return
            self._relay_client_frame(
                channel, data, message_type, offset, t_receive=t_receive
            )
            return
        if channel is None:
            channel = self.channels[document_name] = EdgeDocChannel(
                document_name, self.tenant
            )
            self.hook_payloads[document_name] = Payload(
                instance=self.hocuspocus,
                request=self.request,
                connection_config=ConnectionConfiguration(
                    read_only=False, is_authenticated=False
                ),
                request_headers=self.request.headers,
                request_parameters=self.request.parameters,
                socket_id=self.socket_id,
                context={**self.default_context},
            )
        if (
            message_type == MessageType.Auth
            and document_name not in self._auth_pending
            and not channel.established
        ):
            self._auth_pending.add(document_name)
            await self._door_auth(channel, data, offset)
            return
        # pre-establishment traffic (the client's Step1/awareness land
        # right behind its Auth): buffer until the channel binds
        self._buffer_frame(channel, data)

    async def _door_auth(self, channel: EdgeDocChannel, data: bytes, offset: int) -> None:
        """The PR-12 front door: full auth hook chain + tenant admission
        run ON THE EDGE; only authenticated, admitted channels ever
        touch a cell."""
        document_name = channel.name
        hook_payload = self.hook_payloads[document_name]
        wire = get_wire_telemetry()
        auth_started = time.perf_counter() if wire.enabled else None
        try:
            try:
                tmp = IncomingMessage(data)
                tmp.decoder.pos = offset
                tmp.read_var_uint()  # auth submessage type (always Token)
                token = tmp.read_var_string()
            except Exception as error:
                # malformed Auth frame: same terminal behavior as the
                # monolith's establishment path (ClientConnection) —
                # log + reset the socket, never tear the loop down
                logger.log_error(f"[edge] malformed auth frame: {error!r}")
                self.transport.close(4205, "Reset Connection")
                return

            def merge_context(context_additions: Any) -> None:
                if isinstance(context_additions, dict):
                    hook_payload.context = {
                        **hook_payload.context,
                        **context_additions,
                    }

            try:
                await self.hocuspocus.hooks(
                    "on_connect",
                    Payload(
                        **{**hook_payload.__dict__, "document_name": document_name}
                    ),
                    merge_context,
                )
                await self.hocuspocus.hooks(
                    "on_authenticate",
                    Payload(
                        **{
                            **hook_payload.__dict__,
                            "token": token,
                            "document_name": document_name,
                        }
                    ),
                    merge_context,
                )
                if auth_started is not None:
                    wire.record_auth(time.perf_counter() - auth_started, ok=True)
            except Exception as error:
                if auth_started is not None:
                    wire.record_auth(time.perf_counter() - auth_started, ok=False)
                reason = getattr(error, "reason", None) or getattr(
                    getattr(error, "event", None), "reason", None
                )
                self._send_to_client(
                    OutgoingMessage(document_name)
                    .write_permission_denied(reason or "permission-denied")
                    .to_bytes()
                )
                self._drop_channel(channel)
                return
            # admission AFTER the hook chain (a tenant stamped into the
            # context by an auth hook is honored; an invalid token never
            # drains a victim's bucket) — identical to the monolith's
            # auth-time admission in server/client_connection.py
            channel.tenant = resolve_tenant(
                request=self.request, context=hook_payload.context
            )
            overload = get_overload_controller()
            if overload.enabled:
                refusal = overload.admit_connect(channel.tenant)
                if refusal is not None:
                    self._send_to_client(
                        OutgoingMessage(document_name)
                        .write_permission_denied(
                            f"overloaded: {refusal}; "
                            f"retry-after={overload.retry_after_s:g}s"
                        )
                        .to_bytes()
                    )
                    self._drop_channel(channel)
                    return
            hook_payload.connection_config.is_authenticated = True
            channel.established = True
            channel.auth_frame = data
            self.gateway.counters["channels_opened"] += 1
            # audience first: this channel's own bind should already see
            # the watermark it just crossed
            self.gateway.note_channel_opened(channel.name)
            self._bind_channel(channel)
        finally:
            self._auth_pending.discard(document_name)

    def _relay_client_frame(
        self,
        channel: EdgeDocChannel,
        data: bytes,
        message_type: Optional[int] = None,
        offset: int = 0,
        t_receive: Optional[float] = None,
    ) -> None:
        """Relay one established-channel frame toward the owning cell,
        caching the client's latest SyncStep1 (the handoff resync
        replay) on the way through. Callers that already parsed the
        header pass (message_type, offset) — the per-frame hot path
        must not pay the parse twice; buffered frames re-parse here.

        With tracing on, a sampled update/SyncStep2 frame arriving
        straight off the socket (`t_receive` set — buffered replays
        have no honest receive stamp and are never traced) is stamped
        with a cross-tier trace context in the envelope aux: the cell
        adopts the id, and the broadcast frame coming back closes the
        edge→cell→edge chain (docs/guides/edge-routing.md)."""
        if message_type is None:
            try:
                _name, message_type, offset = parse_frame_header(data)
            except Exception:
                return
        sync_type = None
        if message_type == MessageType.Sync:
            try:
                decoder = Decoder(data)
                decoder.pos = offset
                sync_type = decoder.read_var_uint()
            except Exception:
                sync_type = None
            if sync_type == MESSAGE_YJS_SYNC_STEP1:
                channel.step1_frame = data
        if channel.session is None or channel.session.closed:
            self._buffer_frame(channel, data)
            return
        aux = ""
        if t_receive is not None and sync_type in (
            MESSAGE_YJS_SYNC_STEP2,
            MESSAGE_YJS_UPDATE,
        ):
            aux = self.gateway.stamp_trace(channel.name, t_receive)
        channel.session.send(data, aux)

    def _buffer_frame(self, channel: EdgeDocChannel, data: bytes) -> None:
        """The bounded per-channel relay queue: a parked or
        re-establishing channel buffers; overflow sheds the OLDEST frame
        with accounting (newest state wins — the rebind resync re-offers
        whatever was shed)."""
        limit = self.gateway.relay_queue_limit
        while limit and len(channel.buffer) >= limit:
            channel.buffer.popleft()
            self.gateway.counters["relay_overflows"] += 1
            self.gateway.relay_overflow_total.inc()
            get_wire_telemetry().record_queue_overflow()
        channel.buffer.append(data)

    # -- binding / handoff ---------------------------------------------------

    def _session_for(self, cell_id: str) -> RelaySession:
        session = self.cell_sessions.get(cell_id)
        if session is None or session.closed:
            session = self.gateway.open_session(self, cell_id)
            self.cell_sessions[cell_id] = session
        return session

    def _bind_channel(
        self, channel: EdgeDocChannel, reason: Optional[str] = None
    ) -> bool:
        """Bind (or re-bind) a channel to its routed cell: replay Auth,
        replay the resync SyncStep1 on handoff, flush the buffer.
        Returns False when no healthy cell exists (channel parks)."""
        handoff = reason is not None
        # audience-aware: below the replica watermark this IS
        # router.route(); above it the channel spreads across the doc's
        # owner + follower cells (docs/guides/hot-doc-replication.md)
        cell_id = self.gateway.route_channel(channel.name, self.socket_id)
        if cell_id is None:
            self.gateway.counters["parked_binds"] += 1
            return False
        session = self._session_for(cell_id)
        channel.session = session
        session.docs.add(channel.name)
        if channel.auth_frame is not None:
            session.send(channel.auth_frame)
        if handoff and channel.step1_frame is not None:
            # THE resync exchange: the new cell answers SyncStep2 (a
            # superset diff — idempotent) + its own SyncStep1, which
            # makes the client re-offer anything the handoff dropped
            session.send(channel.step1_frame)
        while channel.buffer:
            self._relay_client_frame(channel, channel.buffer.popleft())
        if handoff:
            self.gateway.counters["handoffs"] += 1
            self.gateway.handoffs_total.inc(reason=reason)
            get_flight_recorder().record(
                "__edge__",
                "handoff",
                doc=channel.name,
                to_cell=cell_id,
                reason=reason,
            )
        return True

    def rebind_docs(self, session: RelaySession, reason: str) -> None:
        """A relay session died (cell drain/death/session CLOSED):
        every doc bound to it re-establishes on its re-routed cell."""
        if self.cell_sessions.get(session.cell_id) is session:
            self.cell_sessions.pop(session.cell_id, None)
        for name in sorted(session.docs):
            session.docs.discard(name)
            channel = self.channels.get(name)
            if channel is None or channel.session is not session:
                continue
            channel.session = None
            self._bind_channel(channel, reason=reason)

    def rebind_parked(self) -> None:
        """A cell came up: parked channels (no routable cell at bind
        time) try again; the replayed Step1 heals anything buffered or
        shed while parked."""
        for channel in list(self.channels.values()):
            if channel.established and (
                channel.session is None or channel.session.closed
            ):
                channel.session = None
                self._bind_channel(channel, reason="recovered")

    def detach_doc(self, channel: EdgeDocChannel) -> None:
        """Remove one doc from its session, telling a still-reachable
        cell to close the server-side Connection."""
        session = channel.session
        channel.session = None
        if session is None or session.closed:
            return
        session.docs.discard(channel.name)
        state = self.gateway.router.state_of(session.cell_id)
        if state == "healthy":
            self.gateway.publish_to_cell(
                session.cell_id,
                relay.encode_envelope(relay.DETACH, session.session_id, channel.name),
            )

    # -- inbound from cells --------------------------------------------------

    def deliver_from_cell(self, session: RelaySession, payload: bytes) -> None:
        try:
            document_name, message_type, offset = parse_frame_header(payload)
        except Exception:
            return
        channel = self.channels.get(document_name)
        if channel is None or channel.session is not session:
            # stale-session traffic: a late broadcast or the old cell's
            # drain-time 1012 close for a doc that already handed off —
            # never client-visible
            self.gateway.counters["stale_drops"] += 1
            self.gateway.stale_frames_total.inc()
            return
        if message_type == MessageType.Auth:
            try:
                decoder = Decoder(payload)
                decoder.pos = offset
                subtype = decoder.read_var_uint()
            except Exception:
                subtype = None
            if subtype == AuthMessageType.Authenticated:
                if channel.authenticated_seen:
                    return  # handoff re-auth: the client already has one
                channel.authenticated_seen = True
            elif subtype == AuthMessageType.PermissionDenied:
                # terminal from the cell (e.g. cell-side admission):
                # forward, then forget the channel so a retry re-auths
                self._send_to_client(payload)
                self.detach_doc(channel)
                self._drop_channel(channel)
                return
        self._send_to_client(payload)

    def _send_to_client(self, data: bytes) -> None:
        if self.transport.is_closed:
            return
        try:
            self.transport.send(data)
        except Exception:
            return
        self.gateway.counters["frames_to_client"] += 1
        self.gateway.frames_total.inc(direction="to_client")
        wire = get_wire_telemetry()
        if wire.enabled:
            wire.record_egress_frame(data)

    # -- quota heal ----------------------------------------------------------

    def _schedule_quota_heal(self, channel: EdgeDocChannel) -> None:
        """A dropped over-quota frame must not diverge the doc forever:
        after the bucket's refill window, replay the client's Step1 to
        the cell — the cell's SyncStep2 + Step1 exchange re-offers
        everything the drops lost (state-based sync, idempotent)."""
        if channel.heal_handle is not None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return

        def heal() -> None:
            channel.heal_handle = None
            if (
                self._closed
                or not channel.established
                or channel.session is None
                or channel.session.closed
            ):
                return
            if channel.step1_frame is not None:
                channel.session.send(channel.step1_frame)

        channel.heal_handle = loop.call_later(1.0, heal)

    # -- teardown ------------------------------------------------------------

    def _close_channel(self, channel: EdgeDocChannel, code: int, reason: str) -> None:
        wire = get_wire_telemetry()
        if wire.enabled:
            wire.record_channel_close(code)
        self._send_to_client(
            OutgoingMessage(channel.name).write_close_message(reason).to_bytes()
        )
        self.detach_doc(channel)
        self._drop_channel(channel)

    def _drop_channel(self, channel: EdgeDocChannel) -> None:
        if channel.heal_handle is not None:
            channel.heal_handle.cancel()
            channel.heal_handle = None
        if channel.established and self.channels.get(channel.name) is channel:
            self.gateway.note_channel_closed(channel.name)
        self.channels.pop(channel.name, None)
        self.hook_payloads.pop(channel.name, None)
        session = channel.session
        if session is not None:
            session.docs.discard(channel.name)
        channel.session = None
        channel.buffer.clear()

    async def handle_transport_close(self, code: int, reason: str) -> None:
        if self._closed:
            return
        self._closed = True
        wire = get_wire_telemetry()
        if wire.enabled:
            wire.record_socket_closed(code)
            wire.untrack_transport(self.transport)
        for channel in list(self.channels.values()):
            if channel.heal_handle is not None:
                channel.heal_handle.cancel()
                channel.heal_handle = None
            if channel.established:
                self.gateway.note_channel_closed(channel.name)
            channel.buffer.clear()
        for session in list(self.cell_sessions.values()):
            self.gateway.close_session(session)
        self.cell_sessions.clear()
        self.channels.clear()
        self.hook_payloads.clear()
        self.gateway.client_sessions.discard(self)


class EdgeGateway:
    """One edge process's relay fabric: the router, the RESP lane, the
    session registry and the edge metric surface."""

    def __init__(
        self,
        edge_id: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 6379,
        prefix: str = DEFAULT_PREFIX,
        router: Optional[CellRouter] = None,
        create_client: Optional[Any] = None,
        create_subscriber: Optional[Any] = None,
        relay_queue_limit: int = DEFAULT_RELAY_QUEUE_LIMIT,
        heartbeat_timeout_s: Optional[float] = None,
        heartbeat_sweep_s: Optional[float] = None,
        digest_interval_s: float = 2.0,
        replica_watermark: int = DEFAULT_REPLICA_WATERMARK,
        host_id: Optional[str] = None,
        admission: Optional[AdmissionGate] = None,
    ) -> None:
        self.edge_id = edge_id or f"edge-{uuid.uuid4().hex[:8]}"
        self.prefix = prefix
        # cross-host admission (fleet/roster.py): cells announcing with
        # a foreign host qualifier stay PENDING — probed, not routable —
        # until their clock offset resolves; local cells admit as before
        self.host_id = host_id
        self.admission = admission or AdmissionGate(local_host=host_id)
        if router is None:
            router = (
                CellRouter()
                if heartbeat_timeout_s is None
                else CellRouter(heartbeat_timeout_s=heartbeat_timeout_s)
            )
        self.router = router
        # heartbeat-expiry sweep: the timer that actually DRIVES
        # `CellRouter.expire_stale` — a cell that dies without a
        # CELL_DOWN (kill -9, network partition) flips to dead when its
        # CELL_UP heartbeats go quiet past the router timeout, and its
        # docs hand off exactly like an announced death. Half the
        # timeout by default: a cell expires at most 1.5x the timeout
        # after its last heartbeat.
        self.heartbeat_sweep_s = (
            heartbeat_sweep_s
            if heartbeat_sweep_s is not None
            else max(self.router.heartbeat_timeout_s / 2.0, 0.05)
        )
        self._sweep_handle: "Optional[asyncio.TimerHandle]" = None
        # telemetry federation + clock-offset probes: one digest on the
        # control channel (and one PING per healthy cell) per interval
        self.digest_interval_s = digest_interval_s
        self._digest_handle: "Optional[asyncio.TimerHandle]" = None
        self._trace_seq = 0
        self.relay_queue_limit = relay_queue_limit
        self.sessions: "dict[str, RelaySession]" = {}
        self.client_sessions: "set[EdgeClientSession]" = set()
        self._session_seq = 0
        self._tasks: set = set()
        self._started = False
        self.counters = {
            "frames_to_cell": 0,
            "frames_to_client": 0,
            "channels_opened": 0,
            "handoffs": 0,
            "stale_drops": 0,
            "relay_overflows": 0,
            "parked_binds": 0,
            "remaps": 0,
            "heartbeat_expiries": 0,
            "traces_stamped": 0,
            "traces_closed": 0,
            "digests_published": 0,
            "follow_hints": 0,
            "promotions": 0,
            "admissions_pending": 0,
            "admissions_foreign": 0,
        }
        # -- hot-doc replication (docs/guides/hot-doc-replication.md) ---
        # audience watermark (0 disables): per-doc ESTABLISHED channel
        # counts on this edge drive the follower count
        self.replica_watermark = replica_watermark
        self._doc_audience: "dict[str, int]" = {}
        # doc -> {"owner": cell, "followers": [cells], "hinted":
        #         {(cell, owner), ...}} — the replication topology this
        #         edge has grown (hints are idempotent per (cell, owner))
        self._replica_routes: "dict[str, dict]" = {}
        # doc -> cell -> last digest-reported tick seq: the freshness
        # signal behind promote-the-freshest-follower
        self._replica_seqs: "dict[str, dict[str, int]]" = {}
        if create_client is not None:
            self.pub = create_client()
        else:
            self.pub = PipelinedRedisClient(host, port)
        if create_subscriber is not None:
            self.sub = create_subscriber(self._on_message)
        else:
            self.sub = RedisSubscriber(host, port, on_message=self._on_message)
        # -- exposition (hocuspocus_edge_*; adopted by Metrics) ---------
        self.sessions_gauge = Gauge(
            "hocuspocus_edge_relay_sessions",
            "Live edge→cell relay sessions",
            fn=lambda: len(self.sessions),
        )
        self.cells_gauge = Gauge(
            "hocuspocus_edge_cells_healthy",
            "Merge cells the router considers healthy",
            fn=lambda: len(self.router.healthy_cells()),
        )
        self.channels_gauge = Gauge(
            "hocuspocus_edge_doc_channels",
            "Established per-document relay channels",
            fn=self._count_channels,
        )
        self.parked_gauge = Gauge(
            "hocuspocus_edge_parked_channels",
            "Established channels with no routable cell (buffering)",
            fn=self._count_parked,
        )
        self.relay_queue_gauge = Gauge(
            "hocuspocus_edge_relay_queue_depth",
            "Frames buffered across parked/re-establishing channels",
            fn=self._relay_queue_depth,
        )
        self.frames_total = Counter(
            "hocuspocus_edge_relay_frames_total",
            "Frames relayed through this edge, by direction",
        )
        self.handoffs_total = Counter(
            "hocuspocus_edge_handoffs_total",
            "Doc channels handed off between cells, by reason",
        )
        self.stale_frames_total = Counter(
            "hocuspocus_edge_stale_frames_total",
            "Frames from superseded sessions dropped by the edge",
        )
        self.relay_overflow_total = Counter(
            "hocuspocus_edge_relay_overflow_total",
            "Frames shed from bounded per-channel relay queues",
        )
        self.route_epoch_gauge = Gauge(
            "hocuspocus_edge_route_epoch",
            "Router epoch (bumps on every membership/override change)",
            fn=lambda: self.router.epoch,
        )
        self.replicated_docs_gauge = Gauge(
            "hocuspocus_replica_docs",
            "Docs this edge routes with an owner + follower set",
            fn=lambda: float(len(self._replica_routes)),
        )
        self.follow_hints_total = Counter(
            "hocuspocus_replica_follow_hints_total",
            "FOLLOW routing hints sent to follower cells",
        )
        self.edge_promotions_total = Counter(
            "hocuspocus_replica_edge_promotions_total",
            "Owner promotions driven by this edge, by reason",
        )

    def metrics(self) -> tuple:
        """Metric objects for MetricsRegistry.register adoption."""
        return (
            self.sessions_gauge,
            self.cells_gauge,
            self.channels_gauge,
            self.parked_gauge,
            self.relay_queue_gauge,
            self.frames_total,
            self.handoffs_total,
            self.stale_frames_total,
            self.relay_overflow_total,
            self.route_epoch_gauge,
            self.replicated_docs_gauge,
            self.follow_hints_total,
            self.edge_promotions_total,
        )

    def _count_channels(self) -> int:
        return sum(len(s.channels) for s in self.client_sessions)

    def _count_parked(self) -> int:
        return sum(
            1
            for s in self.client_sessions
            for c in s.channels.values()
            if c.established and (c.session is None or c.session.closed)
        )

    def _relay_queue_depth(self) -> int:
        return sum(
            len(c.buffer)
            for s in self.client_sessions
            for c in s.channels.values()
        )

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        # fleet identity: debug payload headers + cross-tier span lanes
        get_fleet_view().set_identity("edge", self.edge_id)
        await self.sub.subscribe(relay.edge_channel(self.prefix, self.edge_id))
        await self.sub.subscribe(relay.control_channel(self.prefix))
        get_flight_recorder().record("__edge__", "edge_up", edge=self.edge_id)
        self._schedule_heartbeat_sweep()
        self._digest_tick()

    def _schedule_heartbeat_sweep(self) -> None:
        if self.heartbeat_sweep_s <= 0 or self._sweep_handle is not None:
            return
        try:
            loop = asyncio.get_event_loop()
        except RuntimeError:
            return
        self._sweep_handle = loop.call_later(
            self.heartbeat_sweep_s, self._heartbeat_sweep
        )

    def _heartbeat_sweep(self) -> None:
        """Expiry-driven handoff: cells whose heartbeats went quiet past
        the router timeout flip to dead and their docs remap — the same
        transparent Auth+Step1-replay rebind an announced CELL_DOWN
        takes, so a kill -9'd cell strands its sessions for at most one
        timeout + sweep interval."""
        self._sweep_handle = None
        try:
            # pending (never-admitted) cells that stopped announcing
            # age out on the same liveness contract as routable ones
            for cell_id in self.admission.expire(
                self.router.heartbeat_timeout_s
            ):
                get_flight_recorder().record(
                    "__autoscale__",
                    "cell_pending_expired",
                    cell=cell_id,
                    edge=self.edge_id,
                )
            # per-cell isolation: expire_stale reports each dead cell
            # exactly ONCE, so a handoff failure for cell A must not
            # strand cell B's sessions for good
            for cell_id in self.router.expire_stale():
                self.counters["heartbeat_expiries"] += 1
                get_flight_recorder().record(
                    "__edge__",
                    "cell_expired",
                    cell=cell_id,
                    edge=self.edge_id,
                    timeout_s=self.router.heartbeat_timeout_s,
                )
                try:
                    self._handoff_cell(cell_id, "expired")
                except Exception as error:
                    logger.log_error(
                        f"[edge] expiry handoff for {cell_id!r} failed "
                        f"({error!r}); sessions heal on the next rebind"
                    )
        finally:
            if self._started:
                self._schedule_heartbeat_sweep()

    def _digest_tick(self) -> None:
        """Per-interval federation work: publish this edge's telemetry
        digest on the control channel (+ ingest locally), and PING every
        healthy cell so the clock-offset estimates stay fresh for the
        relay spans. Gated on the fleet view (lit by Metrics) for the
        digests; pings ride only while tracing is on — both are no-ops
        on an unobserved edge."""
        self._digest_handle = None
        view = get_fleet_view()
        try:
            if view.enabled:
                digest = build_digest(
                    role="edge",
                    node_id=self.edge_id,
                    interval_s=self.digest_interval_s,
                    extra={
                        "sessions": len(self.client_sessions),
                        "placement_epoch": self.router.epoch,
                        "edge": {
                            "cells_healthy": len(self.router.healthy_cells()),
                            "doc_channels": self._count_channels(),
                            "parked_channels": self._count_parked(),
                            "relay_queue_depth": self._relay_queue_depth(),
                            "relay_sessions": len(self.sessions),
                            "replicated_docs": len(self._replica_routes),
                        },
                    },
                )
                view.ingest(digest)
                self.publish_control(
                    relay.encode_envelope(
                        relay.DIGEST,
                        self.edge_id,
                        "",
                        json.dumps(digest, separators=(",", ":")).encode(),
                    )
                )
                self.counters["digests_published"] += 1
            if get_tracer().enabled:
                ping_aux = json.dumps(
                    {"t": time.perf_counter()}, separators=(",", ":")
                )
                for cell_id in self.router.healthy_cells():
                    self.publish_to_cell(
                        cell_id,
                        relay.encode_envelope(relay.PING, self.edge_id, ping_aux),
                    )
            # pending (cross-host) cells are ALWAYS probed: their
            # admission is waiting on exactly these samples
            for cell_id in list(self.admission.pending):
                self._ping_cell(cell_id)
        finally:
            if self._started and self.digest_interval_s > 0:
                try:
                    loop = asyncio.get_event_loop()
                except RuntimeError:
                    return
                self._digest_handle = loop.call_later(
                    self.digest_interval_s, self._digest_tick
                )

    def stamp_trace(self, doc_name: str, t_receive: float) -> str:
        """Sample one inbound update for cross-tier tracing: returns the
        encoded trace-context aux (or "" when not sampled). The context
        carries everything the return path needs — the edge holds no
        per-trace state, in keeping with its statelessness."""
        tracer = get_tracer()
        if not tracer.enabled or not tracer.take_sample():
            return ""
        self._trace_seq += 1
        self.counters["traces_stamped"] += 1
        return relay.encode_trace_aux(
            {
                "id": f"{self.edge_id}:{self._trace_seq}",
                "e": self.edge_id,
                "d": doc_name,
                "t0": t_receive,
                "t1": time.perf_counter(),
                "h": 1,
            }
        )

    def _finish_cross_tier(
        self, returns: list, t9a: float, t9b: float
    ) -> None:
        """Close cross-tier traces from a cell's TRACE_RET contexts:
        emit the four edge-side spans and feed the fleet e2e histogram.

        The chain closes on the SAME edge that stamped it, so `t0`/`t1`
        (echoed back verbatim) and `t9a`/`t9b` share this edge's clock
        and the end-to-end latency is a single-clock difference —
        exact. Only the interior boundary needs reconciliation: the two
        relay spans partition the edge-observed gap
        `(t9a - t1) - interior`, split at the offset-corrected
        cell-receive stamp (heartbeat-RTT estimate). Any one-way skew
        folds into the relay spans — the split clamps to [0, gap], so
        no span ever goes negative and the spans still sum exactly to
        the edge-to-edge e2e."""
        tracer = get_tracer()
        view = get_fleet_view()
        for ctx in returns:
            try:
                trace_id = ctx["id"]
                t0 = float(ctx["t0"])
                t1 = float(ctx["t1"])
                t_cell_recv = float(ctx["tr"])
                t_cell_close = float(ctx["ts"])
            except (KeyError, TypeError, ValueError):
                continue
            node = str(ctx.get("n", "cell"))
            doc = ctx.get("d")
            hop = int(ctx.get("h", 2))
            estimator = view.offsets.get(node)
            offset = 0.0 if estimator is None else estimator.offset_s
            interior = max(t_cell_close - t_cell_recv, 0.0)
            gap = max((t9a - t1) - interior, 0.0)
            relay_out = min(max((t_cell_recv - offset) - t1, 0.0), gap)
            relay_return = gap - relay_out
            edge_ingress = max(t1 - t0, 0.0)
            edge_egress = max(t9b - t9a, 0.0)
            e2e = edge_ingress + gap + interior + edge_egress
            e2e_ms = round(e2e * 1000.0, 3)
            if tracer.enabled:
                tracer.add_span(
                    "update.edge_ingress", t0, t1,
                    trace_id=trace_id, doc=doc, node=self.edge_id, hop=hop,
                )
                tracer.add_span(
                    "update.relay_out", t1, t1 + relay_out,
                    trace_id=trace_id, doc=doc, node=self.edge_id,
                    clock_offset_ms=round(offset * 1000.0, 3),
                )
                tracer.add_span(
                    "update.relay_return", t9a - relay_return, t9a,
                    trace_id=trace_id, doc=doc, node=self.edge_id,
                )
                tracer.add_span(
                    "update.edge_egress", t9a, t9b,
                    trace_id=trace_id, doc=doc, node=self.edge_id,
                    e2e_ms=e2e_ms,
                )
            view.record_cross_tier("edge_ingress", edge_ingress)
            view.record_cross_tier("relay_out", relay_out)
            view.record_cross_tier("relay_return", relay_return)
            view.record_cross_tier("edge_egress", edge_egress)
            view.record_cross_tier("total", e2e)
            self.counters["traces_closed"] += 1

    def close(self) -> None:
        self._started = False
        if self._sweep_handle is not None:
            self._sweep_handle.cancel()
            self._sweep_handle = None
        if self._digest_handle is not None:
            self._digest_handle.cancel()
            self._digest_handle = None
        for session in list(self.sessions.values()):
            session.closed = True
        self.sessions.clear()
        self.pub.close()
        self.sub.close()

    # -- relay plumbing ------------------------------------------------------

    def publish_to_cell(self, cell_id: str, envelope) -> None:
        """Publish one envelope (bytes, or a zero-copy segment list from
        `relay.encode_envelope_view`)."""
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            nowait(relay.cell_channel(self.prefix, cell_id), envelope)
        else:
            if isinstance(envelope, (list, tuple)):
                envelope = b"".join(envelope)
            spawn_tracked(
                self._tasks,
                self.pub.publish(relay.cell_channel(self.prefix, cell_id), envelope),
            )

    def publish_control(self, envelope: bytes) -> None:
        channel = relay.control_channel(self.prefix)
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            nowait(channel, envelope)
        else:
            spawn_tracked(self._tasks, self.pub.publish(channel, envelope))

    # -- hot-doc replication -------------------------------------------------

    def note_channel_opened(self, doc_name: str) -> None:
        self._doc_audience[doc_name] = self._doc_audience.get(doc_name, 0) + 1

    def note_channel_closed(self, doc_name: str) -> None:
        count = self._doc_audience.get(doc_name, 0) - 1
        if count > 0:
            self._doc_audience[doc_name] = count
        else:
            self._doc_audience.pop(doc_name, None)

    def replica_route_set(self, doc_name: str) -> "list[str]":
        """Audience-aware placement: [owner] below the watermark, else
        [owner, follower...] with one follower per watermark's worth of
        local audience (capped at healthy-1). Growing the set sends the
        FOLLOW hints that stand the followers up; the set only shrinks
        through cell churn — an audience dip must not thrash follower
        bootstrap."""
        watermark = self.replica_watermark
        if watermark <= 0:
            owner = self.router.route(doc_name)
            return [] if owner is None else [owner]
        audience = self._doc_audience.get(doc_name, 0)
        wanted = audience // watermark
        entry = self._replica_routes.get(doc_name)
        if entry is not None:
            wanted = max(wanted, len(entry["followers"]))
        wanted = min(wanted, max(len(self.router.healthy_cells()) - 1, 0))
        route_set = self.router.route_set(doc_name, wanted)
        if len(route_set) > 1:
            self._ensure_hints(doc_name, route_set)
        return route_set

    def route_channel(self, doc_name: str, socket_id: str) -> "Optional[str]":
        """The serving cell for one (doc, socket) channel: the owner
        below the watermark; above it, a stable spread across owner +
        followers so the read storm lands proportionally on every
        replica while a given socket always rebinds to the same slot
        (its SyncStep1 replay heals the one-slot move on churn)."""
        route_set = self.replica_route_set(doc_name)
        if not route_set:
            return None
        if len(route_set) == 1:
            return route_set[0]
        digest = hashlib.blake2b(
            f"{doc_name}\x00{socket_id}".encode(), digest_size=4
        ).digest()
        return route_set[int.from_bytes(digest, "big") % len(route_set)]

    def _ensure_hints(self, doc_name: str, route_set: "list[str]") -> None:
        owner = route_set[0]
        entry = self._replica_routes.get(doc_name)
        if entry is None:
            entry = self._replica_routes[doc_name] = {
                "owner": owner,
                "followers": [],
                "hinted": set(),
            }
        entry["owner"] = owner
        entry["followers"] = [c for c in route_set[1:]]
        for follower in route_set[1:]:
            self._send_follow_hint(entry, follower, doc_name, owner)

    def _send_follow_hint(
        self, entry: dict, target: str, doc_name: str, owner: str
    ) -> None:
        """Idempotent per (target, owner): the target cell learns the
        doc's owner — follower cells subscribe, the owner itself (on
        promotion) flips role."""
        key = (target, owner)
        if key in entry["hinted"]:
            return
        entry["hinted"].add(key)
        self.publish_to_cell(
            target,
            relay.encode_envelope(
                relay.FOLLOW,
                self.edge_id,
                relay.encode_replica_aux(d=doc_name, o=owner),
            ),
        )
        self.counters["follow_hints"] += 1
        self.follow_hints_total.inc()
        get_flight_recorder().record(
            "__replica__",
            "follow" if target != owner else "promoted",
            doc=doc_name,
            cell=target,
            owner=owner,
            edge=self.edge_id,
        )

    def _harvest_replica_digest(self, node_id: str, digest: dict) -> None:
        """Cell digests carry per-doc tick seqs; the freshest-follower
        pick at promotion time reads them here. Harvested from every
        cell digest — including our own echo — so the signal survives
        digest dedup policy."""
        replica = digest.get("replica")
        if not isinstance(replica, dict):
            return
        for section in ("owned", "following"):
            docs = replica.get(section)
            if not isinstance(docs, dict):
                continue
            for doc_name, info in docs.items():
                seq = info.get("seq") if isinstance(info, dict) else None
                if isinstance(seq, int):
                    self._replica_seqs.setdefault(doc_name, {})[node_id] = seq

    def _promote_replicas(self, cell_id: str, reason: str) -> None:
        """The departed cell leaves every replica topology it was part
        of. Followers just drop out; a departed OWNER promotes the
        freshest surviving follower (highest digest-carried tick seq,
        HRW-order tie-break), clears the doc's stale router entries
        (`CellRouter.promote`), and re-hints every survivor so the
        promoted cell flips role and the rest re-subscribe to it."""
        for doc_name, entry in list(self._replica_routes.items()):
            if entry["owner"] != cell_id:
                if cell_id in entry["followers"]:
                    entry["followers"] = [
                        f for f in entry["followers"] if f != cell_id
                    ]
                continue
            survivors = [
                f
                for f in entry["followers"]
                if self.router.state_of(f) == "healthy"
            ]
            if not survivors:
                # no replica to promote: drop the entry — the ordinary
                # re-route + Auth/Step1 resync takes over
                self._replica_routes.pop(doc_name, None)
                continue
            seqs = self._replica_seqs.get(doc_name, {})
            new_owner = max(
                survivors,
                key=lambda c: (seqs.get(c, -1), -survivors.index(c)),
            )
            self.router.promote(doc_name, new_owner)
            entry["owner"] = new_owner
            entry["followers"] = [f for f in survivors if f != new_owner]
            self.counters["promotions"] += 1
            self.edge_promotions_total.inc(reason=reason)
            get_flight_recorder().record(
                "__replica__",
                "promoted",
                doc=doc_name,
                old_owner=cell_id,
                new_owner=new_owner,
                reason=reason,
                edge=self.edge_id,
            )
            self._send_follow_hint(entry, new_owner, doc_name, new_owner)
            for follower in entry["followers"]:
                self._send_follow_hint(entry, follower, doc_name, new_owner)

    def open_session(self, owner: EdgeClientSession, cell_id: str) -> RelaySession:
        self._session_seq += 1
        session_id = f"{self.edge_id}:{owner.socket_id[:8]}:{self._session_seq}"
        session = RelaySession(self, session_id, cell_id, owner)
        self.sessions[session_id] = session
        self.publish_to_cell(
            cell_id,
            relay.encode_envelope(
                relay.OPEN,
                session_id,
                relay.encode_open_aux(self.edge_id, tenant=owner.tenant),
            ),
        )
        return session

    def close_session(self, session: RelaySession) -> None:
        if not session.closed:
            session.closed = True
            self.publish_to_cell(
                session.cell_id,
                relay.encode_envelope(relay.CLOSE, session.session_id),
            )
        self.sessions.pop(session.session_id, None)
        session.docs.clear()

    # -- inbound dispatch ----------------------------------------------------

    def _consider_cell(self, cell_id: str) -> None:
        """CELL_UP admission (fleet/roster.py): local cells join the
        router immediately; a FOREIGN cell holds in the pending table —
        announced, clock-probed, but not routable — until its per-peer
        ClockOffsetEstimator resolves. Every membership change still
        rides `router.add_cell`'s epoch bump, so in-flight routes heal
        through the usual stale-route/Step1-resync machinery."""
        admit, reason = self.admission.evaluate(
            cell_id, get_fleet_view().offsets.get(cell_id)
        )
        if not admit:
            if self.admission.hold(cell_id, reason):
                self.counters["admissions_pending"] += 1
                get_flight_recorder().record(
                    "__autoscale__",
                    "cell_pending",
                    cell=cell_id,
                    edge=self.edge_id,
                    reason=reason,
                )
            # probe the pending peer's clock NOW: admission is what
            # needs the offset resolved, never gated on the tracer
            self._ping_cell(cell_id)
            return
        if self.admission.admit(cell_id):
            self.counters["admissions_foreign"] += 1
            get_flight_recorder().record(
                "__autoscale__",
                "cell_admitted",
                cell=cell_id,
                edge=self.edge_id,
                reason=reason,
            )
        if self.router.add_cell(cell_id):
            if reason == "local":
                self.admission.note_local(True)
            get_flight_recorder().record(
                "__edge__", "cell_up", cell=cell_id, edge=self.edge_id
            )
            self._rebind_parked()

    def _ping_cell(self, cell_id: str) -> None:
        self.publish_to_cell(
            cell_id,
            relay.encode_envelope(
                relay.PING,
                self.edge_id,
                json.dumps({"t": time.perf_counter()}, separators=(",", ":")),
            ),
        )

    def _on_message(self, channel: bytes, data: bytes) -> None:
        try:
            t0 = time.perf_counter_ns()
            kind, session_id, aux, payload = relay.decode_envelope(data)
        except Exception:
            return
        ledger = get_cost_ledger()
        if ledger.enabled:
            ledger.record(
                "envelope_decode", "Relay", time.perf_counter_ns() - t0, len(data)
            )
        if kind == relay.CELL_UP:
            self._consider_cell(session_id)
            return
        if kind == relay.CELL_DRAINING:
            if self.router.mark_draining(session_id):
                get_flight_recorder().record(
                    "__edge__", "cell_draining", cell=session_id, edge=self.edge_id
                )
                self._handoff_cell(session_id, "drain")
            return
        if kind == relay.CELL_DOWN:
            get_fleet_view().mark_down(session_id)
            self.admission.pending.pop(session_id, None)
            if self.router.mark_dead(session_id):
                get_flight_recorder().record(
                    "__edge__", "cell_down", cell=session_id, edge=self.edge_id
                )
                self._handoff_cell(session_id, "down")
            return
        if kind == relay.DIGEST:
            # a peer's telemetry digest off the control channel (other
            # edges and every cell publish). Our own publish echoes back
            # here too — skip it: _digest_tick already ingested locally,
            # and double-ingest would halve the self-peer's ring window
            # and inflate the digest counters
            try:
                digest = json.loads(payload)
            except Exception:
                return
            if isinstance(digest, dict):
                # replica tick seqs ride cell digests: harvest before
                # the self-echo skip so freshness survives dedup
                self._harvest_replica_digest(session_id, digest)
                view = get_fleet_view()
                if view.enabled and session_id != self.edge_id:
                    try:
                        view.ingest(digest)
                    except Exception:
                        pass
            return
        if kind == relay.PONG:
            # clock-offset probe reply: session field = the cell's id,
            # aux echoes our PING stamp plus the cell's own clock
            try:
                reply = json.loads(aux)
                get_fleet_view().offset_for(session_id).observe(
                    float(reply["t"]), float(reply["tc"]), time.perf_counter()
                )
            except Exception:
                pass
            if session_id in self.admission.pending:
                # a pending cell's probe landed: re-evaluate admission
                # now instead of waiting out its next CELL_UP heartbeat
                self._consider_cell(session_id)
            return
        if kind == relay.TRACE_RET:
            # cross-tier trace returns (session field = the cell's id):
            # processed at the gateway, independent of any relay session
            # — a handoff racing the close can't lose the trace
            t9a = time.perf_counter()
            trace_ctx = relay.decode_trace_aux(aux)
            returns = None if trace_ctx is None else trace_ctx.get("r")
            if returns:
                self._finish_cross_tier(returns, t9a, time.perf_counter())
            return
        session = self.sessions.get(session_id)
        if session is None:
            return
        if kind == relay.FRAME:
            session.owner.deliver_from_cell(session, payload)
        elif kind == relay.CLOSED:
            # the cell closed this session (drain 1012, overflow,
            # shutdown): remap its docs. A drain-coded close also
            # downgrades the cell so new routes avoid it even when the
            # control announcement was lost.
            self.sessions.pop(session_id, None)
            session.closed = True
            if aux.startswith("1012") and self.router.mark_draining(session.cell_id):
                self._handoff_cell(session.cell_id, "drain")
            session.owner.rebind_docs(session, "closed")

    def _handoff_cell(self, cell_id: str, reason: str) -> None:
        """Remap every doc bound to `cell_id` — transparent handoff: the
        clients keep their sockets; each channel replays Auth+Step1 on
        its new cell."""
        self.counters["remaps"] += 1
        # promotions FIRST: the rebinds below must route through the
        # promoted owner's fresh placement, not the dead cell's
        self._promote_replicas(cell_id, reason)
        affected = [
            session
            for session in self.sessions.values()
            if session.cell_id == cell_id
        ]
        for session in affected:
            self.sessions.pop(session.session_id, None)
            session.closed = True
            session.owner.rebind_docs(session, reason)

    def _rebind_parked(self) -> None:
        for client in list(self.client_sessions):
            client.rebind_parked()

    # -- observability -------------------------------------------------------

    def status(self) -> dict:
        """The `/debug/edge` payload: routing table + live sessions +
        per-doc bindings + counters."""
        bindings = {}
        for client in self.client_sessions:
            for name, channel in client.channels.items():
                bindings[name] = {
                    "cell": channel.session.cell_id
                    if channel.session is not None and not channel.session.closed
                    else None,
                    "established": channel.established,
                    "buffered": len(channel.buffer),
                }
        view = get_fleet_view()
        return {
            "edge_id": self.edge_id,
            "host_id": self.host_id,
            "admission": self.admission.status(),
            "router": self.router.table(),
            "sessions": {
                session_id: {"cell": session.cell_id, "docs": sorted(session.docs)}
                for session_id, session in sorted(self.sessions.items())
            },
            "channels": dict(sorted(bindings.items())),
            "client_sockets": len(self.client_sessions),
            "counters": dict(self.counters),
            "replica": {
                "watermark": self.replica_watermark,
                "docs": {
                    doc: {
                        "owner": entry["owner"],
                        "followers": list(entry["followers"]),
                        "audience": self._doc_audience.get(doc, 0),
                        "seqs": dict(
                            sorted(self._replica_seqs.get(doc, {}).items())
                        ),
                    }
                    for doc, entry in sorted(self._replica_routes.items())
                },
            },
            "clock_offsets": {
                peer: {
                    "offset_ms": round(est.offset_s * 1000.0, 3),
                    "rtt_ms": None
                    if est.rtt_s is None
                    else round(est.rtt_s * 1000.0, 3),
                    "samples": est.samples,
                }
                for peer, est in sorted(view.offsets.items())
            },
        }

    def health_brief(self) -> dict:
        healthy = len(self.router.healthy_cells())
        return {
            "state": "routing" if healthy else "no_cells",
            "degraded": self._started and healthy == 0,
            "cells_healthy": healthy,
            "relay_sessions": len(self.sessions),
            "parked_channels": self._count_parked(),
        }
