"""Doc → cell placement: rendezvous hashing + an explicit override table.

The router is the edge tier's only routing state, and it is SOFT state:
every entry is reconstructible from the control channel (cells announce
themselves) and every stale answer is healed by the SyncStep1 resync
exchange, never trusted to be right forever. Placement properties:

- **Rendezvous (HRW) hashing.** Each doc scores every healthy cell with
  ``blake2b(doc || cell)`` and picks the max. Adding a cell moves only
  the docs whose new-cell score wins (~1/N of the population, all of
  them TO the new cell); removing a cell moves only the docs that lived
  on it. No ring maintenance, no token math — the minimal-movement
  property the handoff story depends on (pinned by
  tests/edge/test_router.py).
- **Override table.** An explicit ``doc -> cell`` map consulted first —
  the operator's tool for pinning a mega-doc to a dedicated cell or
  draining a hot spot by hand. An override naming an unhealthy or
  unknown cell falls through to rendezvous (a stale pin must degrade to
  correct placement, not to a black hole).
- **Health states.** ``healthy`` cells take traffic; ``draining`` cells
  (PR-9 graceful drain announced departure) and ``dead`` cells (missed
  heartbeats / session failures) are excluded from routing, and a
  re-announce heals either state back to healthy. Every change bumps
  ``epoch`` so observers (/debug/edge) can cheaply detect remaps.
"""

from __future__ import annotations

import hashlib
import time
from typing import Optional

HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"


class CellRouter:
    def __init__(
        self,
        overrides: "Optional[dict[str, str]]" = None,
        heartbeat_timeout_s: float = 10.0,
    ) -> None:
        # cell_id -> {"state": str, "since": float, "seen": float}
        self.cells: "dict[str, dict]" = {}
        self.overrides: "dict[str, str]" = dict(overrides or {})
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.epoch = 0

    # -- membership ----------------------------------------------------------

    def _transition(self, cell_id: str, state: str) -> bool:
        now = time.monotonic()
        entry = self.cells.get(cell_id)
        if entry is None:
            self.cells[cell_id] = {"state": state, "since": now, "seen": now}
            self.epoch += 1
            return True
        entry["seen"] = now
        if entry["state"] != state:
            entry["state"] = state
            entry["since"] = now
            self.epoch += 1
            return True
        return False

    def add_cell(self, cell_id: str) -> bool:
        """A cell announced itself (CELL_UP / heartbeat). Returns True
        when membership or health changed — the caller's cue to rebind
        parked docs. A draining/dead cell that re-announces heals."""
        return self._transition(cell_id, HEALTHY)

    def mark_draining(self, cell_id: str) -> bool:
        return self._transition(cell_id, DRAINING)

    def mark_dead(self, cell_id: str) -> bool:
        return self._transition(cell_id, DEAD)

    def remove_cell(self, cell_id: str) -> bool:
        if self.cells.pop(cell_id, None) is not None:
            self.epoch += 1
            return True
        return False

    def expire_stale(self) -> "list[str]":
        """Cells whose heartbeat went quiet past the timeout flip to
        dead (returned so the caller can trigger handoffs)."""
        now = time.monotonic()
        expired = [
            cell_id
            for cell_id, entry in self.cells.items()
            if entry["state"] == HEALTHY
            and now - entry["seen"] > self.heartbeat_timeout_s
        ]
        for cell_id in expired:
            self.mark_dead(cell_id)
        return expired

    def healthy_cells(self) -> "list[str]":
        return sorted(
            cell_id
            for cell_id, entry in self.cells.items()
            if entry["state"] == HEALTHY
        )

    def state_of(self, cell_id: str) -> "Optional[str]":
        entry = self.cells.get(cell_id)
        return entry["state"] if entry is not None else None

    # -- overrides -----------------------------------------------------------

    def set_override(self, doc_name: str, cell_id: str) -> None:
        self.overrides[doc_name] = cell_id
        self.epoch += 1

    def clear_override(self, doc_name: str) -> None:
        if self.overrides.pop(doc_name, None) is not None:
            self.epoch += 1

    def promote(self, doc_name: str, cell_id: str) -> None:
        """Follower → owner promotion (hot-doc replication): make
        `cell_id` the doc's owner and CLEAR any stale placement entry
        first — a stranded override naming the dead owner would shadow
        the promotion the moment that cell re-announced, re-splitting
        the doc across two owners. When the promoted cell is already
        the rendezvous winner no override is needed at all (the natural
        map IS the promotion); otherwise a fresh override pins it."""
        self.overrides.pop(doc_name, None)
        entry = self.cells.get(cell_id)
        if entry is not None and entry["state"] == HEALTHY:
            natural = self.route(doc_name)
            if natural != cell_id:
                self.overrides[doc_name] = cell_id
        self.epoch += 1

    # -- placement -----------------------------------------------------------

    @staticmethod
    def _score(doc_name: str, cell_id: str) -> int:
        digest = hashlib.blake2b(
            doc_name.encode() + b"\x00" + cell_id.encode(), digest_size=8
        ).digest()
        return int.from_bytes(digest, "big")

    def route(self, doc_name: str) -> "Optional[str]":
        """The owning cell for `doc_name`, or None when no healthy cell
        exists (callers park the doc and rebind on the next CELL_UP).
        Override precedence: an override naming a HEALTHY cell wins;
        anything else (unknown cell, draining, dead) falls through to
        rendezvous so a stale pin degrades to correct placement."""
        override = self.overrides.get(doc_name)
        if override is not None:
            entry = self.cells.get(override)
            if entry is not None and entry["state"] == HEALTHY:
                return override
        cells = self.healthy_cells()
        if not cells:
            return None
        # deterministic tie-break on the id keeps the map stable across
        # processes even in the astronomically unlikely score collision
        return max(cells, key=lambda cell: (self._score(doc_name, cell), cell))

    def route_set(self, doc_name: str, followers: int) -> "list[str]":
        """Audience-aware placement (hot-doc replication): the owner
        plus up to `followers` follower cells, owner first, followers
        in rendezvous order. Override-aware — position 0 is always
        exactly `route(doc_name)`, so the replicated and unreplicated
        answers can never disagree about the owner. Followers inherit
        HRW's minimal-movement property: cell churn moves only the
        follower slots the churned cell occupied."""
        owner = self.route(doc_name)
        if owner is None:
            return []
        if followers <= 0:
            return [owner]
        ranked = sorted(
            self.healthy_cells(),
            key=lambda cell: (self._score(doc_name, cell), cell),
            reverse=True,
        )
        return [owner] + [c for c in ranked if c != owner][:followers]

    def table(self) -> dict:
        """The `/debug/edge` routing view."""
        return {
            "epoch": self.epoch,
            "cells": {
                cell_id: {
                    "state": entry["state"],
                    "since_s": round(time.monotonic() - entry["since"], 1),
                    "seen_s": round(time.monotonic() - entry["seen"], 1),
                }
                for cell_id, entry in sorted(self.cells.items())
            },
            "overrides": dict(sorted(self.overrides.items())),
        }
