"""Frame-relay protocol between the edge tier and merge cells.

The edge↔cell hop reuses the PR-8 transport machinery wholesale: every
relay message is ONE pub/sub publish on the pipelined RESP lane
(`net/resp.py PipelinedRedisClient.publish_nowait` — per-tick coalesced
into a single write+drain, flushed-or-resent on transport failure), and
the in-process `MiniRedis` serves as the bus for tests and single-host
topologies exactly as it does for cross-instance replication.

Channel layout (all under one `prefix`, default ``hocuspocus-edge``):

==========================  =================================================
``{prefix}:cell:{cell}``    edge → cell: session OPEN/FRAME/DETACH/CLOSE
``{prefix}:edge:{edge}``    cell → edge: session FRAME/CLOSED replies
``{prefix}:cells``          control plane: CELL_UP (also the heartbeat),
                            CELL_DRAINING (PR-9 drain announces departure),
                            CELL_DOWN — the router registry rides this
==========================  =================================================

Envelope: ``[varUint kind][varString session][varString aux]
[varUint8Array payload]``. ``session`` identifies one (client socket,
cell) relay session; ``aux`` carries side data (OPEN: a JSON context
blob; CLOSED: ``code:reason``; control frames: the cell id rides the
session field). ``payload`` is a verbatim hocuspocus wire frame — the
relay never re-encodes protocol traffic, which is what keeps the edge
stateless: CRDT sync is order-insensitive and state-based (Shapiro et
al.), so at-most-once relay delivery heals through the same SyncStep1
resync exchange the replication lane uses.

Ordering: one publisher connection per process and one bounded
subscriber queue per consumer (mini_redis/_pump, real redis TCP) keep
each channel FIFO, so a session's OPEN → auth → frames arrive in send
order with no handshake round trip.
"""

from __future__ import annotations

import json
from typing import Optional

from ..crdt.encoding import Decoder, Encoder

# session-plane kinds (edge -> cell, cell -> edge)
OPEN = 0  # edge opens a relay session on a cell (aux: JSON context)
FRAME = 1  # verbatim wire frame, either direction
DETACH = 2  # edge detaches ONE doc channel from a session (aux: doc name)
CLOSE = 3  # edge closes the whole session (client socket went away)
CLOSED = 4  # cell closed the session (aux: "code:reason")

# control-plane kinds (cell -> every edge, on the control channel; the
# cell id rides the session field)
CELL_UP = 10  # liveness announce — doubles as the heartbeat
CELL_DRAINING = 11  # graceful drain started: remap my docs NOW
CELL_DOWN = 12  # orderly departure (destroy)

KIND_NAMES = {
    OPEN: "open",
    FRAME: "frame",
    DETACH: "detach",
    CLOSE: "close",
    CLOSED: "closed",
    CELL_UP: "cell_up",
    CELL_DRAINING: "cell_draining",
    CELL_DOWN: "cell_down",
}

DEFAULT_PREFIX = "hocuspocus-edge"


def cell_channel(prefix: str, cell_id: str) -> str:
    return f"{prefix}:cell:{cell_id}"


def edge_channel(prefix: str, edge_id: str) -> str:
    return f"{prefix}:edge:{edge_id}"


def control_channel(prefix: str) -> str:
    return f"{prefix}:cells"


def encode_envelope(
    kind: int, session: str, aux: str = "", payload: bytes = b""
) -> bytes:
    encoder = Encoder()
    encoder.write_var_uint(kind)
    encoder.write_var_string(session)
    encoder.write_var_string(aux)
    encoder.write_var_uint8_array(payload)
    return encoder.to_bytes()


def decode_envelope(data: bytes) -> "tuple[int, str, str, bytes]":
    decoder = Decoder(data)
    kind = decoder.read_var_uint()
    session = decoder.read_var_string()
    aux = decoder.read_var_string()
    payload = decoder.read_var_uint8_array()
    return kind, session, aux, payload


def encode_open_aux(edge_id: str, tenant: Optional[str] = None) -> str:
    aux = {"edge": edge_id}
    if tenant:
        aux["tenant"] = tenant
    return json.dumps(aux, sort_keys=True, separators=(",", ":"))


def decode_open_aux(aux: str) -> dict:
    try:
        data = json.loads(aux) if aux else {}
    except Exception:
        data = {}
    return data if isinstance(data, dict) else {}
