"""Frame-relay protocol between the edge tier and merge cells.

The edge↔cell hop reuses the PR-8 transport machinery wholesale: every
relay message is ONE pub/sub publish on the pipelined RESP lane
(`net/resp.py PipelinedRedisClient.publish_nowait` — per-tick coalesced
into a single write+drain, flushed-or-resent on transport failure), and
the in-process `MiniRedis` serves as the bus for tests and single-host
topologies exactly as it does for cross-instance replication.

Channel layout (all under one `prefix`, default ``hocuspocus-edge``):

==========================  =================================================
``{prefix}:cell:{cell}``    edge → cell: session OPEN/FRAME/DETACH/CLOSE
``{prefix}:edge:{edge}``    cell → edge: session FRAME/CLOSED replies
``{prefix}:cells``          control plane: CELL_UP (also the heartbeat),
                            CELL_DRAINING (PR-9 drain announces departure),
                            CELL_DOWN — the router registry rides this
==========================  =================================================

Envelope: ``[varUint kind][varString session][varString aux]
[varUint8Array payload]``. ``session`` identifies one (client socket,
cell) relay session; ``aux`` carries side data (OPEN: a JSON context
blob; CLOSED: ``code:reason``; control frames: the cell id rides the
session field). ``payload`` is a verbatim hocuspocus wire frame — the
relay never re-encodes protocol traffic, which is what keeps the edge
stateless: CRDT sync is order-insensitive and state-based (Shapiro et
al.), so at-most-once relay delivery heals through the same SyncStep1
resync exchange the replication lane uses.

Ordering: one publisher connection per process and one bounded
subscriber queue per consumer (mini_redis/_pump, real redis TCP) keep
each channel FIFO, so a session's OPEN → auth → frames arrive in send
order with no handshake round trip.
"""

from __future__ import annotations

import base64
import json
from typing import Optional

from ..crdt.encoding import Decoder, Encoder

# session-plane kinds (edge -> cell, cell -> edge)
OPEN = 0  # edge opens a relay session on a cell (aux: JSON context)
FRAME = 1  # verbatim wire frame, either direction
DETACH = 2  # edge detaches ONE doc channel from a session (aux: doc name)
CLOSE = 3  # edge closes the whole session (client socket went away)
CLOSED = 4  # cell closed the session (aux: "code:reason")

# control-plane kinds (cell -> every edge, on the control channel; the
# cell id rides the session field)
CELL_UP = 10  # liveness announce — doubles as the heartbeat
CELL_DRAINING = 11  # graceful drain started: remap my docs NOW
CELL_DOWN = 12  # orderly departure (destroy)
# telemetry federation (docs/guides/observability.md "fleet view"):
# every role publishes a compact periodic digest on the control channel
# (payload: JSON digest bytes; the node id rides the session field) —
# the FleetView aggregator on any subscribed peer ingests them
DIGEST = 13
# clock-offset probes (cross-tier tracing): an edge PINGs a cell's
# channel (aux: JSON {"t": sender perf_counter}); the cell answers PONG
# on the edge's channel echoing the sender stamp plus its own clock —
# the edge folds the RTT-midpoint offset estimate into the relay spans
PING = 20
PONG = 21
# cross-tier trace returns: the cell closes a traced update's lifecycle
# at the device barrier — AFTER the encode-once broadcast frame already
# left (fan-out is host-decoupled) — so the return context rides its own
# envelope back to the stamping edge (aux: {"v":1,"r":[...]}; same
# pipelined lane, per-tick coalesced). Unknown to old edges: ignored.
TRACE_RET = 22

# hot-doc replication kinds (docs/guides/hot-doc-replication.md). Two
# FOLLOW shapes share one kind, told apart by the aux keys:
#   edge → cell   aux {"d": doc, "o": owner_id} — a routing hint: "this
#                 doc's owner is `o`; follow it". When `o` names the
#                 receiving cell itself, the cell BECOMES the owner
#                 (promotion path).
#   cell → cell   aux {"d": doc, "f": follower_id, "sv": b64 state
#                 vector} — the follower subscribing at (or resyncing
#                 with) the owner; the owner answers with a REPLICA_TICK
#                 carrying the SV-diff plus its own state vector.
# REPLICA_TICK (owner → follower) aux {"d": doc, "s": seq} carries the
# owner's per-tick coalesced update; a bootstrap/resync reply adds
# {"r": 1, "sv": owner SV b64} and resets the follower's seq counter.
# A seq gap means a lost tick: the follower re-FOLLOWs with its local
# state vector — the same state-based SyncStep1 resync exchange that
# heals the relay everywhere else, never a silent divergence.
# REPLICA_PUSH (follower → owner) aux {"d": doc} forwards coalesced
# follower-local writes up to the owner, which applies them under a
# replicable origin so the next tick re-streams them to every follower.
FOLLOW = 30
UNFOLLOW = 31
REPLICA_TICK = 32
REPLICA_PUSH = 33

KIND_NAMES = {
    OPEN: "open",
    FRAME: "frame",
    DETACH: "detach",
    CLOSE: "close",
    CLOSED: "closed",
    CELL_UP: "cell_up",
    CELL_DRAINING: "cell_draining",
    CELL_DOWN: "cell_down",
    DIGEST: "digest",
    PING: "ping",
    PONG: "pong",
    TRACE_RET: "trace_return",
    FOLLOW: "follow",
    UNFOLLOW: "unfollow",
    REPLICA_TICK: "replica_tick",
    REPLICA_PUSH: "replica_push",
}

DEFAULT_PREFIX = "hocuspocus-edge"


def cell_channel(prefix: str, cell_id: str) -> str:
    return f"{prefix}:cell:{cell_id}"


def edge_channel(prefix: str, edge_id: str) -> str:
    return f"{prefix}:edge:{edge_id}"


def control_channel(prefix: str) -> str:
    return f"{prefix}:cells"


def encode_envelope(
    kind: int, session: str, aux: str = "", payload: bytes = b""
) -> bytes:
    encoder = Encoder()
    encoder.write_var_uint(kind)
    encoder.write_var_string(session)
    encoder.write_var_string(aux)
    encoder.write_var_uint8_array(payload)
    return encoder.to_bytes()


def encode_envelope_view(
    kind: int, session: str, aux: str = "", payload: bytes = b""
) -> "list[bytes | memoryview]":
    """Zero-copy envelope: the header is encoded fresh (it is tiny) but
    the payload — the encode-once broadcast frame shared by the whole
    audience — is wrapped as a memoryview, never copied. The result is a
    segment list for the pipelined publish lane (`net/resp.py
    publish_nowait` accepts segment lists and `b"".join`s them straight
    into the socket write, so the frame bytes are copied exactly once,
    INTO the kernel).

    Lifetime rule (docs/guides/native-codec.md): the segments alias the
    caller's buffer — they must be handed to the transport synchronously
    and never mutated before the flush; holders that outlive the call
    must `bytes()` them first.
    """
    encoder = Encoder()
    encoder.write_var_uint(kind)
    encoder.write_var_string(session)
    encoder.write_var_string(aux)
    encoder.write_var_uint(len(payload))
    return [encoder.to_bytes(), memoryview(payload)]


def decode_envelope(data: bytes) -> "tuple[int, str, str, bytes]":
    from ..native import get_codec

    codec = get_codec()
    if codec is not None:
        return codec.parse_envelope(data)
    decoder = Decoder(data)
    kind = decoder.read_var_uint()
    session = decoder.read_var_string()
    aux = decoder.read_var_string()
    payload = decoder.read_var_uint8_array()
    return kind, session, aux, payload


def decode_envelopes_batch(
    raws: "list[bytes]", skip_malformed: bool = False
) -> "list[tuple[int, str, str, bytes] | None]":
    """Decode a drained batch of envelopes in ONE native call
    (consecutive envelopes of the same session share one str object).
    ``skip_malformed=True`` yields None slots for undecodable entries —
    the relay's drop-and-resync contract — instead of raising."""
    codec = None
    if raws:
        from ..native import get_codec

        codec = get_codec()
    if codec is not None:
        return codec.parse_envelopes_batch(raws, skip_malformed)
    out: "list[tuple[int, str, str, bytes] | None]" = []
    for raw in raws:
        try:
            out.append(decode_envelope(raw))
        except Exception:
            if not skip_malformed:
                raise
            out.append(None)
    return out


def encode_open_aux(edge_id: str, tenant: Optional[str] = None) -> str:
    aux = {"edge": edge_id}
    if tenant:
        aux["tenant"] = tenant
    return json.dumps(aux, sort_keys=True, separators=(",", ":"))


def decode_open_aux(aux: str) -> dict:
    try:
        data = json.loads(aux) if aux else {}
    except Exception:
        data = {}
    return data if isinstance(data, dict) else {}


# -- trace-context aux (versioned, optional envelope extension) -----------
#
# FRAME envelopes may carry a trace context in the (previously unused)
# aux field — docs/guides/edge-routing.md. Edge→cell, a sampled inbound
# update stamps `{"v": 1, "id": <fleet trace id>, "e": <edge id>,
# "d": <doc>, "t0": <edge ingress stamp>, "t1": <edge publish stamp>,
# "h": 1}` (stamps are the edge's own perf_counter — opaque to the
# cell, echoed back verbatim so the edge stays stateless). Cell→edge,
# a TRACE_RET envelope closing traced updates echoes
# `{"v": 1, "r": [{...}, ...]}`: each original context plus the cell's
# receive/close stamps `tr`/`ts` (the cell's OWN clock — the edge
# reconciles via its heartbeat-RTT offset estimate), node id `n`, and
# the incremented hop counter `h`. Both directions are OPTIONAL and
# versioned: an empty/foreign/unversioned aux decodes to None and the
# frame relays exactly as before, so pre-trace envelopes keep parsing.

TRACE_AUX_VERSION = 1


def encode_trace_aux(context: dict) -> str:
    return json.dumps(
        {"v": TRACE_AUX_VERSION, **context}, sort_keys=True, separators=(",", ":")
    )


def decode_trace_aux(aux: str) -> Optional[dict]:
    """The trace context carried by a FRAME aux, or None when absent,
    malformed, or from an incompatible version (forward-compat: unknown
    versions are ignored, never an error)."""
    if not aux:
        return None
    try:
        data = json.loads(aux)
    except Exception:
        return None
    if not isinstance(data, dict) or data.get("v") != TRACE_AUX_VERSION:
        return None
    return data


# -- replica aux (FOLLOW / UNFOLLOW / REPLICA_TICK / REPLICA_PUSH) ---------
#
# Replica envelopes carry structured JSON in the aux field; state vectors
# (raw lib0 bytes) ride base64 under "sv". Malformed aux decodes to {} —
# the dispatcher drops the envelope and the follower's gap detector plus
# the FOLLOW resync exchange recover, same contract as the rest of the
# relay (at-most-once delivery healed by state-based resync).


def encode_replica_aux(**fields) -> str:
    aux = {}
    for key, value in fields.items():
        if value is None:
            continue
        if isinstance(value, (bytes, bytearray)):
            value = base64.b64encode(bytes(value)).decode("ascii")
        aux[key] = value
    return json.dumps(aux, sort_keys=True, separators=(",", ":"))


def decode_replica_aux(aux: str) -> dict:
    """The replica envelope's aux dict with any "sv" field decoded back
    to raw state-vector bytes; {} when absent or malformed."""
    try:
        data = json.loads(aux) if aux else {}
    except Exception:
        return {}
    if not isinstance(data, dict):
        return {}
    sv = data.get("sv")
    if isinstance(sv, str):
        try:
            data["sv"] = base64.b64decode(sv.encode("ascii"))
        except Exception:
            return {}
    return data
