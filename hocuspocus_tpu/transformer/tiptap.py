"""Tiptap transformer (reference `packages/transformer/src/Tiptap.ts`).

Tiptap documents are ProseMirror documents with field name "default";
schema extensions are accepted for API parity but the structural JSON
mapping needs none.
"""

from __future__ import annotations

from typing import Any, Union

from ..crdt import Doc
from .prosemirror import ProsemirrorTransformer


class Tiptap:
    def __init__(self) -> None:
        self.default_extensions: list = []

    def extensions(self, extensions: list) -> "Tiptap":
        self.default_extensions = extensions
        return self

    def from_ydoc(self, document: Doc, field_name: Union[str, list, None] = None) -> Any:
        return ProsemirrorTransformer.from_ydoc(document, field_name)

    def to_ydoc(
        self, document: Any, field_name: Union[str, list] = "default", extensions: Any = None
    ) -> Doc:
        return ProsemirrorTransformer.to_ydoc(document, field_name)


TiptapTransformer = Tiptap()
