"""ProseMirror JSON ⇄ CRDT doc transformer.

Equivalent of reference `packages/transformer/src/Prosemirror.ts` +
y-prosemirror's prosemirrorJSONToYDoc / yDocToProsemirrorJSON: maps
ProseMirror JSON structurally onto YXmlFragment/YXmlElement/YXmlText
(marks become text formatting attributes). Works without a ProseMirror
schema — the JSON shape itself drives the mapping.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Union

from ..crdt import (
    Doc,
    YXmlElement,
    YXmlFragment,
    YXmlText,
    apply_update,
    encode_state_as_update,
)


def _marks_to_attributes(marks: Optional[list[dict]]) -> dict:
    attributes: dict = {}
    for mark in marks or []:
        attributes[mark["type"]] = mark.get("attrs", {})
    return attributes


def _json_to_xml_nodes(nodes: Iterable[dict]) -> list:
    """Convert a run of ProseMirror JSON nodes to XML type instances.
    Consecutive text nodes collapse into one YXmlText (y-prosemirror
    behavior)."""
    result: list = []
    text_delta: list[dict] = []

    def flush_text() -> None:
        nonlocal text_delta
        if text_delta:
            text = YXmlText()
            # applied when the type integrates into a doc
            delta = text_delta
            text._pending.append(lambda d=delta: text.apply_delta(d))
            result.append(text)
            text_delta = []

    for node in nodes:
        if node.get("type") == "text":
            op: dict = {"insert": node.get("text", "")}
            attributes = _marks_to_attributes(node.get("marks"))
            if attributes:
                op["attributes"] = attributes
            text_delta.append(op)
        else:
            flush_text()
            element = YXmlElement(node["type"])
            for key, value in (node.get("attrs") or {}).items():
                if value is not None:
                    element.set_attribute(key, value)
            children = _json_to_xml_nodes(node.get("content") or [])
            if children:
                element.push(children)
            result.append(element)
    flush_text()
    return result


def _coalesce_strings(children: list) -> list:
    """Merge consecutive str content entries into runs.

    `to_array()` on a text-bearing type yields one str PER UTF-16
    position (ContentString.get_content semantics); emitting a text
    node per character would blow up payloads ~30x and diverge from
    the merged runs y-prosemirror produces.
    """
    out: list = []
    run: list[str] = []
    for child in children:
        if isinstance(child, str):
            run.append(child)
        else:
            if run:
                out.append("".join(run))
                run = []
            out.append(child)
    if run:
        out.append("".join(run))
    return out


def _xml_node_to_json(node: Any) -> list[dict]:
    if isinstance(node, YXmlText):
        ops = []
        for op in node.to_delta():
            entry: dict = {"type": "text", "text": op["insert"]}
            attributes = op.get("attributes")
            if attributes:
                entry["marks"] = [
                    {"type": mark_type, **({"attrs": attrs} if attrs else {})}
                    for mark_type, attrs in attributes.items()
                ]
            ops.append(entry)
        return ops
    if isinstance(node, str):
        # a plain-text root read through the XML view (e.g. the webhook
        # transforming a Y.Text document): string runs become text
        # nodes, as y-prosemirror yields for text content (callers
        # coalesce per-character content entries into runs first)
        return [{"type": "text", "text": node}] if node else []
    result: dict = {"type": node.node_name}
    attrs = node.get_attributes()
    if attrs:
        result["attrs"] = attrs
    content: list = []
    for child in _coalesce_strings(node.to_array()):
        content.extend(_xml_node_to_json(child))
    if content:
        result["content"] = content
    return [result]


class Prosemirror:
    """`to_ydoc` / `from_ydoc` between ProseMirror JSON and CRDT docs."""

    def from_ydoc(self, document: Doc, field_name: Union[str, list, None] = None) -> Any:
        if isinstance(field_name, str):
            return self._fragment_to_json(document.get_xml_fragment(field_name))
        if not field_name:
            field_name = list(document.share.keys())
        return {
            field: self._fragment_to_json(document.get_xml_fragment(field))
            for field in field_name
        }

    def _fragment_to_json(self, fragment: YXmlFragment) -> dict:
        content: list = []
        for child in _coalesce_strings(fragment.to_array()):
            content.extend(_xml_node_to_json(child))
        return {"type": "doc", "content": content}

    def to_ydoc(
        self,
        document: Any,
        field_name: Union[str, list] = "prosemirror",
        schema: Any = None,
    ) -> Doc:
        if not document:
            raise ValueError(
                "empty or invalid document passed to the transformer; "
                f"expected ProseMirror-compatible JSON, got {document!r}"
            )
        fields = [field_name] if isinstance(field_name, str) else list(field_name)
        ydoc = Doc()
        for field in fields:
            fragment = ydoc.get_xml_fragment(field)
            nodes = _json_to_xml_nodes(document.get("content") or [])
            if nodes:
                fragment.push(nodes)
        return ydoc


ProsemirrorTransformer = Prosemirror()
