from .prosemirror import Prosemirror, ProsemirrorTransformer
from .tiptap import Tiptap, TiptapTransformer

__all__ = ["Prosemirror", "ProsemirrorTransformer", "Tiptap", "TiptapTransformer"]
