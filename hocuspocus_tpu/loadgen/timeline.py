"""Process-global loadgen timeline: the live `/debug/loadgen` surface.

A scenario run is only diagnosable if it is observable from the same
`/debug/*` surfaces production uses — a failing storm phase must be
explorable while it runs, not reconstructed from a result artifact
afterwards. The runner drives this singleton (run/phase/op edges); the
`Metrics` extension serves `status()` at `GET /debug/loadgen`; phase
transitions are mirrored into the flight recorder's `__loadgen__` ring
by the runner so the two timelines can be cross-referenced.

Deliberately stdlib-only and tiny: the observability extension imports
it lazily at request time, and recording an op is one dict update plus
a bounded-deque append.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional


class LoadgenTimeline:
    """Bounded live state for the current (and last finished) run."""

    def __init__(self, max_events: int = 256) -> None:
        self.max_events = max_events
        self._run: Optional[dict] = None
        self._last_run: Optional[dict] = None
        self._events: deque = deque(maxlen=max_events)

    # -- run edges -----------------------------------------------------------

    def begin_run(
        self,
        scenario: str,
        seed: int,
        schedule_hash: str,
        phases: "list[dict]",
        time_scale: float,
        ops_total: int,
    ) -> None:
        self._run = {
            "scenario": scenario,
            "seed": seed,
            "schedule_hash": schedule_hash,
            "time_scale": time_scale,
            "started_ts": time.time(),
            "started_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "ops_total": ops_total,
            "ops_done": 0,
            "ops_failed": 0,
            "current_phase": None,
            "verdict": None,
            "phases": [
                {
                    "name": p["name"],
                    "planned_ms": p["planned_ms"],
                    "state": "pending",
                    "started_s": None,
                    "ended_s": None,
                    "ops_done": 0,
                    "ops_failed": 0,
                    "latency_p50_ms": None,
                    "latency_p99_ms": None,
                    "breaching": [],
                }
                for p in phases
            ],
        }
        self._events.clear()
        self._event("run_start", scenario=scenario, schedule_hash=schedule_hash)

    def end_run(self, verdict: str, slo: Optional[dict] = None) -> None:
        if self._run is None:
            return
        self._run["verdict"] = verdict
        self._run["current_phase"] = None
        self._run["ended_ts"] = time.time()
        if slo is not None:
            self._run["slo"] = slo
        self._event("run_end", verdict=verdict)
        self._last_run, self._run = self._run, None

    # -- phase edges ---------------------------------------------------------

    def _phase(self, name: str) -> Optional[dict]:
        if self._run is None:
            return None
        for phase in self._run["phases"]:
            if phase["name"] == name:
                return phase
        return None

    def phase_start(self, name: str) -> None:
        phase = self._phase(name)
        if phase is None:
            return
        phase["state"] = "running"
        phase["started_s"] = round(time.time() - self._run["started_ts"], 3)
        self._run["current_phase"] = name
        self._event("phase_start", phase=name)

    def phase_end(self, name: str, **summary: Any) -> None:
        phase = self._phase(name)
        if phase is None:
            return
        phase["state"] = "done"
        phase["ended_s"] = round(time.time() - self._run["started_ts"], 3)
        for key, value in summary.items():
            phase[key] = value
        if self._run["current_phase"] == name:
            self._run["current_phase"] = None
        self._event("phase_end", phase=name)

    # -- ops -----------------------------------------------------------------

    def op_done(
        self,
        phase: str,
        kind: str,
        ok: bool,
        latency_ms: Optional[float] = None,
    ) -> None:
        if self._run is not None:
            self._run["ops_done"] += 1
            if not ok:
                self._run["ops_failed"] += 1
            row = self._phase(phase)
            if row is not None:
                row["ops_done"] += 1
                if not ok:
                    row["ops_failed"] += 1
        if not ok or latency_ms is not None:
            # measured and failed ops are the interesting ones on a live
            # timeline; fire-and-forget background edits stay aggregate
            self._event(
                "op",
                phase=phase,
                kind=kind,
                ok=ok,
                latency_ms=None if latency_ms is None else round(latency_ms, 3),
            )

    def note_breach(self, phase: str, target: str) -> None:
        row = self._phase(phase)
        if row is not None and target not in row["breaching"]:
            row["breaching"].append(target)
            self._event("slo_breach", phase=phase, target=target)

    def _event(self, event: str, **attrs: Any) -> None:
        entry = {"ts": time.time(), "event": event}
        entry.update(attrs)
        self._events.append(entry)

    # -- reading -------------------------------------------------------------

    def status(self) -> dict:
        """JSON-able rollup for `GET /debug/loadgen`."""
        run = None
        if self._run is not None:
            run = dict(self._run)
            run["elapsed_s"] = round(time.time() - run["started_ts"], 3)
        return {
            "active": self._run is not None,
            "run": run,
            "last_run": self._last_run,
            "events": list(self._events),
        }

    def clear(self) -> None:
        self._run = None
        self._last_run = None
        self._events.clear()


_default = LoadgenTimeline()


def get_loadgen_timeline() -> LoadgenTimeline:
    return _default
