"""CLI: run a scenario and emit its SLO-verdict artifact.

    python -m hocuspocus_tpu.loadgen --scenario smoke --seed 7
    python -m hocuspocus_tpu.loadgen --list
    python -m hocuspocus_tpu.loadgen --scenario flash_crowd \\
        --record /tmp/storm.schedule.json           # compile only
    python -m hocuspocus_tpu.loadgen --replay /tmp/storm.schedule.json

Prints ONE JSON line (the result artifact) on stdout; progress goes to
stderr. Exit code: 0 = SLO verdict pass, 1 = verdict fail, 2 = the run
itself errored. The artifact's ``schedule_hash`` is deterministic for a
given (scenario, seed): two runs are comparable iff hashes match.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from .runner import ScenarioRunner
from .scenario import Schedule
from .scenarios import SCENARIOS, get_scenario


def _progress(msg: str) -> None:
    print(f"[loadgen] {msg}", file=sys.stderr, flush=True)


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hocuspocus_tpu.loadgen",
        description="Scenario traffic simulator with an SLO burn-rate verdict.",
    )
    parser.add_argument("--scenario", help="scenario name (see --list)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--time-scale",
        type=float,
        default=1.0,
        help="compress logical time by this factor (2.0 = run twice as fast)",
    )
    parser.add_argument(
        "--record",
        metavar="PATH",
        help="compile and write the schedule (canonical JSON) without running",
    )
    parser.add_argument(
        "--replay",
        metavar="PATH",
        help="run a previously recorded schedule byte-identically",
    )
    parser.add_argument("--out", metavar="PATH", help="also write the artifact here")
    parser.add_argument(
        "--list", action="store_true", help="list known scenarios and exit"
    )
    args = parser.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            scenario = get_scenario(name)
            print(f"{name:18s} {scenario.description}")
        return 0

    if args.replay:
        with open(args.replay) as fh:
            schedule = Schedule.from_json(fh.read())
        _progress(
            f"replaying {args.replay} (hash {schedule.schedule_hash[:12]}...)"
        )
    else:
        if not args.scenario:
            parser.error("--scenario (or --replay/--list) is required")
        schedule = get_scenario(args.scenario).compile(args.seed)

    if args.record:
        with open(args.record, "w") as fh:
            fh.write(schedule.to_json())
        print(json.dumps(schedule.summary()))
        return 0

    # scenario runs are a CPU-first tool: never let an absent TPU tunnel
    # hang the verdict (bench_capture drives the on-chip flavor with the
    # env it probed)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    runner = ScenarioRunner(
        schedule, time_scale=args.time_scale, progress=_progress
    )
    try:
        result = asyncio.run(runner.run())
    except Exception as error:  # noqa: BLE001 — the artifact IS the report
        print(
            json.dumps(
                {
                    "metric": "scenario_slo_verdict",
                    "scenario": schedule.scenario,
                    "seed": schedule.seed,
                    "schedule_hash": schedule.schedule_hash,
                    "verdict": "error",
                    "error": repr(error)[:500],
                }
            )
        )
        return 2
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(result, fh, indent=1)
    print(json.dumps(result))
    return 0 if result["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
