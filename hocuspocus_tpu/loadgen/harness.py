"""Socket-free at-scale load harness for the served merge plane.

The reference's scale story is doc-sharding across instances
(`docs/guides/scalability.md:7-14`), but OS sockets cap any in-process
measurement near 4k docs (fd limits). This harness drives a
config4-shaped population — live served docs with writers, sampled
readers, steady background load, and optional cross-instance Redis
fan-out — through REAL server objects over `InProcessProviderSocket`,
so the 100k-doc regime is measurable in CI and on-chip (`bench.py`
reuses it for the served p99 metric).

Everything on the path is production code: providers run the full
auth/SyncStep1/2/awareness pipeline, the server runs the full hook
chain, and docs are served by `ShardedTpuMergeExtension` planes. Only
the network framing (websocket upgrade + TCP) is absent.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

import numpy as np

from ..aio import await_synced
from ..provider import HocuspocusProvider
from ..provider.inprocess import InProcessProviderSocket
from ..server import Configuration, Server
from ..tpu import ShardedTpuMergeExtension, TpuMergeExtension


class ServedLoadHarness:
    """One measured run of the served-plane topology.

    Parameters:
    - num_docs: live documents (each gets a writer provider).
    - instances: server instances; >1 wires them through Redis
      (mini_redis unless REDIS_HOST targets a real one) and places the
      sampled readers on the SECOND instance so the timed path crosses
      the fan-out, exactly like benchmarks/config4.
    - sampled: docs that get a reader and are latency-timed.
    - shards / shard_rows / capacity / flush_interval_ms: plane layout
      per instance (rows must exceed num_docs/shards + hash skew).
    - docs_per_socket: provider multiplexing width per in-process socket.
    - seed: RNG seed behind every random choice the harness makes
      (timed-edit sizes, background payload widths); recorded in the
      result dict so any run is reproducible from its artifact.
    """

    def __init__(
        self,
        num_docs: int = 1024,
        instances: int = 1,
        edges: int = 0,
        cells: int = 0,
        sampled: int = 32,
        edits: int = 200,
        shards: int = 4,
        devices: int = 0,
        multi_device: "Optional[dict]" = None,
        shard_rows: Optional[int] = None,
        capacity: int = 1024,
        flush_interval_ms: float = 2.0,
        docs_per_socket: int = 512,
        replica_watermark: "Optional[int]" = None,
        sync_timeout: float = 600.0,
        background_fraction: int = 16,
        with_metrics: bool = False,
        seed: int = 0,
        overload: "Optional[dict]" = None,
        autoscale: "Optional[dict]" = None,
        anti_entropy_s: "Optional[float]" = None,
        progress=None,
    ) -> None:
        self.num_docs = num_docs
        self.instances = instances
        # edge topology (docs/guides/edge-routing.md): edges > 0 boots
        # `edges` stateless EdgeServers + `cells` merge-cell servers
        # over one mini_redis relay bus; self.servers then holds the
        # EDGE servers (providers terminate there) and self.extensions
        # the cells' plane extensions (merge capacity lives there)
        self.edges = int(edges)
        self.cells = int(cells) if edges else 0
        self.sampled = min(sampled, num_docs)
        self.edits = edits
        self.shards = shards
        # multi-device cell plane: devices > 1 serves each instance from
        # per-chip merge cells (tpu/cells.py) instead of same-chip
        # shards; multi_device carries rebalancer tuning (interval,
        # ratio, batch) straight into the extension
        self.devices = int(devices)
        self.multi_device = dict(multi_device or {})
        partitions = self.devices if self.devices > 1 else max(shards, 1)
        self.shard_rows = shard_rows or max(int(num_docs / partitions * 1.25), 64)
        self.capacity = capacity
        self.flush_interval_ms = flush_interval_ms
        self.docs_per_socket = docs_per_socket
        # hot-doc replication knob (docs/guides/hot-doc-replication.md):
        # None keeps the gateway default; mega-audience scenarios set a
        # CI-scale watermark so a small join wave grows follower cells
        self.replica_watermark = replica_watermark
        self.sync_timeout = sync_timeout
        self.background_fraction = background_fraction
        # with_metrics: add a Metrics extension per instance (enables
        # the wire telemetry singleton and binds each plane's trace
        # book to the e2e histogram) — the bench's wire_load pass reads
        # ingress-stage quantiles off metrics[0] after the run
        self.with_metrics = with_metrics
        self.metrics: list[Any] = []
        # overload: per-instance OverloadExtension options — the
        # scenario runner's seam for driving the degradation ladder
        # (docs/guides/overload.md). anti_entropy_s tightens the Redis
        # extension's anti-entropy cadence so partition-heal scenarios
        # reconverge inside CI-scale phases.
        self.overload = overload
        # autoscale: FleetControllerExtension tuning per plane-holding
        # instance (docs/guides/elastic-fleet.md) — only meaningful with
        # devices > 1, where the controller can park/activate cells
        self.autoscale = autoscale
        self.fleet_controllers: list[Any] = []
        self.anti_entropy_s = anti_entropy_s
        # seed: every random choice the harness makes (timed edit sizes,
        # background payload widths) draws from a seeded generator, and
        # the seed is stamped into the result dict — any bench or
        # scenario run is reproducible from its artifact alone. The
        # timed path and the concurrent background task get INDEPENDENT
        # streams: sharing one would interleave draws by event-loop
        # timing, making the recorded seed non-reproducing.
        self.seed = int(seed)
        self.rng = np.random.default_rng([self.seed, 0])
        self._bg_rng = np.random.default_rng([self.seed, 1])
        self._progress = progress or (lambda msg: None)

        self.servers: list[Server] = []
        self.extensions: list[Any] = []
        self.cell_servers: list[Server] = []
        self.cell_ingresses: list[Any] = []
        self.edge_gateways: list[Any] = []
        self.sockets: list[InProcessProviderSocket] = []
        self.writers: list[HocuspocusProvider] = []
        self.readers: list[HocuspocusProvider] = []
        self._mini_redis = None
        self._bg_len: list[int] = []

    @property
    def mini_redis(self):
        """The in-process MiniRedis backing a multi-instance run (None
        single-instance or against a real REDIS_HOST) — the scenario
        runner's replication-lag injection point."""
        return self._mini_redis

    # -- topology ----------------------------------------------------------

    def _plane_extension(self) -> "tuple[Any, list]":
        """One serve-mode plane extension + its planes, per the layout."""
        if self.devices > 1:
            from ..tpu import MultiDeviceMergeExtension

            ext = MultiDeviceMergeExtension(
                devices=self.devices,
                num_docs=self.shard_rows,
                capacity=self.capacity,
                flush_interval_ms=self.flush_interval_ms,
                serve=True,
                **self.multi_device,
            )
            return ext, [cell.plane for cell in ext.cells]
        if self.shards > 1:
            ext = ShardedTpuMergeExtension(
                shards=self.shards,
                num_docs=self.shard_rows,
                capacity=self.capacity,
                flush_interval_ms=self.flush_interval_ms,
                serve=True,
            )
            return ext, [s.plane for s in ext.shards]
        ext = TpuMergeExtension(
            num_docs=self.shard_rows,
            capacity=self.capacity,
            flush_interval_ms=self.flush_interval_ms,
            serve=True,
        )
        return ext, [ext.plane]

    async def _start_edge_topology(self) -> None:
        """The split front door: `cells` merge cells + `edges` stateless
        edge servers over one mini_redis relay bus. self.servers = the
        EDGE servers (writers land on edge 0, readers on edge 1 — the
        timed path crosses edge->cell->edge), self.extensions = the
        cells' plane extensions (merge capacity)."""
        from ..edge import CellIngressExtension, EdgeGatewayExtension, EdgeServer
        from ..net.mini_redis import MiniRedis

        self._mini_redis = await MiniRedis().start()
        host, port = "127.0.0.1", self._mini_redis.port
        for i in range(max(self.cells, 1)):
            plane_ext, planes = self._plane_extension()
            ingress = CellIngressExtension(
                cell_id=self.cell_identifier(i),
                host=host,
                port=port,
                announce_interval_s=0.25,
            )
            extensions: list[Any] = [ingress]
            if self.overload is not None:
                from ..server.overload import OverloadExtension

                extensions.append(OverloadExtension(**self.overload))
            if self.with_metrics:
                from ..observability import Metrics

                metrics = Metrics()
                self.metrics.append(metrics)
                extensions.append(metrics)
            extensions.append(plane_ext)
            if self.autoscale is not None and self.devices > 1:
                from ..fleet import FleetControllerExtension

                fleet_ext = FleetControllerExtension(**self.autoscale)
                self.fleet_controllers.append(fleet_ext)
                extensions.append(fleet_ext)
            server = Server(Configuration(quiet=True, extensions=extensions))
            await server.listen(port=0)
            for plane in planes:
                plane.warmup_compiles()
            self.cell_servers.append(server)
            self.cell_ingresses.append(ingress)
            self.extensions.append(plane_ext)
        for i in range(self.edges):
            gateway_options: "dict[str, Any]" = {
                "edge_id": f"loadgen-edge-{i}",
                "host": host,
                "port": port,
            }
            if self.replica_watermark is not None:
                gateway_options["replica_watermark"] = int(self.replica_watermark)
            gateway_ext = EdgeGatewayExtension(**gateway_options)
            server = EdgeServer(
                Configuration(quiet=True, extensions=[gateway_ext])
            )
            await server.listen(port=0)
            self.servers.append(server)
            self.edge_gateways.append(gateway_ext.gateway)
        # population sync storms must not race discovery: every edge
        # sees every cell before providers connect
        deadline = time.perf_counter() + 10.0
        want = len(self.cell_servers)
        for gateway in self.edge_gateways:
            while len(gateway.router.healthy_cells()) < want:
                if time.perf_counter() > deadline:
                    raise TimeoutError(
                        f"edge {gateway.edge_id} saw "
                        f"{gateway.router.healthy_cells()} of {want} cells"
                    )
                await asyncio.sleep(0.02)

    async def drain_cell(self, index: int) -> dict:
        """Gracefully drain merge cell `index` (the scenario `drain`
        op): the cell announces departure, edges remap its docs and
        re-establish sessions on the survivors — no client-visible
        disconnect beyond the resync exchange."""
        server = self.cell_servers[index]
        return await server.drain(timeout_secs=10.0)

    def cell_identifier(self, index: int) -> str:
        return f"loadgen-cell-{index}"

    def plane_health(self) -> "list[dict]":
        """Plane counters per merge-capacity holder (instances in the
        replicated topology, cells in the edge topology)."""
        return [dict(self._counters(i)) for i in range(len(self.extensions))]

    async def _start_servers(self) -> None:
        import os

        if self.edges > 0:
            await self._start_edge_topology()
            return
        redis_cfg = None
        if self.instances > 1:
            host = os.environ.get("REDIS_HOST")
            if host:
                redis_cfg = (host, int(os.environ.get("REDIS_PORT", 6379)))
            else:
                from ..net.mini_redis import MiniRedis

                self._mini_redis = await MiniRedis().start()
                redis_cfg = ("127.0.0.1", self._mini_redis.port)
        for i in range(self.instances):
            ext, planes = self._plane_extension()
            extensions: list[Any] = []
            if redis_cfg is not None:
                from ..extensions import Redis

                redis_ext = Redis(
                    host=redis_cfg[0],
                    port=redis_cfg[1],
                    identifier=self.redis_identifier(i),
                    disconnect_delay=100,
                )
                if self.anti_entropy_s is not None:
                    redis_ext.plane_anti_entropy_seconds = float(
                        self.anti_entropy_s
                    )
                extensions.append(redis_ext)
            if self.overload is not None:
                from ..server.overload import OverloadExtension

                extensions.append(OverloadExtension(**self.overload))
            if self.with_metrics:
                from ..observability import Metrics

                metrics = Metrics()
                self.metrics.append(metrics)
                extensions.append(metrics)
            extensions.append(ext)
            if self.autoscale is not None and self.devices > 1:
                from ..fleet import FleetControllerExtension

                fleet_ext = FleetControllerExtension(**self.autoscale)
                self.fleet_controllers.append(fleet_ext)
                extensions.append(fleet_ext)
            server = Server(Configuration(quiet=True, extensions=extensions))
            await server.listen(port=0)
            for plane in planes:
                plane.warmup_compiles()
            self.servers.append(server)
            self.extensions.append(ext)

    def redis_identifier(self, instance: int) -> str:
        """The identifier instance `instance`'s Redis extension frames
        its publishes with — the mini_redis partition-injection key."""
        return f"loadgen-{instance}"

    def _counters(self, instance: int = 0) -> dict:
        ext = self.extensions[instance]
        return ext.counters if hasattr(ext, "counters") else ext.plane.counters

    async def _connect_writers(self) -> None:
        """Writers for every doc on instance 0, multiplexed over
        in-process sockets, synced chunk by chunk (one chunk's sync
        storm completes before the next connects — the same pacing a
        production rollout's connection ramp gives the server)."""
        server = self.servers[0]
        t0 = time.perf_counter()
        for base in range(0, self.num_docs, self.docs_per_socket):
            socket = InProcessProviderSocket(server)
            self.sockets.append(socket)
            chunk = []
            for d in range(base, min(base + self.docs_per_socket, self.num_docs)):
                p = HocuspocusProvider(name=f"load-{d}", websocket_provider=socket)
                p.attach()
                chunk.append(p)
            self.writers.extend(chunk)
            await await_synced(chunk, self.sync_timeout, f"writer chunk @{base}")
            if base % (self.docs_per_socket * 8) == 0:
                rate = len(self.writers) / (time.perf_counter() - t0)
                self._progress(
                    f"writers {len(self.writers)}/{self.num_docs} ({rate:.0f}/s)"
                )
        self._bg_len = [0] * self.num_docs

    async def _connect_readers(self) -> None:
        # second instance (replicated) or second edge (edge topology):
        # the timed path crosses the fan-out either way
        server = self.servers[1 if len(self.servers) > 1 else 0]
        socket = InProcessProviderSocket(server)
        self.sockets.append(socket)
        for d in range(self.sampled):
            p = HocuspocusProvider(name=f"load-{d}", websocket_provider=socket)
            p.attach()
            self.readers.append(p)
        await await_synced(self.readers, self.sync_timeout, "readers")

    # -- measurement -------------------------------------------------------

    async def timed_edit(
        self,
        doc: int,
        size: int,
        timeout_s: float = 30.0,
        raise_on_timeout: bool = True,
    ) -> "Optional[float]":
        """Writer inserts `size` units into sampled doc `doc`; returns
        seconds until the reader's doc shows the grown text (None on
        timeout when not raising). Event-driven: woken by reader doc
        updates. Shared by the bench edit loop and the scenario runner —
        the straggler-safe measurement logic must exist exactly once.

        The target is the WRITER's post-insert length: after a swallowed
        straggler, a reader-relative target (+size over current reader
        length) would be satisfied by the straggler's late bytes and
        record a bogus ~0 latency; the writer high-water mark requires
        THIS edit to have landed."""
        wtext = self.writers[doc].document.get_text("body")
        rdoc = self.readers[doc].document
        rtext = rdoc.get_text("body")
        expected = len(wtext) + size
        wake = asyncio.Event()
        handler = lambda *args: wake.set()  # noqa: E731
        rdoc.on("update", handler)
        try:
            t0 = time.perf_counter()
            wtext.insert(len(wtext), "x" * size)
            while len(rtext) < expected:
                if time.perf_counter() - t0 > timeout_s:
                    if raise_on_timeout:
                        raise TimeoutError(
                            f"edit on doc {doc} never observed by reader"
                        )
                    return None
                wake.clear()
                try:
                    await asyncio.wait_for(wake.wait(), timeout=0.25)
                except asyncio.TimeoutError:
                    pass
            return time.perf_counter() - t0
        finally:
            rdoc.off("update", handler)

    async def _one_edit(self, i: int) -> float:
        """One bench-loop edit: rng-sized insert on the i-th sampled doc."""
        return await self.timed_edit(
            i % self.sampled, int(self.rng.integers(8, 25))
        )

    async def _background_load(self, stop: asyncio.Event) -> None:
        """Steady inserts across ~1/background_fraction of the
        non-sampled population per tick, so flushes run at real batch
        width during the timed samples."""
        tick = 0
        n = self.background_fraction
        while not stop.is_set():
            for d in range(self.sampled + tick % n, self.num_docs, n):
                width = int(self._bg_rng.integers(4, 13))
                self.writers[d].document.get_text("body").insert(
                    self._bg_len[d], "y" * width
                )
                self._bg_len[d] += width
                await asyncio.sleep(0)
                if stop.is_set():
                    return
            tick += 1
            await asyncio.sleep(0.01)

    async def run(self, budget_s: float = 600.0) -> dict:
        """Build the topology, measure, tear down; returns the metrics
        dict (config4-shaped: served p99 + plane health)."""
        t_start = time.perf_counter()
        try:
            self._progress(
                f"starting {self.instances} instance(s), "
                f"{self.shards}x{self.shard_rows}x{self.capacity} planes"
            )
            await self._start_servers()
            await self._connect_writers()
            await self._connect_readers()
            self._progress("population synced; warming sampled docs")

            for i in range(self.sampled):
                await self._one_edit(i)

            stop = asyncio.Event()
            load_task = asyncio.ensure_future(self._background_load(stop))
            lat: list[float] = []
            stragglers = 0
            try:
                deadline = t_start + budget_s * 0.8
                for i in range(self.edits):
                    try:
                        lat.append(await self._one_edit(i))
                    except TimeoutError:
                        # one straggler must not discard the whole run's
                        # samples (a 100k-doc pass costs ~20 min); give
                        # up only when stragglers dominate
                        stragglers += 1
                        if stragglers > 3 or not lat:
                            raise
                    if time.perf_counter() > deadline and len(lat) >= 50:
                        break
            finally:
                stop.set()
                await load_task

            counters = [dict(self._counters(i)) for i in range(self.instances)]
            if counters[0]["plane_broadcasts"] <= 0:
                raise RuntimeError(f"plane never served: {counters[0]}")
            lat_ms = np.array(lat) * 1000
            return {
                "metric": "served_merge_to_broadcast_p99_ms",
                "value": round(float(np.percentile(lat_ms, 99)), 2),
                "unit": "ms",
                "extra": {
                    "docs": self.num_docs,
                    "seed": self.seed,
                    "instances": self.instances,
                    "cross_instance": self.instances > 1,
                    "shards": self.shards,
                    "shard_rows": self.shard_rows,
                    "capacity": self.capacity,
                    "sampled_docs": self.sampled,
                    "samples": len(lat),
                    "straggler_timeouts": stragglers,
                    "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
                    "served_docs": [
                        self.extensions[i].served_docs()
                        if hasattr(self.extensions[i], "served_docs")
                        else len(self.extensions[i]._docs)
                        for i in range(self.instances)
                    ],
                    "plane_health": counters,
                    "transport": "in-process",
                    "setup_s": round(time.perf_counter() - t_start, 1),
                },
            }
        finally:
            await self._teardown()

    async def _teardown(self) -> None:
        for p in self.writers + self.readers:
            p.destroy()
        for socket in self.sockets:
            socket.destroy()
        # let the destroy-close tasks run before the servers go away
        await asyncio.sleep(0)
        for server in self.servers:
            await server.destroy()
        for server in self.cell_servers:
            await server.destroy()
        if self._mini_redis is not None:
            await self._mini_redis.stop()


async def run_served_load(**kwargs) -> dict:
    """Convenience wrapper: build + run a ServedLoadHarness."""
    budget_s = kwargs.pop("budget_s", 600.0)
    return await ServedLoadHarness(**kwargs).run(budget_s=budget_s)
