"""Declarative scenario engine: composable phase-tagged traffic programs.

The north star ("heavy traffic from millions of users", "as many
scenarios as you can imagine") cannot be evidenced by single-shape
bench passes — it needs *named, replayable production mixes* judged by
the SLO engine. This module is the declarative half of that harness:

- a **Scenario** is a population (docs, instances, shards, an optional
  mega-doc) plus an ordered list of **PhaseSpec**s, each a traffic
  program (a pure generator function) with its own SLO thresholds;
- ``Scenario.compile(seed)`` expands the phases into a **Schedule** — a
  flat, sorted op-stream of ``OpEvent``s stamped with a canonical
  SHA-256 **schedule hash**. Compilation is purely a function of
  (scenario, seed): the same seed always yields the same bytes, so a
  recorded schedule replays byte-identically and two runs are
  comparable iff their hashes match;
- the execution half (``runner.ScenarioRunner``) drives a Schedule
  through the real-server ``ServedLoadHarness`` path and judges it with
  multi-window burn rates (docs/guides/load-testing.md).

Op kinds (the whole DSL — small on purpose):

==========  ============================================================
``edit``    writer inserts ``size`` units into doc ``doc``; measured
            end-to-end when the doc is sampled (writer→reader observe)
            — unless ``value`` is nonzero (fire-and-forget background
            traffic even on a sampled doc, e.g. during a partition)
``join``    a new provider joins doc ``doc`` (time-to-synced measured)
``leave``   the oldest scenario-joined provider on doc ``doc`` leaves
``reconnect`` drop + rejoin a provider on doc ``doc`` (resync measured)
``lag``     set cross-instance replication latency to ``value`` ms
            (mini_redis injection; no-op on single-instance runs)
``partition`` ``value`` 1 = one-way-partition instance 0's publisher at
            the mini_redis hop (its publishes blackhole, accounted);
            0 = heal — anti-entropy then reconverges the instances
``overload`` inject ``value`` rungs of synthetic pressure into the
            overload ladder (server/overload.py; 1=brownout1 … 3=red,
            0 clears) — drives shed/admission behavior deterministically
``drain``   gracefully drain merge cell index ``value`` mid-run (edge
            topologies only: the cell announces departure, the router
            remaps its docs, edges re-establish sessions transparently)
==========  ============================================================

Everything here is stdlib-only and import-light: compiling and hashing
schedules must work in tools (bench_capture, tests) without touching
jax or the server stack.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

SCHEDULE_VERSION = 1

OP_KINDS = (
    "edit",
    "join",
    "leave",
    "reconnect",
    "lag",
    "partition",
    "overload",
    "drain",
)


@dataclass(frozen=True)
class OpEvent:
    """One scheduled traffic event, at a logical offset from run start."""

    at_ms: int
    phase: str
    kind: str
    doc: int = 0
    size: int = 0
    value: int = 0

    def row(self) -> list:
        return [self.at_ms, self.phase, self.kind, self.doc, self.size, self.value]

    @classmethod
    def from_row(cls, row: Sequence) -> "OpEvent":
        return cls(
            at_ms=int(row[0]),
            phase=str(row[1]),
            kind=str(row[2]),
            doc=int(row[3]),
            size=int(row[4]),
            value=int(row[5]),
        )


@dataclass
class PhaseSpec:
    """One phase: a traffic program plus the SLO it must meet.

    ``gen(rng, scenario, phase)`` returns this phase's OpEvents with
    ``at_ms`` RELATIVE to the phase start; compile offsets and sorts
    them. Each phase gets its own deterministic sub-rng, so editing one
    phase's program never perturbs another's schedule.

    SLO knobs become per-phase ``SloTarget``s on the run's engine:
    - ``slo_e2e_ms`` / ``slo_objective``: `objective` of this phase's
      measured latencies must complete within the threshold,
    - ``error_objective``: fraction of this phase's ops that must
      succeed (timeouts and refused ops are the bad events).
    """

    name: str
    duration_ms: int
    gen: Callable[[random.Random, "Scenario", "PhaseSpec"], "list[OpEvent]"]
    slo_e2e_ms: float = 250.0
    slo_objective: float = 0.95
    error_objective: float = 0.99

    def spec_row(self) -> dict:
        return {
            "name": self.name,
            "duration_ms": self.duration_ms,
            "slo_e2e_ms": self.slo_e2e_ms,
            "slo_objective": self.slo_objective,
            "error_objective": self.error_objective,
        }


@dataclass
class Scenario:
    """A named production mix: population + ordered phases."""

    name: str
    phases: "list[PhaseSpec]"
    num_docs: int = 32
    sampled: int = 8
    instances: int = 1
    # edge topology (docs/guides/edge-routing.md): when edges > 0 the
    # runner boots `edges` stateless edge servers + `cells` merge cells
    # over one relay bus instead of `instances` replicated servers;
    # writers connect to edge 0, readers to edge 1 (cross-edge path)
    edges: int = 0
    cells: int = 0
    shards: int = 1
    # multi-device cell plane (docs/guides/multi-device.md): devices > 1
    # serves each instance from per-chip merge cells with load-aware
    # placement; params["multi_device"] tunes the rebalancer
    devices: int = 0
    capacity: int = 512
    shard_rows: Optional[int] = None
    docs_per_socket: int = 64
    flush_interval_ms: float = 2.0
    # mega-doc workloads: doc 0 takes outsized edits; capacity must hold it
    mega_doc: bool = False
    description: str = ""
    # free-form knobs a generator may read (kept in the hash input)
    params: dict = field(default_factory=dict)

    def population(self) -> dict:
        return {
            "num_docs": self.num_docs,
            "sampled": self.sampled,
            "instances": self.instances,
            "edges": self.edges,
            "cells": self.cells,
            "shards": self.shards,
            "devices": self.devices,
            "capacity": self.capacity,
            "shard_rows": self.shard_rows,
            "docs_per_socket": self.docs_per_socket,
            "flush_interval_ms": self.flush_interval_ms,
            "mega_doc": self.mega_doc,
            "params": self.params,
        }

    def compile(self, seed: int = 0) -> "Schedule":
        """Expand phases into a deterministic, hash-stamped Schedule."""
        ops: "list[OpEvent]" = []
        offset = 0
        phase_index = {phase.name: i for i, phase in enumerate(self.phases)}
        for index, phase in enumerate(self.phases):
            # a string-seeded Random is stable across processes and
            # platforms (seeded via sha512, unlike hash()): phase
            # schedules depend only on (seed, phase position, name)
            rng = random.Random(f"{self.name}/{seed}/{index}/{phase.name}")
            for op in phase.gen(rng, self, phase):
                if op.kind not in OP_KINDS:
                    raise ValueError(f"unknown op kind {op.kind!r} in {phase.name}")
                # clamp STRICTLY inside the phase window: an op landing
                # exactly on the boundary would share a timestamp with
                # the next phase's first op, and the runner's
                # phase-advance walk requires phase-monotonic order
                at = offset + max(min(op.at_ms, phase.duration_ms - 1), 0)
                ops.append(
                    OpEvent(at, phase.name, op.kind, op.doc, op.size, op.value)
                )
            offset += phase.duration_ms
        # stable order: time, then PHASE POSITION (never the phase name
        # — alphabetical ties across a boundary would break the runner's
        # monotonic phase walk), then the row as a final tie-break so
        # the order never depends on generator emission order
        ops.sort(key=lambda op: (op.at_ms, phase_index[op.phase], op.row()))
        return Schedule(
            scenario=self.name,
            seed=seed,
            population=self.population(),
            phases=[phase.spec_row() for phase in self.phases],
            ops=ops,
        )


class Schedule:
    """A compiled, replayable op-stream with a canonical content hash."""

    def __init__(
        self,
        scenario: str,
        seed: int,
        population: dict,
        phases: "list[dict]",
        ops: "list[OpEvent]",
    ) -> None:
        self.scenario = scenario
        self.seed = seed
        self.population = population
        self.phases = phases
        self.ops = ops

    @property
    def total_ms(self) -> int:
        return sum(int(phase["duration_ms"]) for phase in self.phases)

    def canonical_bytes(self) -> bytes:
        """The hash input AND the serialized form: one byte stream, so
        "replays byte-identically" is checkable by construction."""
        payload = {
            "version": SCHEDULE_VERSION,
            "scenario": self.scenario,
            "seed": self.seed,
            "population": self.population,
            "phases": self.phases,
            "ops": [op.row() for op in self.ops],
        }
        return json.dumps(
            payload, sort_keys=True, separators=(",", ":")
        ).encode("utf-8")

    @property
    def schedule_hash(self) -> str:
        return hashlib.sha256(self.canonical_bytes()).hexdigest()

    def to_json(self) -> str:
        return self.canonical_bytes().decode("utf-8")

    @classmethod
    def from_json(cls, text: "str | bytes") -> "Schedule":
        data = json.loads(text)
        if data.get("version") != SCHEDULE_VERSION:
            raise ValueError(
                f"schedule version {data.get('version')!r} != {SCHEDULE_VERSION}"
            )
        return cls(
            scenario=data["scenario"],
            seed=int(data["seed"]),
            population=data["population"],
            phases=data["phases"],
            ops=[OpEvent.from_row(row) for row in data["ops"]],
        )

    def summary(self) -> dict:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "schedule_hash": self.schedule_hash,
            "phases": [phase["name"] for phase in self.phases],
            "total_ms": self.total_ms,
            "ops": len(self.ops),
        }
