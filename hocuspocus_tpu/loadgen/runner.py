"""Scenario execution: drive a compiled Schedule through real servers.

The runner is the bridge between the declarative half (scenario.py) and
the verdict: it boots the ``ServedLoadHarness`` topology the schedule's
population describes (real Server objects, full provider pipeline,
serve-mode merge planes, mini_redis when cross-instance), executes the
op-stream with wall-clock pacing (``time_scale`` compresses logical
time), and judges the run with the PR-6 :class:`SloEngine`:

- every phase registers TWO targets on one run-scoped engine — a
  latency objective over the phase's measured end-to-end edits/joins
  and an op-success objective over its measured op outcomes;
- the engine samples on a cadence throughout the run; a target whose
  burn rate exceeds the alert threshold on EVERY window (the
  multi-window rule) is **latched** as breached the moment it happens —
  the verdict cannot un-breach when the window later slides past;
- the run's verdict IS that latched breach status: ``pass`` iff no
  target ever breached.

Live observability: the runner narrates into the process-global
loadgen timeline (``GET /debug/loadgen``) and mirrors run/phase edges
into the flight recorder's ``__loadgen__`` ring, so a failing scenario
is diagnosable from the same ``/debug/*`` surfaces production uses.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

import numpy as np

from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import Histogram
from ..observability.slo import SloEngine, SloTarget, latency_slo
from ..observability.wire import get_wire_telemetry
from ..provider import HocuspocusProvider
from ..provider.inprocess import InProcessProviderSocket
from .harness import ServedLoadHarness
from .scenario import Schedule
from .timeline import get_loadgen_timeline

# bucket bounds the phase SLO thresholds snap to: scenario thresholds
# (0.5s/1s/2s defaults) sit EXACTLY on bounds so good/bad counting is
# bucket-exact (observability/slo.py snap_to_bucket)
_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0,
)


class ScenarioRunner:
    """One measured, SLO-judged execution of a compiled Schedule."""

    def __init__(
        self,
        schedule: Schedule,
        time_scale: float = 1.0,
        op_timeout_s: float = 15.0,
        alert_burn_rate: float = 14.4,
        with_metrics: bool = True,
        progress=None,
    ) -> None:
        self.schedule = schedule
        self.time_scale = max(float(time_scale), 1e-6)
        self.op_timeout_s = op_timeout_s
        self._progress = progress or (lambda msg: None)

        pop = schedule.population
        params = pop.get("params") or {}
        # scenario-scoped overload control plane: params["overload"]
        # installs an OverloadExtension per instance with the given
        # tuning (docs/guides/overload.md); the runner resets the
        # process-global controller at teardown
        self._overload_config = params.get("overload")
        # elastic-fleet seam: params["autoscale"] installs a
        # FleetControllerExtension next to each multi-device plane
        # (docs/guides/elastic-fleet.md); params["autoscale_slo"] makes
        # the steady-trough footprint a latched verdict input
        self._autoscale_config = params.get("autoscale")
        self._autoscale_slo = params.get("autoscale_slo") or {}
        self._autoscale_samples: "dict[str, list[int]]" = {}
        self._autoscale_evidence: "Optional[dict]" = None
        self._current_phase: "Optional[str]" = None
        self._verify_convergence = bool(params.get("verify_convergence"))
        # wire-saturation seam: params["wire_saturation"] turns the
        # per-frame cost ledger on for the run and attaches offered vs.
        # achieved frames/s per rung plus the headroom model's verdict
        # inputs as extra.wire_saturation (docs/guides/load-testing.md)
        self._wire_sat_config = params.get("wire_saturation")
        self._tracer_state = None  # (enabled, sample) to restore post-run
        self.harness = ServedLoadHarness(
            num_docs=pop["num_docs"],
            instances=pop["instances"],
            edges=pop.get("edges", 0),
            cells=pop.get("cells", 0),
            sampled=pop["sampled"],
            shards=pop["shards"],
            devices=pop.get("devices", 0),
            multi_device=params.get("multi_device"),
            shard_rows=pop.get("shard_rows"),
            capacity=pop["capacity"],
            flush_interval_ms=pop.get("flush_interval_ms", 2.0),
            docs_per_socket=pop.get("docs_per_socket", 64),
            replica_watermark=params.get("replica_watermark"),
            with_metrics=with_metrics,
            seed=schedule.seed,
            overload=self._overload_config,
            autoscale=self._autoscale_config,
            anti_entropy_s=params.get("anti_entropy_s"),
            progress=self._progress,
        )

        # run-scoped SLO engine: windows sized to the run so the
        # multi-window rule can vote before it ends — "burst" proves the
        # problem is still happening, "run" proves it is real
        planned_s = max(schedule.total_ms / 1000.0 / self.time_scale, 1.0)
        self.engine = SloEngine(
            windows=(("burst", max(planned_s / 4, 0.5)), ("run", planned_s)),
            sample_interval_s=max(planned_s / 50, 0.02),
            alert_burn_rate=alert_burn_rate,
        )
        self.latency_hist = Histogram(
            "hocuspocus_loadgen_scenario_e2e_seconds",
            "Measured end-to-end op latency by scenario phase",
            buckets=_LATENCY_BUCKETS,
        )
        self._phase_counts: "dict[str, dict]" = {}
        self._target_phase: "dict[str, str]" = {}
        for spec in schedule.phases:
            name = spec["name"]
            self._phase_counts[name] = {"total": 0.0, "bad": 0.0}
            latency = latency_slo(
                f"{name}:latency",
                self.latency_hist,
                threshold_s=spec["slo_e2e_ms"] / 1000.0,
                objective=spec["slo_objective"],
                stage=name,
            )
            self.engine.add(latency)
            counts = self._phase_counts[name]
            self.engine.add(
                SloTarget(
                    name=f"{name}:op_success",
                    description=(
                        f"{spec['error_objective']:.0%} of phase "
                        f"'{name}' measured ops succeed"
                    ),
                    objective=spec["error_objective"],
                    collect=(lambda c=counts: (c["total"], c["bad"])),
                )
            )
            self._target_phase[f"{name}:latency"] = name
            self._target_phase[f"{name}:op_success"] = name

        self._breached: "dict[str, bool]" = {}
        self._max_burn: "dict[str, dict[str, float]]" = {}
        self._phase_lat: "dict[str, list[float]]" = {
            spec["name"]: [] for spec in schedule.phases
        }
        self._joined: "dict[int, list]" = {}
        self._join_sockets: "list[InProcessProviderSocket]" = []
        self._behind_ms_max = 0.0

    # -- SLO sampling --------------------------------------------------------

    def _sample_slo(self, force: bool = False) -> None:
        if force:
            self.engine.sample()
        elif not self.engine.maybe_sample():
            return
        if self._current_phase and self.harness.fleet_controllers:
            # footprint evidence rides the SLO cadence: per-phase active
            # cell counts feed the steady-trough footprint verdict
            active = sum(
                len(ext.active_cells())
                for ext in self.harness.fleet_controllers
            )
            self._autoscale_samples.setdefault(
                self._current_phase, []
            ).append(active)
        timeline = get_loadgen_timeline()
        for target in self.engine.targets:
            for window, _secs in self.engine.windows:
                burn = self.engine.burn_rate(target.name, window)
                if burn is not None:
                    prev = self._max_burn.setdefault(target.name, {})
                    prev[window] = max(prev.get(window, 0.0), burn)
            if self.engine.breaching(target) and not self._breached.get(
                target.name
            ):
                # latch: the verdict must remember a breach even after
                # the windows slide past it
                self._breached[target.name] = True
                phase = self._target_phase.get(target.name, "?")
                timeline.note_breach(phase, target.name)
                get_flight_recorder().record(
                    "__loadgen__", "slo_breach", phase=phase, target=target.name
                )
                self._progress(f"SLO BREACH {target.name}")

    # -- op execution --------------------------------------------------------

    async def _await_synced(
        self, provider, abort: "Optional[asyncio.Event]" = None
    ) -> "Optional[float]":
        """Seconds until the provider syncs; None on timeout OR when
        `abort` fires first (e.g. admission denied — the op must fail
        fast, not burn the op timeout)."""
        t0 = time.perf_counter()
        while not provider.synced:
            if abort is not None and abort.is_set():
                return None
            if time.perf_counter() - t0 > self.op_timeout_s:
                return None
            await asyncio.sleep(0.002)
        return time.perf_counter() - t0

    def _join_server(self):
        servers = self.harness.servers
        return servers[1 if len(servers) > 1 else 0]

    async def _op_join(self, doc: int) -> "Optional[float]":
        socket = InProcessProviderSocket(self._join_server())
        self._join_sockets.append(socket)
        provider = HocuspocusProvider(
            name=f"load-{doc}", websocket_provider=socket
        )
        # overload admission refuses at auth with permission-denied —
        # the join must FAIL FAST (a bad op), not burn the op timeout
        denied = asyncio.Event()
        provider.on("authentication_failed", lambda *args: denied.set())
        provider.attach()
        latency = await self._await_synced(provider, abort=denied)
        self._joined.setdefault(doc, []).append(provider)
        return latency

    async def _op_leave(self, doc: int) -> "Optional[float]":
        joined = self._joined.get(doc) or []
        if joined:
            joined.pop(0).destroy()
            await asyncio.sleep(0)
        return 0.0

    async def _op_reconnect(self, doc: int) -> "Optional[float]":
        """Flaky mobile: the doc's reader drops and resyncs — the
        measured latency is the full rejoin (auth + SyncStep1/2)."""
        harness = self.harness
        if doc >= len(harness.readers):
            return 0.0
        old = harness.readers[doc]
        socket = old.websocket_provider
        old.destroy()
        await asyncio.sleep(0)
        provider = HocuspocusProvider(
            name=f"load-{doc}", websocket_provider=socket
        )
        provider.attach()
        harness.readers[doc] = provider
        return await self._await_synced(provider)

    def _op_lag(self, value: int) -> "Optional[float]":
        redis = self.harness.mini_redis
        if redis is not None:
            redis.publish_latency_ms = value
        return 0.0

    def _op_partition(self, value: int) -> "Optional[float]":
        """One-way partition of instance 0's publisher at the mini_redis
        hop (value 1), or heal (value 0). Drops are accounted in the
        server's `dropped_partition` counter — never silent."""
        redis = self.harness.mini_redis
        if redis is not None:
            if value:
                redis.partition_publisher(self.harness.redis_identifier(0))
            else:
                redis.heal_partition()
        return 0.0

    def _op_overload(self, value: int) -> "Optional[float]":
        from ..server.overload import get_overload_controller

        get_overload_controller().inject_pressure(float(value))
        return 0.0

    async def _op_drain(self, value: int) -> "Optional[float]":
        """Gracefully drain merge cell `value` mid-run (edge topology):
        the handoff contract — remap + transparent re-establishment —
        is what the rest of the phase then measures."""
        if not self.harness.cell_servers:
            return 0.0
        outcome = await self.harness.drain_cell(value % len(self.harness.cell_servers))
        get_flight_recorder().record(
            "__loadgen__",
            "cell_drained",
            cell=value,
            stored=outcome.get("stored"),
            duration_s=outcome.get("duration_s"),
        )
        return 0.0

    async def _execute(self, op) -> None:
        """Run one op; measured kinds feed the phase histogram and the
        success counters. A timeout is a bad event, never an abort."""
        measured = True
        latency: "Optional[float]" = 0.0
        if op.kind == "edit":
            if op.doc < self.harness.sampled and not op.value:
                latency = await self.harness.timed_edit(
                    op.doc,
                    max(op.size, 1),
                    timeout_s=self.op_timeout_s,
                    raise_on_timeout=False,
                )
            else:
                # background traffic (non-sampled doc, or an edit
                # flagged fire-and-forget — e.g. during a partition
                # phase whose observation channel is deliberately
                # dead): load, not signal
                wtext = self.harness.writers[op.doc].document.get_text("body")
                wtext.insert(len(wtext), "b" * max(op.size, 1))
                measured = False
        elif op.kind == "join":
            latency = await self._op_join(op.doc)
        elif op.kind == "leave":
            latency = await self._op_leave(op.doc)
            measured = False
        elif op.kind == "reconnect":
            latency = await self._op_reconnect(op.doc)
        elif op.kind == "lag":
            latency = self._op_lag(op.value)
            measured = False
        elif op.kind == "partition":
            latency = self._op_partition(op.value)
            measured = False
        elif op.kind == "overload":
            latency = self._op_overload(op.value)
            measured = False
        elif op.kind == "drain":
            latency = await self._op_drain(op.value)
            measured = False
        ok = latency is not None
        if measured:
            counts = self._phase_counts[op.phase]
            counts["total"] += 1
            if not ok:
                counts["bad"] += 1
            if ok and latency > 0:
                self.latency_hist.observe(latency, stage=op.phase)
                self._phase_lat[op.phase].append(latency)
        get_loadgen_timeline().op_done(
            op.phase,
            op.kind,
            ok,
            latency_ms=(latency * 1000 if measured and ok and latency else None),
        )

    # -- phases --------------------------------------------------------------

    def _phase_summary(self, spec: dict) -> dict:
        name = spec["name"]
        lat = self._phase_lat[name]
        lat_ms = np.array(lat) * 1000 if lat else None
        counts = self._phase_counts[name]
        burn = {}
        for target in (f"{name}:latency", f"{name}:op_success"):
            burn[target] = {
                window: self.engine.burn_rate(target, window)
                for window, _secs in self.engine.windows
            }
        return {
            "name": name,
            "planned_ms": spec["duration_ms"],
            "slo_e2e_ms": spec["slo_e2e_ms"],
            "measured_ops": int(counts["total"]),
            "failed_ops": int(counts["bad"]),
            "latency_p50_ms": None
            if lat_ms is None
            else round(float(np.percentile(lat_ms, 50)), 3),
            "latency_p99_ms": None
            if lat_ms is None
            else round(float(np.percentile(lat_ms, 99)), 3),
            "burn_rates": burn,
            "breached": [
                target
                for target in burn
                if self._breached.get(target)
            ],
        }

    def _start_phase(self, name: str) -> None:
        self._current_phase = name
        get_loadgen_timeline().phase_start(name)
        get_flight_recorder().record(
            "__loadgen__", "phase_start", phase=name, scenario=self.schedule.scenario
        )
        self._progress(f"phase {name} start")
        self._wire_before = get_wire_telemetry().totals()
        self._lane_before = self._lane_counters() or {}
        self._phase_wall_started = time.perf_counter()

    def _end_phase(self, spec: dict, summaries: "list[dict]") -> None:
        name = spec["name"]
        summary = self._phase_summary(spec)
        summary["wall_s"] = round(
            time.perf_counter()
            - getattr(self, "_phase_wall_started", time.perf_counter()),
            3,
        )
        after = get_wire_telemetry().totals()
        summary["wire"] = {
            key: int(after[key] - self._wire_before.get(key, 0))
            for key in ("messages_in", "messages_out", "bytes_in", "bytes_out")
        }
        lane = self._lane_counters()
        if lane is not None:
            before = getattr(self, "_lane_before", None) or {}
            summary["lane"] = {
                key: value - before.get(key, 0) for key, value in lane.items()
            }
        summaries.append(summary)
        get_loadgen_timeline().phase_end(
            name,
            latency_p50_ms=summary["latency_p50_ms"],
            latency_p99_ms=summary["latency_p99_ms"],
        )
        get_flight_recorder().record(
            "__loadgen__",
            "phase_end",
            phase=name,
            measured_ops=summary["measured_ops"],
            failed_ops=summary["failed_ops"],
            p99_ms=summary["latency_p99_ms"],
        )
        self._progress(
            f"phase {name} done: {summary['measured_ops']} measured ops, "
            f"p99={summary['latency_p99_ms']}ms"
        )

    async def _check_convergence(self, timeout_s: float = 8.0) -> dict:
        """Zero-silent-loss acceptance. Replicated topology: every
        sampled doc's server-side state must converge BYTE-IDENTICALLY
        across the two instances (encode_state_as_update orders structs
        deterministically, so equal logical state means equal bytes).
        Edge topology: the kill-9-style assertion runs against the
        SURVIVING REFERENCE CLIENTS — writer and reader docs (which
        hold every acknowledged update, connected through DIFFERENT
        edges) must converge byte-identically even across a mid-run
        cell drain. Waits out the trailing resync/anti-entropy
        exchange; a doc still diverged at the deadline is reported and
        latches the verdict."""
        from ..crdt import encode_state_as_update

        harness = self.harness
        if harness.edges > 0:
            pairs = [
                (f"load-{d}", harness.writers[d].document, harness.readers[d].document)
                for d in range(harness.sampled)
            ]

            def states(name):
                for label, doc_a, doc_b in pairs:
                    if label == name:
                        return doc_a, doc_b
                return None, None

        else:
            docs_a = harness.servers[0].hocuspocus.documents
            docs_b = harness.servers[1].hocuspocus.documents

            def states(name):
                return docs_a.get(name), docs_b.get(name)

        names = [f"load-{d}" for d in range(harness.sampled)]
        pending = set(names)
        t0 = time.perf_counter()
        while pending and time.perf_counter() - t0 < timeout_s:
            for name in list(pending):
                doc_a, doc_b = states(name)
                if doc_a is None or doc_b is None:
                    continue
                try:
                    if encode_state_as_update(doc_a) == encode_state_as_update(
                        doc_b
                    ):
                        pending.discard(name)
                except Exception:
                    pass
            if pending:
                await asyncio.sleep(0.05)
        return {
            "docs_checked": len(names),
            "converged": not pending,
            "diverged": sorted(pending),
            "wait_ms": round((time.perf_counter() - t0) * 1000, 1),
        }

    def _latch_autoscale_footprint(self) -> None:
        """The elasticity acceptance (docs/guides/elastic-fleet.md):
        mean active cells during the configured trough phase over the
        static fleet size must stay <= max_ratio — a fleet that never
        scales back down fails the run even with every latency SLO
        green. Latched like any breach; the ratio lands in
        ``extra.autoscale`` for the bench gate's
        diurnal_autoscale.steady_footprint_ratio stage."""
        controllers = self.harness.fleet_controllers
        if not self._autoscale_config or not controllers:
            return
        total = sum(
            ext.controller.num_cells if ext.controller else 0
            for ext in controllers
        )
        phase_means = {
            phase: round(sum(samples) / len(samples), 3)
            for phase, samples in self._autoscale_samples.items()
            if samples
        }
        evidence: dict = {
            "fleet_cells": total,
            "phase_active_cells": phase_means,
            "controllers": [ext.status() for ext in controllers],
        }
        trough = self._autoscale_slo.get("trough_phase")
        max_ratio = self._autoscale_slo.get("max_ratio")
        if trough and max_ratio is not None and total:
            samples = self._autoscale_samples.get(trough) or []
            if samples:
                ratio = (sum(samples) / len(samples)) / total
                evidence["trough_phase"] = trough
                evidence["max_ratio"] = float(max_ratio)
                evidence["steady_footprint_ratio"] = round(ratio, 4)
                if ratio > float(max_ratio):
                    self._breached["autoscale_footprint"] = True
                    get_loadgen_timeline().note_breach(
                        trough, "autoscale_footprint"
                    )
                    get_flight_recorder().record(
                        "__loadgen__",
                        "autoscale_footprint_breach",
                        phase=trough,
                        ratio=round(ratio, 4),
                        max_ratio=float(max_ratio),
                    )
                    self._progress(
                        f"AUTOSCALE FOOTPRINT BREACH {ratio:.2f} > "
                        f"{float(max_ratio):.2f}"
                    )
            else:
                # no samples in the measured trough = the verdict input
                # is missing, not vacuously green
                self._breached["autoscale_footprint"] = True
                evidence["steady_footprint_ratio"] = None
        self._autoscale_evidence = evidence

    def _chaos_evidence(self) -> dict:
        """Overload/partition accounting attached to the artifact: the
        ladder's transition history + shed counters, mini_redis's
        partition-drop accounting, and the publish lane's shed
        counters — 'every shed publish accounted' is checkable from
        the artifact alone."""
        evidence: dict = {}
        if self._overload_config:
            from ..server.overload import get_overload_controller

            evidence["overload"] = get_overload_controller().status()
        mini = self.harness.mini_redis
        if mini is not None:
            evidence["mini_redis"] = dict(mini.counters)
        if self.harness.edge_gateways:
            # handoff evidence: relay/handoff/stale-drop counters + the
            # router's final view, per edge — "the drain handed off
            # transparently" is checkable from the artifact alone
            evidence["edge"] = {
                gateway.edge_id: {
                    "counters": dict(gateway.counters),
                    "router": gateway.router.table(),
                }
                for gateway in self.harness.edge_gateways
            }
            # fleet observability evidence (docs/guides/observability.md
            # fleet view): digest federation counts, cross-tier
            # edge→cell→edge latency quantiles, stale peers — the
            # bench gate's edge_fanout.cross_tier_e2e_p99 stage reads
            # the p99 from here
            from ..observability.fleet import get_fleet_view

            view = get_fleet_view()
            fleet_status = view.status()
            evidence["fleet"] = {
                "peers": fleet_status["totals"]["peers"],
                "fresh_peers": fleet_status["totals"]["fresh"],
                "stale_peers": len(fleet_status["stale_peers"]),
                "digests_ingested": view.counters["digests_ingested"],
                "epoch_skew": any(
                    info["skew"] for info in fleet_status["epoch_skew"].values()
                ),
                "cross_tier_e2e_ms": fleet_status["cross_tier_e2e_ms"],
                "traces_stamped": sum(
                    gateway.counters.get("traces_stamped", 0)
                    for gateway in self.harness.edge_gateways
                ),
                "traces_closed": sum(
                    gateway.counters.get("traces_closed", 0)
                    for gateway in self.harness.edge_gateways
                ),
            }
        if self.harness.edge_gateways:
            # hot-doc replication evidence (docs/guides/
            # hot-doc-replication.md): each edge's owner+follower route
            # tables and each cell's ReplicaManager stats — follower
            # counts, tick seqs, lag and resync/promotion counters —
            # so "the audience fanned out over followers with bounded
            # owner work" is checkable from the artifact alone
            replica_evidence: dict = {
                "edges": {
                    gateway.edge_id: {
                        "watermark": gateway.replica_watermark,
                        "docs": (gateway.status().get("replica") or {}).get(
                            "docs", {}
                        ),
                    }
                    for gateway in self.harness.edge_gateways
                },
                "cells": {
                    ingress.cell_id: ingress.replicas.stats()
                    for ingress in self.harness.cell_ingresses
                    if getattr(ingress, "replicas", None) is not None
                },
            }
            if any(
                edge["docs"] for edge in replica_evidence["edges"].values()
            ) or any(
                stats.get("owned") or stats.get("following")
                for stats in replica_evidence["cells"].values()
            ):
                evidence["replica"] = replica_evidence
        multi = {}
        for i, ext in enumerate(self.harness.extensions):
            if callable(getattr(ext, "utilization_spread", None)):
                # per-device placement evidence: the multi_device_storm
                # acceptance ("docs spread, no device >2x the mean, every
                # migration accounted") is checkable from the artifact
                multi[f"instance{i}"] = {
                    "placement": ext.placement.table(),
                    "placement_hash": ext.placement.placement_hash(),
                    "migrations": dict(ext.migration_stats),
                    "utilization": ext.utilization_spread(),
                    "per_device": ext.per_device_latency(),
                    "devices": len(ext.cells),
                }
        if multi:
            evidence["multi_device"] = multi
        if self._autoscale_evidence is not None:
            # elastic-fleet evidence: roster timeline, scale decisions,
            # per-phase active-cell means and the steady-trough
            # footprint ratio the bench gate reads
            evidence["autoscale"] = self._autoscale_evidence
        publish = {}
        for i, server in enumerate(self.harness.servers):
            for ext in getattr(server.hocuspocus, "_extensions", []):
                pub = getattr(ext, "pub", None)
                counters = getattr(pub, "counters", None)
                if isinstance(counters, dict):
                    publish[f"instance{i}"] = dict(counters)
        if publish:
            evidence["publish_lane"] = publish
        return evidence

    def _wire_saturation_evidence(self, summaries: "list[dict]") -> dict:
        """The wire-saturation verdict inputs: per-rung offered ops/s
        vs. achieved ingress frames/s (phase wire deltas over measured
        wall time), the headroom model's sustainable rate and the top-5
        per-frame cost attribution. Two latched checks keep the verdict
        non-vacuous: the FIRST rung must achieve at least
        ``min_achieved_ratio`` ingress frames per offered op (later
        rungs are allowed — expected — to saturate), and the cost
        ledger must have produced a non-empty attribution."""
        from ..observability.costs import get_cost_ledger

        ledger = get_cost_ledger()
        config = self._wire_sat_config or {}
        offered_by_phase: "dict[str, int]" = {}
        for op in self.schedule.ops:
            offered_by_phase[op.phase] = offered_by_phase.get(op.phase, 0) + 1
        rungs = []
        for summary in summaries:
            wall_s = summary.get("wall_s") or (
                summary["planned_ms"] / 1000.0 / self.time_scale
            )
            wall_s = max(float(wall_s), 1e-6)
            wire = summary.get("wire") or {}
            offered = offered_by_phase.get(summary["name"], 0) / wall_s
            achieved = wire.get("messages_in", 0) / wall_s
            rungs.append(
                {
                    "phase": summary["name"],
                    "wall_s": round(wall_s, 3),
                    "offered_ops_per_s": round(offered, 1),
                    "achieved_frames_per_s": round(achieved, 1),
                    "bytes_in_per_s": round(
                        wire.get("bytes_in", 0) / wall_s, 1
                    ),
                    "p99_ms": summary["latency_p99_ms"],
                }
            )
        sustained = max(
            (rung["achieved_frames_per_s"] for rung in rungs), default=0.0
        )
        headroom = ledger.headroom_frames_per_s()
        top = ledger.top_costs(5)
        min_ratio = float(config.get("min_achieved_ratio", 0.5))
        if rungs:
            first = rungs[0]
            if first["achieved_frames_per_s"] < (
                min_ratio * first["offered_ops_per_s"]
            ):
                self._breached["wire_saturation_floor"] = True
        if not top or headroom <= 0.0:
            # the whole point of the scenario: evidence, not vacuity
            self._breached["wire_saturation_attribution"] = True
        return {
            "rungs": rungs,
            "sustained_frames_per_s": sustained,
            "headroom_frames_per_s": round(headroom, 1),
            "headroom_ratio": round(headroom / sustained, 3)
            if sustained
            else None,
            "loop_ns_per_frame": round(ledger.loop_ns_per_frame(), 1),
            "ingress_frames": ledger.ingress_frames(),
            "top_costs": top,
        }

    def _lane_counters(self) -> "Optional[dict]":
        total: "dict[str, int]" = {}
        found = False
        for ext in self.harness.extensions:
            lanes_fn = getattr(ext, "lanes", None)
            if callable(lanes_fn):
                lanes = lanes_fn()  # multi-device: one arbiter per chip
            else:
                lanes = [getattr(ext, "lane", None)]
            for lane in lanes:
                counters = getattr(lane, "counters", None)
                if isinstance(counters, dict):
                    found = True
                    for key, value in counters.items():
                        total[key] = total.get(key, 0) + int(value)
        return total if found else None

    # -- the run -------------------------------------------------------------

    async def run(self) -> dict:
        schedule = self.schedule
        harness = self.harness
        timeline = get_loadgen_timeline()
        recorder = get_flight_recorder()
        get_wire_telemetry().enable()
        wire_run_before = get_wire_telemetry().totals()
        if self._wire_sat_config:
            # the ledger is process-global like the overload controller:
            # reset to this run so the headroom model reads THIS
            # scenario's loop-thread costs, not a previous run's
            from ..observability.costs import get_cost_ledger

            ledger = get_cost_ledger()
            ledger.reset()
            ledger.enable()
        t_setup = time.perf_counter()
        summaries: "list[dict]" = []
        timeline.begin_run(
            scenario=schedule.scenario,
            seed=schedule.seed,
            schedule_hash=schedule.schedule_hash,
            phases=[
                {"name": s["name"], "planned_ms": s["duration_ms"]}
                for s in schedule.phases
            ],
            time_scale=self.time_scale,
            ops_total=len(schedule.ops),
        )
        recorder.record(
            "__loadgen__",
            "run_start",
            scenario=schedule.scenario,
            seed=schedule.seed,
            schedule_hash=schedule.schedule_hash,
        )
        verdict = "fail"
        self._tracer_state = None
        if harness.edges > 0:
            # edge topology: light cross-tier tracing so the run lands
            # fleet evidence (extra.fleet cross_tier_e2e_ms feeds the
            # bench gate). The fleet view resets to this run — like the
            # overload controller, it is process-global state a scenario
            # must not inherit; the tracer is restored at teardown.
            from ..observability.fleet import get_fleet_view
            from ..observability.tracing import get_tracer

            view = get_fleet_view()
            view.reset()
            view.enable()
            tracer = get_tracer()
            self._tracer_state = (tracer.enabled, tracer.sample)
            tracer.enabled = True
            # 1-in-4: enough observations for the cross-tier quantiles
            # at CI scale without perturbing the gated interactive_p99
            # (every sampled update pays an aux encode + span chain +
            # one TRACE_RET round trip)
            tracer.sample = 4
        try:
            self._progress(
                f"scenario {schedule.scenario}: booting population "
                f"({harness.num_docs} docs x {harness.instances} instance(s))"
            )
            await harness._start_servers()
            await harness._connect_writers()
            await harness._connect_readers()
            setup_s = time.perf_counter() - t_setup
            self._progress(f"population synced in {setup_s:.1f}s; executing schedule")

            phase_order = [spec["name"] for spec in schedule.phases]
            spec_by_name = {spec["name"]: spec for spec in schedule.phases}
            phase_index = -1
            self._sample_slo(force=True)
            t0 = time.perf_counter()
            for op in schedule.ops:
                due = t0 + op.at_ms / 1000.0 / self.time_scale
                while True:
                    now = time.perf_counter()
                    if now >= due:
                        break
                    await asyncio.sleep(
                        min(due - now, self.engine.sample_interval_s)
                    )
                    self._sample_slo()
                self._behind_ms_max = max(
                    self._behind_ms_max, (time.perf_counter() - due) * 1000
                )
                # advance phases (empty phases open + close in passing)
                while (
                    phase_index < 0
                    or phase_order[phase_index] != op.phase
                ):
                    if phase_index + 1 >= len(phase_order):
                        # only reachable with a hand-edited schedule:
                        # compile() emits phase-monotonic op order
                        raise ValueError(
                            f"op phase {op.phase!r} violates declared "
                            f"phase order {phase_order}"
                        )
                    if phase_index >= 0:
                        self._sample_slo(force=True)
                        self._end_phase(
                            spec_by_name[phase_order[phase_index]], summaries
                        )
                    phase_index += 1
                    self._start_phase(phase_order[phase_index])
                await self._execute(op)
                self._sample_slo()
            # close the tail: final sample with full-run coverage, then
            # remaining phase summaries
            self._sample_slo(force=True)
            while phase_index < len(phase_order):
                if phase_index >= 0:
                    self._end_phase(spec_by_name[phase_order[phase_index]], summaries)
                phase_index += 1
                if phase_index < len(phase_order):
                    self._start_phase(phase_order[phase_index])
            elapsed = time.perf_counter() - t0
            if self._overload_config:
                # the schedule is over: stop the ladder's sampler NOW so
                # teardown churn (provider/server destruction stalls the
                # loop) can't smear spurious transitions into the
                # flight recorder after the measured run
                from ..server.overload import get_overload_controller

                get_overload_controller().stop()

            convergence = None
            if self._verify_convergence and (
                harness.instances > 1 or harness.edges > 0
            ):
                convergence = await self._check_convergence()
                if not convergence["converged"]:
                    # zero-silent-loss acceptance: divergence after the
                    # heal window is a latched failure like any breach
                    self._breached["convergence"] = True
                    get_flight_recorder().record(
                        "__loadgen__",
                        "convergence_failed",
                        diverged=",".join(convergence["diverged"]),
                    )
                    self._progress(
                        f"CONVERGENCE FAILED: {convergence['diverged']}"
                    )

            self._latch_autoscale_footprint()

            wire_sat = None
            if self._wire_sat_config:
                wire_sat = self._wire_saturation_evidence(summaries)

            verdict = "fail" if any(self._breached.values()) else "pass"
            slo_status = self.engine.status()
            result = {
                "metric": "scenario_slo_verdict",
                "value": 1.0 if verdict == "pass" else 0.0,
                "unit": "pass",
                "scenario": schedule.scenario,
                "seed": schedule.seed,
                "schedule_hash": schedule.schedule_hash,
                "verdict": verdict,
                "slo": {
                    "alert_burn_rate": self.engine.alert_burn_rate,
                    "windows": {
                        name: secs for name, secs in self.engine.windows
                    },
                    "breached_targets": sorted(
                        name for name, hit in self._breached.items() if hit
                    ),
                    "max_burn_rates": {
                        name: {
                            window: round(burn, 4)
                            for window, burn in windows.items()
                        }
                        for name, windows in sorted(self._max_burn.items())
                    },
                    "targets": {
                        name: {
                            "description": slo["description"],
                            "objective": slo["objective"],
                            "breached": bool(self._breached.get(name)),
                        }
                        for name, slo in slo_status["slos"].items()
                    },
                },
                "phases": summaries,
                "extra": {
                    "population": schedule.population,
                    "time_scale": self.time_scale,
                    "ops_total": len(schedule.ops),
                    "ops_measured": int(
                        sum(c["total"] for c in self._phase_counts.values())
                    ),
                    "ops_failed": int(
                        sum(c["bad"] for c in self._phase_counts.values())
                    ),
                    "behind_ms_max": round(self._behind_ms_max, 1),
                    "setup_s": round(setup_s, 2),
                    "elapsed_s": round(elapsed, 2),
                    "seed": schedule.seed,
                    "wire": {
                        key: int(value - wire_run_before.get(key, 0))
                        for key, value in get_wire_telemetry().totals().items()
                    },
                    "plane_health": harness.plane_health(),
                },
            }
            if convergence is not None:
                result["extra"]["convergence"] = convergence
            if wire_sat is not None:
                result["extra"]["wire_saturation"] = wire_sat
            chaos = self._chaos_evidence()
            if chaos:
                result["extra"].update(chaos)
            return result
        finally:
            timeline.end_run(verdict)
            recorder.record(
                "__loadgen__", "run_end", scenario=schedule.scenario, verdict=verdict
            )
            await self._teardown()

    async def _teardown(self) -> None:
        if self._tracer_state is not None:
            from ..observability.tracing import get_tracer

            tracer = get_tracer()
            tracer.enabled, tracer.sample = self._tracer_state
            self._tracer_state = None
        for providers in self._joined.values():
            for provider in providers:
                provider.destroy()
        self._joined.clear()
        for socket in self._join_sockets:
            socket.destroy()
        self._join_sockets.clear()
        await asyncio.sleep(0)
        await self.harness._teardown()
        if self._overload_config:
            # the controller is process-global: a scenario that tuned +
            # drove it must hand the next run a cold GREEN one
            from ..server.overload import get_overload_controller

            get_overload_controller().reset()
        if self._wire_sat_config:
            # same process-global discipline for the cost ledger: the
            # next scenario must not pay this run's per-frame timers
            from ..observability.costs import get_cost_ledger

            get_cost_ledger().disable()


async def run_scenario(
    scenario_or_schedule: "Any",
    seed: int = 0,
    time_scale: float = 1.0,
    **runner_kwargs: Any,
) -> dict:
    """Compile (when given a Scenario) and run; returns the artifact."""
    schedule = scenario_or_schedule
    if not isinstance(schedule, Schedule):
        schedule = scenario_or_schedule.compile(seed)
    runner = ScenarioRunner(schedule, time_scale=time_scale, **runner_kwargs)
    return await runner.run()
