"""Load generation: the served harness + the scenario traffic simulator.

Two layers (docs/guides/load-testing.md):

- :mod:`.harness` — ``ServedLoadHarness``, the socket-free real-server
  topology bench.py measures the served 100k-doc regime with;
- the scenario engine — declarative, phase-tagged, seeded traffic
  programs (:mod:`.scenario`), a library of production mixes
  (:mod:`.scenarios`), and the SLO-judged executor (:mod:`.runner`)
  whose verdict is the PR-6 burn-rate engine's breach status.

Run one from the command line::

    python -m hocuspocus_tpu.loadgen --scenario smoke --seed 7

Back-compat: ``from hocuspocus_tpu.loadgen import run_served_load``
keeps working exactly as when this was a single module.

Import weight: the schedule/timeline layers (scenario, scenarios,
timeline) are stdlib-only and imported eagerly — tools and the
``/debug/loadgen`` endpoint rely on that staying cheap. The execution
layers (harness, runner) pull the full server + jax stack and resolve
lazily via PEP 562 on first attribute access.
"""

from .scenario import OpEvent, PhaseSpec, Scenario, Schedule
from .scenarios import BENCH_SUITE, SCENARIOS, get_scenario
from .timeline import LoadgenTimeline, get_loadgen_timeline

# heavy symbols (server/tpu/jax imports) -> providing submodule
_LAZY = {
    "ServedLoadHarness": "harness",
    "run_served_load": "harness",
    "ScenarioRunner": "runner",
    "run_scenario": "runner",
}

__all__ = [
    "BENCH_SUITE",
    "LoadgenTimeline",
    "OpEvent",
    "PhaseSpec",
    "SCENARIOS",
    "Scenario",
    "ScenarioRunner",
    "Schedule",
    "ServedLoadHarness",
    "get_loadgen_timeline",
    "get_scenario",
    "run_scenario",
    "run_served_load",
]


def __getattr__(name: str):
    submodule = _LAZY.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module

    value = getattr(import_module(f".{submodule}", __name__), name)
    globals()[name] = value  # cache: resolve once per process
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
