"""Built-in scenario library: the production mixes the paper promises.

Each factory returns a :class:`~.scenario.Scenario` sized by keyword
arguments (defaults are CI-scale; pass bigger numbers for real storms).
``get_scenario(name, **overrides)`` resolves by registry name — the
``python -m hocuspocus_tpu.loadgen`` CLI, bench.py's scenario-suite
pass and ``tools/bench_capture.py`` all go through it.

The mixes (ROADMAP item 5, Collabs arXiv:2212.02618 composed multi-user
workloads, Eg-walker arXiv:2409.14252 realistic-concurrency merges):

- ``smoke``            — tiny three-phase mix for tier-1 CI
- ``diurnal``          — trough → ramp → peak → ramp-down edit rates
- ``flash_crowd``      — a join storm lands on one hot doc mid-run
- ``reconnect_herd``   — flaky-mobile clients drop and resync in herds
- ``mega_doc``         — one outsized doc among thousands of small ones
- ``replication_lag``  — cross-instance lag injected into mini_redis
- ``storm``            — flash crowd + reconnect herd composed (slow)
- ``overload_storm``   — injected RED pressure: brownout shedding +
  admission rejections while interactive p99 holds, hysteresis-clean
  recovery to GREEN
- ``partition_heal``   — one-way mini_redis partition, accounted drops,
  anti-entropy heal to byte-identical convergence
- ``edge_fanout``      — split front door: edge-terminated join storm +
  cross-edge fan-out over two merge cells
- ``edge_handoff``     — mid-run cell drain: transparent handoff, zero
  acked-update loss, byte-identical convergence
- ``multi_device_storm`` — hot-doc skew on the per-chip cell plane: one
  mega-doc plus a small-doc population forces load-aware rebalancing
  mid-run (docs migrate between device cells with zero acked loss)
- ``diurnal_autoscale`` — the diurnal ramp with the elastic-fleet
  controller on: SLOs hold through the peak while the steady-trough
  active-cell footprint drops to warm spares (ratio latched into the
  verdict and gated)
- ``mega_audience``    — one viral doc, few writers, a huge read
  audience through the edge tier: the replica watermark grows follower
  cells and the fan-out spreads across them (owner work stays bounded)
- ``wire_saturation`` — ramping ingress edit rate with the per-frame
  cost ledger on: the runner attaches offered vs. achieved frames/s per
  rung, the headroom model's sustainable rate and the top-5 cost
  attribution as ``extra.wire_saturation``
"""

from __future__ import annotations

import random
from typing import Callable

from .scenario import OpEvent, PhaseSpec, Scenario


def _spread(rng: random.Random, count: int, duration_ms: int) -> "list[int]":
    """`count` op times spread over the phase with seeded jitter."""
    if count <= 0:
        return []
    step = duration_ms / count
    return sorted(
        min(int(i * step + rng.random() * step), duration_ms - 1)
        for i in range(count)
    )


def _edit_gen(
    rate_per_s: float,
    size_lo: int = 8,
    size_hi: int = 24,
    mega_every: int = 0,
    mega_lo: int = 192,
    mega_hi: int = 384,
    background: bool = False,
) -> Callable:
    """Steady random-doc edit traffic at `rate_per_s` (logical time).

    With ``mega_every`` = N, every Nth op targets doc 0 with a
    mega-sized insert — the one-big-doc-among-thousands mix. With
    ``background`` the edits are fire-and-forget even on sampled docs
    (``OpEvent.value = 1``) — traffic that must keep flowing while its
    observation channel is deliberately broken (a partition phase)."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        count = max(int(rate_per_s * phase.duration_ms / 1000), 1)
        ops = []
        for i, at in enumerate(_spread(rng, count, phase.duration_ms)):
            if mega_every and i % mega_every == 0:
                doc, size = 0, rng.randrange(mega_lo, mega_hi)
            else:
                doc = rng.randrange(scenario.num_docs)
                size = rng.randrange(size_lo, size_hi)
            ops.append(
                OpEvent(
                    at,
                    phase.name,
                    "edit",
                    doc=doc,
                    size=size,
                    value=1 if background else 0,
                )
            )
        return ops

    return gen


def _compose(*gens: Callable) -> Callable:
    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        ops = []
        for sub in gens:
            ops.extend(sub(rng, scenario, phase))
        return ops

    return gen


def _join_storm_gen(joins: int, doc: int = 0, window_frac: float = 0.5) -> Callable:
    """`joins` new clients pile onto one hot doc inside the first
    `window_frac` of the phase — the flash-crowd front edge."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        window = max(int(phase.duration_ms * window_frac), 1)
        return [
            OpEvent(at, phase.name, "join", doc=doc, value=i)
            for i, at in enumerate(_spread(rng, joins, window))
        ]

    return gen


def _leave_gen(leaves: int, doc: int = 0) -> Callable:
    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [
            OpEvent(at, phase.name, "leave", doc=doc)
            for at in _spread(rng, leaves, phase.duration_ms)
        ]

    return gen


def _reconnect_gen(reconnects: int) -> Callable:
    """Flaky-mobile herd: measured docs drop and resync repeatedly."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [
            OpEvent(
                at,
                phase.name,
                "reconnect",
                doc=rng.randrange(max(scenario.sampled, 1)),
            )
            for at in _spread(rng, reconnects, phase.duration_ms)
        ]

    return gen


def _lag_gen(lag_ms: int, at_ms: int = 0) -> Callable:
    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [OpEvent(at_ms, phase.name, "lag", value=lag_ms)]

    return gen


def _partition_gen(on: bool, at_ms: int = 0) -> Callable:
    """Start (on=True) or heal (on=False) the one-way mini_redis
    partition of instance 0's publisher."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [OpEvent(at_ms, phase.name, "partition", value=1 if on else 0)]

    return gen


def _overload_gen(rung: int, at_ms: int = 0) -> Callable:
    """Inject `rung` rungs of synthetic pressure into the overload
    ladder (0 clears)."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [OpEvent(at_ms, phase.name, "overload", value=rung)]

    return gen


def _drain_gen(cell: int, at_ms: int = 0) -> Callable:
    """Gracefully drain merge cell `cell` (edge topologies): the cell
    announces departure, the router remaps, edges re-establish."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [OpEvent(at_ms, phase.name, "drain", value=cell)]

    return gen


# -- the library -------------------------------------------------------------


def smoke(
    num_docs: int = 6,
    phase_ms: int = 800,
    rate: float = 20.0,
) -> Scenario:
    """Tier-1 CI mix: edits, one tiny join wave, a leave — seconds on CPU."""
    return Scenario(
        name="smoke",
        description="tiny three-phase mix proving the harness end to end",
        num_docs=num_docs,
        sampled=min(4, num_docs),
        shards=1,
        capacity=512,
        shard_rows=max(num_docs * 2, 16),
        docs_per_socket=num_docs,
        phases=[
            PhaseSpec("warm", phase_ms, _edit_gen(rate), slo_e2e_ms=1000.0),
            PhaseSpec(
                "burst",
                phase_ms,
                _compose(_edit_gen(rate * 2), _join_storm_gen(2)),
                slo_e2e_ms=1000.0,
            ),
            PhaseSpec(
                "cool",
                phase_ms,
                _compose(_edit_gen(rate / 2), _leave_gen(2)),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def diurnal(
    num_docs: int = 48,
    phase_ms: int = 2000,
    peak_rate: float = 120.0,
) -> Scenario:
    """A day of traffic compressed into four phases: trough, morning
    ramp, peak, evening ramp-down. The peak phase carries the tight
    SLO; the trough proves the idle floor doesn't rot."""
    return Scenario(
        name="diurnal",
        description="diurnal ramp: trough -> ramp -> peak -> ramp-down",
        num_docs=num_docs,
        sampled=min(12, num_docs),
        shards=2,
        capacity=768,
        phases=[
            PhaseSpec("trough", phase_ms, _edit_gen(peak_rate / 8)),
            PhaseSpec("ramp_up", phase_ms, _edit_gen(peak_rate / 2)),
            PhaseSpec("peak", phase_ms, _edit_gen(peak_rate), slo_e2e_ms=500.0),
            PhaseSpec("ramp_down", phase_ms, _edit_gen(peak_rate / 4)),
        ],
    )


def flash_crowd(
    num_docs: int = 32,
    joins: int = 24,
    phase_ms: int = 2000,
) -> Scenario:
    """A hot doc goes viral: a join storm lands mid-run while steady
    edits continue everywhere (PR 7's join-storm sync cache under a
    composed mix, not an isolated pass)."""
    return Scenario(
        name="flash_crowd",
        description="flash-crowd join storm on one hot doc",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=2,
        capacity=768,
        params={"joins": joins},
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(40.0)),
            PhaseSpec(
                "storm",
                phase_ms,
                _compose(_edit_gen(40.0), _join_storm_gen(joins)),
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "drain",
                phase_ms,
                _compose(_edit_gen(20.0), _leave_gen(joins)),
            ),
        ],
    )


def reconnect_herd(
    num_docs: int = 32,
    reconnects: int = 16,
    phase_ms: int = 2000,
) -> Scenario:
    """Flaky-mobile herd: a subway tunnel's worth of clients drop and
    resync while edits continue — catch-up tiering and SyncStep2 under
    churn, measured as resync latency."""
    return Scenario(
        name="reconnect_herd",
        description="flaky-mobile reconnect herd over steady edits",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=2,
        capacity=768,
        params={"reconnects": reconnects},
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(40.0)),
            PhaseSpec(
                "herd",
                phase_ms,
                _compose(_edit_gen(40.0), _reconnect_gen(reconnects)),
                slo_e2e_ms=2000.0,
                slo_objective=0.90,
            ),
            PhaseSpec("recovered", phase_ms, _edit_gen(40.0)),
        ],
    )


def mega_doc(
    num_docs: int = 64,
    phase_ms: int = 2000,
) -> Scenario:
    """One mega-document among a small-doc population: every 4th op is
    an outsized insert into doc 0. The merge plane must keep the small
    docs' latency flat while the mega doc's row grows."""
    return Scenario(
        name="mega_doc",
        description="one mega-doc among a population of small docs",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=2,
        capacity=4096,
        mega_doc=True,
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(40.0, mega_every=8)),
            PhaseSpec(
                "mega_burst",
                phase_ms,
                _edit_gen(60.0, mega_every=4),
                slo_e2e_ms=1000.0,
            ),
            PhaseSpec("settle", phase_ms, _edit_gen(30.0, mega_every=8)),
        ],
    )


def replication_lag(
    num_docs: int = 16,
    phase_ms: int = 1500,
    lag_ms: int = 40,
) -> Scenario:
    """Cross-instance mix: writers on instance A, readers on instance B
    through mini_redis; the middle phase injects publish latency, so the
    lagged phase's SLO must absorb exactly the injected delay — and the
    recovered phase must return to the healthy budget."""
    return Scenario(
        name="replication_lag",
        description="cross-instance replication lag via mini_redis injection",
        num_docs=num_docs,
        sampled=min(6, num_docs),
        instances=2,
        shards=1,
        capacity=512,
        docs_per_socket=num_docs,
        params={"lag_ms": lag_ms},
        phases=[
            PhaseSpec("healthy", phase_ms, _edit_gen(24.0), slo_e2e_ms=1000.0),
            PhaseSpec(
                "lagged",
                phase_ms,
                _compose(_lag_gen(lag_ms), _edit_gen(24.0)),
                slo_e2e_ms=2000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "recovered",
                phase_ms,
                _compose(_lag_gen(0), _edit_gen(24.0)),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def storm(
    num_docs: int = 64,
    joins: int = 48,
    reconnects: int = 32,
    phase_ms: int = 3000,
) -> Scenario:
    """The composed worst hour: flash crowd AND reconnect herd over a
    peak edit rate — the slow-marked stress scenario."""
    return Scenario(
        name="storm",
        description="composed flash crowd + reconnect herd at peak rate",
        num_docs=num_docs,
        sampled=min(12, num_docs),
        shards=4,
        capacity=768,
        params={"joins": joins, "reconnects": reconnects},
        phases=[
            PhaseSpec("build_up", phase_ms, _edit_gen(60.0)),
            PhaseSpec(
                "landfall",
                phase_ms,
                _compose(
                    _edit_gen(80.0),
                    _join_storm_gen(joins),
                    _reconnect_gen(reconnects),
                ),
                slo_e2e_ms=2000.0,
                slo_objective=0.85,
            ),
            PhaseSpec(
                "aftermath",
                phase_ms,
                _compose(_edit_gen(40.0), _leave_gen(joins)),
            ),
        ],
    )


def overload_storm(
    num_docs: int = 12,
    phase_ms: int = 1200,
    joins: int = 3,
    hold_s: float = 0.1,
) -> Scenario:
    """The overload control plane under deterministic pressure
    (docs/guides/overload.md): a calm phase, then synthetic RED-rung
    pressure lands WITH a join wave — the ladder must reject the new
    joins (shed/reject counters go nonzero) while the already-admitted
    interactive edits keep their p99, then a recovery phase clears the
    pressure and the ladder must walk back to GREEN one rung per hold
    window (hysteresis-clean: the flight recorder shows a monotonic
    descent, never a flap). The runner installs an OverloadExtension
    from ``params["overload"]`` and attaches the controller's
    transition/shed evidence to the artifact."""
    return Scenario(
        name="overload_storm",
        description="brownout ladder + admission under injected RED pressure",
        num_docs=num_docs,
        sampled=min(6, num_docs),
        shards=1,
        capacity=512,
        docs_per_socket=num_docs,
        params={
            "overload": {
                "hold_s": hold_s,
                "sample_interval_s": min(hold_s / 2, 0.05),
                "awareness_stretch_ms": 100.0,
                "catchup_retry_s": 0.1,
                # the INJECTED signal alone drives this scenario's
                # ladder: ambient signals (loop lag on a loaded CI
                # runner, send queues) are parked far out of range so
                # the transition path is deterministic
                "thresholds": {
                    "loop_lag_ms": (1e7, 2e7, 3e7),
                    "send_queue_depth": (1e7, 2e7, 3e7),
                    "backpressure_per_s": (1e7, 2e7, 3e7),
                    "lane_depth": (1e7, 2e7, 3e7),
                    "wal_commit_ms": (1e7, 2e7, 3e7),
                    "inbox_depth": (1e7, 2e7, 3e7),
                },
            }
        },
        phases=[
            PhaseSpec("calm", phase_ms, _edit_gen(20.0), slo_e2e_ms=1000.0),
            PhaseSpec(
                "storm",
                phase_ms,
                _compose(
                    _overload_gen(3),  # straight to RED at phase start
                    _edit_gen(40.0),
                    _join_storm_gen(joins),
                ),
                # the acceptance bar: interactive edit p99 HOLDS while
                # the ladder sheds — the joins are the sacrificed load
                # (they fail fast with permission-denied), so the
                # op-success objective tolerates exactly them
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
                error_objective=0.85,
            ),
            PhaseSpec(
                "recover",
                phase_ms,
                _compose(_overload_gen(0), _edit_gen(20.0)),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def partition_heal(
    num_docs: int = 8,
    phase_ms: int = 1500,
    anti_entropy_s: float = 0.25,
) -> Scenario:
    """Partition-heal chaos (docs/guides/overload.md): writers on
    instance A, readers on instance B; the middle phase one-way
    blackholes A's publishes at the mini_redis hop (every drop is
    accounted in ``dropped_partition`` — zero silent loss) while edits
    keep flowing fire-and-forget; the heal phase ends the partition and
    measures edits end to end again — their latency INCLUDES the
    anti-entropy exchange that pulls back the partition-era updates.
    ``params["verify_convergence"]`` makes the runner assert the
    instances' documents converge byte-identically after the schedule
    (a failure latches the verdict to fail)."""
    return Scenario(
        name="partition_heal",
        description="one-way mini_redis partition, anti-entropy heal, "
        "byte-identical convergence",
        num_docs=num_docs,
        sampled=min(4, num_docs),
        instances=2,
        shards=1,
        capacity=512,
        docs_per_socket=num_docs,
        params={
            "verify_convergence": True,
            "anti_entropy_s": anti_entropy_s,
        },
        phases=[
            PhaseSpec("healthy", phase_ms, _edit_gen(16.0), slo_e2e_ms=1000.0),
            PhaseSpec(
                "partitioned",
                phase_ms,
                _compose(
                    _partition_gen(True),
                    # fire-and-forget even on sampled docs: the traffic
                    # must keep flowing while its replication channel is
                    # deliberately dead (measuring here would only time
                    # out — the HEAL phase measures the recovery)
                    _edit_gen(16.0, background=True),
                ),
            ),
            PhaseSpec(
                "healed",
                phase_ms,
                _compose(_partition_gen(False), _edit_gen(12.0)),
                # the first measured edits carry the heal: their
                # latency includes the anti-entropy exchange pulling
                # back everything the partition dropped
                slo_e2e_ms=2000.0,
                slo_objective=0.90,
                error_objective=0.90,
            ),
        ],
    )


def multi_device_storm(
    num_docs: int = 24,
    phase_ms: int = 1500,
    devices: int = 4,
) -> Scenario:
    """Hot-doc skew on the multi-device cell plane
    (docs/guides/multi-device.md): a small-doc population plus one
    mega-doc whose outsized inserts pile dispatched work onto its
    owning chip. The storm phase's skew must force the rebalancer to
    migrate docs OFF the hot cell mid-run (evict-snapshot→hydrate, zero
    acked-update loss — ``verify_convergence`` latches divergence into
    the verdict via the cross-instance check), and the small docs'
    interactive p99 holds while the mega-doc churns — the
    `multi_device_storm.interactive_p99` gate stage in
    tools/bench_gate.py. Per-device doc counts, utilization spread,
    placement hash and migration accounting land in
    ``extra.multi_device`` so the next on-chip capture can verify the
    226 ms → <50 ms trajectory chip by chip."""
    return Scenario(
        name="multi_device_storm",
        description="hot-doc skew forcing load-aware rebalancing across "
        "per-device merge cells",
        num_docs=num_docs,
        sampled=min(6, num_docs),
        instances=2,
        shards=1,
        devices=devices,
        capacity=8192,
        mega_doc=True,
        docs_per_socket=num_docs,
        params={
            "verify_convergence": True,
            "multi_device": {
                # CI-scale rebalancer: sweep fast, trip on small skews,
                # so a three-phase run demonstrably migrates mid-storm
                "rebalance_interval_s": 0.25,
                "rebalance_ratio": 1.5,
                "rebalance_min_units": 64.0,
                "migrate_batch": 4,
            },
        },
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(24.0, mega_every=12), slo_e2e_ms=1000.0),
            PhaseSpec(
                "storm",
                phase_ms,
                # every 3rd op is a mega insert into doc 0: its cell's
                # dispatched-work counter races ahead of its peers and
                # the rebalancer must spread the small docs away
                _edit_gen(36.0, mega_every=3, mega_lo=256, mega_hi=512),
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "rebalanced",
                phase_ms,
                _edit_gen(24.0, mega_every=12),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def edge_fanout(
    num_docs: int = 10,
    phase_ms: int = 1200,
    joins: int = 4,
) -> Scenario:
    """The split front door under load (docs/guides/edge-routing.md):
    writers on edge 0, readers on edge 1, two merge cells behind the
    relay lane — every measured edit crosses edge→cell→edge, and a join
    storm lands THROUGH the edge tier mid-run (door auth + relay
    session establishment under pressure). The fanout phase's p99 is
    the `edge_fanout.interactive_p99` gate stage in
    tools/bench_gate.py: the edge hop must stay a constant tax, not a
    new tail."""
    return Scenario(
        name="edge_fanout",
        description="edge-terminated join storm + cross-edge fan-out "
        "over two merge cells",
        num_docs=num_docs,
        sampled=min(5, num_docs),
        edges=2,
        cells=2,
        shards=1,
        capacity=512,
        docs_per_socket=num_docs,
        params={"joins": joins},
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(20.0), slo_e2e_ms=1000.0),
            PhaseSpec(
                "fanout",
                phase_ms,
                _compose(_edit_gen(30.0), _join_storm_gen(joins)),
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "cool",
                phase_ms,
                _compose(_edit_gen(15.0), _leave_gen(joins)),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def mega_audience(
    num_docs: int = 4,
    phase_ms: int = 1500,
    joins: int = 18,
    watermark: int = 6,
) -> Scenario:
    """One doc goes viral (docs/guides/hot-doc-replication.md): a tiny
    writer population keeps editing doc 0 while a huge read audience
    piles in through edge 1 — crossing the replica watermark mid-run,
    so the router grows an owner + follower placement, followers
    bootstrap off the owner's snapshot rail and the edge spreads the
    audience's channels across the whole route set. The fanout phase's
    p99 is the `mega_audience.fanout_p99` gate stage in
    tools/bench_gate.py: the measured write→observe path must stay FLAT
    as the audience (and the follower count) scales, because the owner
    only streams one coalesced tick per flush regardless of audience —
    reads are the followers' problem. ``verify_convergence`` latches a
    follower serving stale state into the verdict, and the per-edge
    route tables + per-cell ReplicaManager stats land in
    ``extra.replica`` so follower counts and tick lag are checkable
    from the artifact alone."""
    return Scenario(
        name="mega_audience",
        description="viral mega-doc: huge read audience fanned out over "
        "follower cells while the write path stays on one owner",
        num_docs=num_docs,
        sampled=min(4, num_docs),
        edges=2,
        cells=3,
        shards=1,
        capacity=768,
        docs_per_socket=num_docs,
        params={
            "verify_convergence": True,
            "joins": joins,
            # CI-scale watermark: the join wave must cross it with room
            # to want several followers (wanted = audience // watermark,
            # capped at healthy-1 by the gateway)
            "replica_watermark": watermark,
        },
        phases=[
            # every 2nd op lands on doc 0 at NORMAL sizes (the doc is
            # hot by audience, not by payload — mega_doc covers that)
            PhaseSpec(
                "steady",
                phase_ms,
                _edit_gen(16.0, mega_every=2, mega_lo=16, mega_hi=32),
                slo_e2e_ms=1000.0,
            ),
            PhaseSpec(
                "swarm",
                phase_ms,
                _compose(
                    _edit_gen(16.0, mega_every=2, mega_lo=16, mega_hi=32),
                    _join_storm_gen(joins),
                ),
                # the swarm measures join time-to-synced WHILE followers
                # bootstrap — a follower mid-hydration still admits and
                # serves SyncStep2, so joins must not stall on it
                slo_e2e_ms=2000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "fanout",
                phase_ms,
                _edit_gen(24.0, mega_every=2, mega_lo=16, mega_hi=32),
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
            ),
        ],
    )


def diurnal_autoscale(
    num_docs: int = 24,
    phase_ms: int = 2500,
    peak_rate: float = 96.0,
    devices: int = 4,
) -> Scenario:
    """The diurnal ramp with the elastic-fleet controller ON
    (docs/guides/elastic-fleet.md): the same trough → ramp → peak →
    ramp-down shape over a multi-device cell plane, plus a long steady
    `night` trough where the autoscaler must have parked the fleet back
    down to warm spares. Two latched verdict inputs: the per-phase SLOs
    (peak p99 is the `diurnal_autoscale.interactive_p99` gate stage —
    elasticity must not cost the peak), and the **steady-trough
    footprint ratio** — mean active cells during `night` over the
    static fleet size — which must stay ≤ `max_ratio`
    (`diurnal_autoscale.steady_footprint_ratio` in tools/bench_gate.py).
    Scale-downs migrate docs over the evict-snapshot→hydrate rail with
    zero acked loss; the runner attaches the roster timeline, scale
    decisions and migration counts as ``extra.autoscale``."""
    return Scenario(
        name="diurnal_autoscale",
        description="diurnal ramp under the elastic-fleet autoscaler: "
        "SLOs hold while the trough footprint drops",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=1,
        devices=devices,
        capacity=4096,
        docs_per_socket=num_docs,
        params={
            # FleetControllerExtension tuning (loadgen/harness.py):
            # CI-scale cadence so a 2.5s trough fits several decisions
            "autoscale": {
                "interval_s": 0.1,
                "hold_ticks": 2,
                "cooldown_ticks": 3,
                "min_cells": 1,
                "up_threshold": 0.75,
                "down_threshold": 0.35,
                # normalized so the trough (peak/8 edit units/s spread
                # over the fleet) reads well below down_threshold while
                # the peak saturates past up_threshold
                "work_target": 600.0,
                "lane_target": 64.0,
            },
            # runner-side verdict latch: mean active cells over the
            # `night` phase vs. the static fleet, latched like an SLO
            "autoscale_slo": {"trough_phase": "night", "max_ratio": 0.6},
            "multi_device": {
                # the rebalancer stays on (it coexists with the
                # controller) but sweeps slowly — scale decisions own
                # topology here, the rebalancer only polishes
                "rebalance_interval_s": 1.0,
                "rebalance_ratio": 2.0,
                "rebalance_min_units": 256.0,
            },
        },
        phases=[
            PhaseSpec("trough", phase_ms, _edit_gen(peak_rate / 8)),
            PhaseSpec("ramp_up", phase_ms, _edit_gen(peak_rate / 2)),
            PhaseSpec(
                "peak", phase_ms, _edit_gen(peak_rate), slo_e2e_ms=1000.0
            ),
            PhaseSpec("ramp_down", phase_ms, _edit_gen(peak_rate / 4)),
            # the measured steady trough: long enough for hold_ticks +
            # cooldown + the scale-down migrations to fully settle
            PhaseSpec("night", phase_ms, _edit_gen(peak_rate / 8)),
        ],
    )


def edge_handoff(
    num_docs: int = 8,
    phase_ms: int = 1500,
) -> Scenario:
    """Mid-run cell drain with transparent handoff
    (docs/guides/edge-routing.md): steady cross-edge traffic, then cell
    0 gracefully drains — it announces departure, the router remaps its
    docs and every affected session re-establishes on cell 1 via the
    replayed Auth + SyncStep1 resync, with NO client-visible
    disconnect. The handoff phase's edits measure the re-establishment
    tax; ``verify_convergence`` latches the zero-acknowledged-update-
    loss assertion (writer vs reader client docs byte-identical, the
    surviving-reference-client check) into the SLO verdict."""
    return Scenario(
        name="edge_handoff",
        description="mid-run cell drain: transparent handoff, zero "
        "acked-update loss, byte-identical convergence",
        num_docs=num_docs,
        sampled=min(4, num_docs),
        edges=2,
        cells=2,
        shards=1,
        capacity=512,
        docs_per_socket=num_docs,
        params={"verify_convergence": True},
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(16.0), slo_e2e_ms=1000.0),
            PhaseSpec(
                "handoff",
                phase_ms,
                _compose(
                    _drain_gen(0),
                    # the drain runs mid-phase edits: sessions hand off
                    # UNDER traffic, and the measured latencies include
                    # the resync exchange
                    _edit_gen(16.0),
                ),
                slo_e2e_ms=5000.0,
                slo_objective=0.80,
                error_objective=0.80,
            ),
            PhaseSpec(
                "settled",
                phase_ms,
                _edit_gen(12.0),
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
            ),
        ],
    )


def wire_saturation(
    num_docs: int = 8,
    phase_ms: int = 900,
    base_rate: float = 30.0,
) -> Scenario:
    """Ramping ingress rate with the per-frame cost ledger ON
    (docs/guides/observability.md "profiling & cost attribution"): four rungs
    doubling the offered edit rate. The runner enables the
    :mod:`~..observability.costs` ledger for the run and attaches
    ``extra.wire_saturation`` — per-rung offered vs. achieved frames/s
    (from the phase wire deltas), the headroom model's sustainable
    rate (``hocuspocus_profile_headroom_frames_per_s``) and the top-5
    per-frame cost attribution. tools/bench_gate.py gates
    ``wire_saturation.frames_per_s`` and
    ``wire_saturation.headroom_frames_per_s`` as higher-is-better
    stages. SLOs are deliberately generous — the verdict input here is
    throughput and attribution, not interactive latency."""
    return Scenario(
        name="wire_saturation",
        description="ramping ingress rate: cost-ledger attribution + "
        "headroom model vs. achieved frames/s",
        num_docs=num_docs,
        sampled=min(4, num_docs),
        shards=1,
        capacity=1024,
        docs_per_socket=num_docs,
        params={
            # runner-side: enable the cost ledger, attach the evidence.
            # min_achieved_ratio is a soft floor on achieved/offered for
            # the *first* rung only (the others are allowed to saturate
            # — that is the point of the ramp)
            "wire_saturation": {"min_achieved_ratio": 0.5},
        },
        phases=[
            PhaseSpec(
                "rung_1x",
                phase_ms,
                _edit_gen(base_rate),
                slo_e2e_ms=5000.0,
                slo_objective=0.80,
            ),
            PhaseSpec(
                "rung_2x",
                phase_ms,
                _edit_gen(base_rate * 2),
                slo_e2e_ms=5000.0,
                slo_objective=0.80,
            ),
            PhaseSpec(
                "rung_4x",
                phase_ms,
                _edit_gen(base_rate * 4),
                slo_e2e_ms=5000.0,
                slo_objective=0.80,
            ),
            PhaseSpec(
                "rung_8x",
                phase_ms,
                _edit_gen(base_rate * 8),
                slo_e2e_ms=5000.0,
                slo_objective=0.70,
                error_objective=0.90,
            ),
        ],
    )


SCENARIOS: "dict[str, Callable[..., Scenario]]" = {
    "smoke": smoke,
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "reconnect_herd": reconnect_herd,
    "mega_doc": mega_doc,
    "replication_lag": replication_lag,
    "storm": storm,
    "overload_storm": overload_storm,
    "partition_heal": partition_heal,
    "multi_device_storm": multi_device_storm,
    "diurnal_autoscale": diurnal_autoscale,
    "edge_fanout": edge_fanout,
    "edge_handoff": edge_handoff,
    "mega_audience": mega_audience,
    "wire_saturation": wire_saturation,
}

# the default suite bench.py / bench_capture run: fast enough for every
# round, covers the single-instance, cross-instance, overload-shed,
# partition-heal, multi-device-rebalance and edge-tier (split front
# door, cell-drain handoff, hot-doc follower fan-out) paths
BENCH_SUITE = (
    "smoke",
    "replication_lag",
    "overload_storm",
    "partition_heal",
    "multi_device_storm",
    "diurnal_autoscale",
    "edge_fanout",
    "edge_handoff",
    "mega_audience",
    "wire_saturation",
)


def get_scenario(name: str, **overrides) -> Scenario:
    try:
        factory = SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(sorted(SCENARIOS))}"
        ) from None
    return factory(**overrides)
