"""Built-in scenario library: the production mixes the paper promises.

Each factory returns a :class:`~.scenario.Scenario` sized by keyword
arguments (defaults are CI-scale; pass bigger numbers for real storms).
``get_scenario(name, **overrides)`` resolves by registry name — the
``python -m hocuspocus_tpu.loadgen`` CLI, bench.py's scenario-suite
pass and ``tools/bench_capture.py`` all go through it.

The mixes (ROADMAP item 5, Collabs arXiv:2212.02618 composed multi-user
workloads, Eg-walker arXiv:2409.14252 realistic-concurrency merges):

- ``smoke``            — tiny three-phase mix for tier-1 CI
- ``diurnal``          — trough → ramp → peak → ramp-down edit rates
- ``flash_crowd``      — a join storm lands on one hot doc mid-run
- ``reconnect_herd``   — flaky-mobile clients drop and resync in herds
- ``mega_doc``         — one outsized doc among thousands of small ones
- ``replication_lag``  — cross-instance lag injected into mini_redis
- ``storm``            — flash crowd + reconnect herd composed (slow)
"""

from __future__ import annotations

import random
from typing import Callable

from .scenario import OpEvent, PhaseSpec, Scenario


def _spread(rng: random.Random, count: int, duration_ms: int) -> "list[int]":
    """`count` op times spread over the phase with seeded jitter."""
    if count <= 0:
        return []
    step = duration_ms / count
    return sorted(
        min(int(i * step + rng.random() * step), duration_ms - 1)
        for i in range(count)
    )


def _edit_gen(
    rate_per_s: float,
    size_lo: int = 8,
    size_hi: int = 24,
    mega_every: int = 0,
    mega_lo: int = 192,
    mega_hi: int = 384,
) -> Callable:
    """Steady random-doc edit traffic at `rate_per_s` (logical time).

    With ``mega_every`` = N, every Nth op targets doc 0 with a
    mega-sized insert — the one-big-doc-among-thousands mix."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        count = max(int(rate_per_s * phase.duration_ms / 1000), 1)
        ops = []
        for i, at in enumerate(_spread(rng, count, phase.duration_ms)):
            if mega_every and i % mega_every == 0:
                doc, size = 0, rng.randrange(mega_lo, mega_hi)
            else:
                doc = rng.randrange(scenario.num_docs)
                size = rng.randrange(size_lo, size_hi)
            ops.append(OpEvent(at, phase.name, "edit", doc=doc, size=size))
        return ops

    return gen


def _compose(*gens: Callable) -> Callable:
    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        ops = []
        for sub in gens:
            ops.extend(sub(rng, scenario, phase))
        return ops

    return gen


def _join_storm_gen(joins: int, doc: int = 0, window_frac: float = 0.5) -> Callable:
    """`joins` new clients pile onto one hot doc inside the first
    `window_frac` of the phase — the flash-crowd front edge."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        window = max(int(phase.duration_ms * window_frac), 1)
        return [
            OpEvent(at, phase.name, "join", doc=doc, value=i)
            for i, at in enumerate(_spread(rng, joins, window))
        ]

    return gen


def _leave_gen(leaves: int, doc: int = 0) -> Callable:
    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [
            OpEvent(at, phase.name, "leave", doc=doc)
            for at in _spread(rng, leaves, phase.duration_ms)
        ]

    return gen


def _reconnect_gen(reconnects: int) -> Callable:
    """Flaky-mobile herd: measured docs drop and resync repeatedly."""

    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [
            OpEvent(
                at,
                phase.name,
                "reconnect",
                doc=rng.randrange(max(scenario.sampled, 1)),
            )
            for at in _spread(rng, reconnects, phase.duration_ms)
        ]

    return gen


def _lag_gen(lag_ms: int, at_ms: int = 0) -> Callable:
    def gen(rng: random.Random, scenario: Scenario, phase: PhaseSpec):
        return [OpEvent(at_ms, phase.name, "lag", value=lag_ms)]

    return gen


# -- the library -------------------------------------------------------------


def smoke(
    num_docs: int = 6,
    phase_ms: int = 800,
    rate: float = 20.0,
) -> Scenario:
    """Tier-1 CI mix: edits, one tiny join wave, a leave — seconds on CPU."""
    return Scenario(
        name="smoke",
        description="tiny three-phase mix proving the harness end to end",
        num_docs=num_docs,
        sampled=min(4, num_docs),
        shards=1,
        capacity=512,
        shard_rows=max(num_docs * 2, 16),
        docs_per_socket=num_docs,
        phases=[
            PhaseSpec("warm", phase_ms, _edit_gen(rate), slo_e2e_ms=1000.0),
            PhaseSpec(
                "burst",
                phase_ms,
                _compose(_edit_gen(rate * 2), _join_storm_gen(2)),
                slo_e2e_ms=1000.0,
            ),
            PhaseSpec(
                "cool",
                phase_ms,
                _compose(_edit_gen(rate / 2), _leave_gen(2)),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def diurnal(
    num_docs: int = 48,
    phase_ms: int = 2000,
    peak_rate: float = 120.0,
) -> Scenario:
    """A day of traffic compressed into four phases: trough, morning
    ramp, peak, evening ramp-down. The peak phase carries the tight
    SLO; the trough proves the idle floor doesn't rot."""
    return Scenario(
        name="diurnal",
        description="diurnal ramp: trough -> ramp -> peak -> ramp-down",
        num_docs=num_docs,
        sampled=min(12, num_docs),
        shards=2,
        capacity=768,
        phases=[
            PhaseSpec("trough", phase_ms, _edit_gen(peak_rate / 8)),
            PhaseSpec("ramp_up", phase_ms, _edit_gen(peak_rate / 2)),
            PhaseSpec("peak", phase_ms, _edit_gen(peak_rate), slo_e2e_ms=500.0),
            PhaseSpec("ramp_down", phase_ms, _edit_gen(peak_rate / 4)),
        ],
    )


def flash_crowd(
    num_docs: int = 32,
    joins: int = 24,
    phase_ms: int = 2000,
) -> Scenario:
    """A hot doc goes viral: a join storm lands mid-run while steady
    edits continue everywhere (PR 7's join-storm sync cache under a
    composed mix, not an isolated pass)."""
    return Scenario(
        name="flash_crowd",
        description="flash-crowd join storm on one hot doc",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=2,
        capacity=768,
        params={"joins": joins},
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(40.0)),
            PhaseSpec(
                "storm",
                phase_ms,
                _compose(_edit_gen(40.0), _join_storm_gen(joins)),
                slo_e2e_ms=1000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "drain",
                phase_ms,
                _compose(_edit_gen(20.0), _leave_gen(joins)),
            ),
        ],
    )


def reconnect_herd(
    num_docs: int = 32,
    reconnects: int = 16,
    phase_ms: int = 2000,
) -> Scenario:
    """Flaky-mobile herd: a subway tunnel's worth of clients drop and
    resync while edits continue — catch-up tiering and SyncStep2 under
    churn, measured as resync latency."""
    return Scenario(
        name="reconnect_herd",
        description="flaky-mobile reconnect herd over steady edits",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=2,
        capacity=768,
        params={"reconnects": reconnects},
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(40.0)),
            PhaseSpec(
                "herd",
                phase_ms,
                _compose(_edit_gen(40.0), _reconnect_gen(reconnects)),
                slo_e2e_ms=2000.0,
                slo_objective=0.90,
            ),
            PhaseSpec("recovered", phase_ms, _edit_gen(40.0)),
        ],
    )


def mega_doc(
    num_docs: int = 64,
    phase_ms: int = 2000,
) -> Scenario:
    """One mega-document among a small-doc population: every 4th op is
    an outsized insert into doc 0. The merge plane must keep the small
    docs' latency flat while the mega doc's row grows."""
    return Scenario(
        name="mega_doc",
        description="one mega-doc among a population of small docs",
        num_docs=num_docs,
        sampled=min(8, num_docs),
        shards=2,
        capacity=4096,
        mega_doc=True,
        phases=[
            PhaseSpec("steady", phase_ms, _edit_gen(40.0, mega_every=8)),
            PhaseSpec(
                "mega_burst",
                phase_ms,
                _edit_gen(60.0, mega_every=4),
                slo_e2e_ms=1000.0,
            ),
            PhaseSpec("settle", phase_ms, _edit_gen(30.0, mega_every=8)),
        ],
    )


def replication_lag(
    num_docs: int = 16,
    phase_ms: int = 1500,
    lag_ms: int = 40,
) -> Scenario:
    """Cross-instance mix: writers on instance A, readers on instance B
    through mini_redis; the middle phase injects publish latency, so the
    lagged phase's SLO must absorb exactly the injected delay — and the
    recovered phase must return to the healthy budget."""
    return Scenario(
        name="replication_lag",
        description="cross-instance replication lag via mini_redis injection",
        num_docs=num_docs,
        sampled=min(6, num_docs),
        instances=2,
        shards=1,
        capacity=512,
        docs_per_socket=num_docs,
        params={"lag_ms": lag_ms},
        phases=[
            PhaseSpec("healthy", phase_ms, _edit_gen(24.0), slo_e2e_ms=1000.0),
            PhaseSpec(
                "lagged",
                phase_ms,
                _compose(_lag_gen(lag_ms), _edit_gen(24.0)),
                slo_e2e_ms=2000.0,
                slo_objective=0.90,
            ),
            PhaseSpec(
                "recovered",
                phase_ms,
                _compose(_lag_gen(0), _edit_gen(24.0)),
                slo_e2e_ms=1000.0,
            ),
        ],
    )


def storm(
    num_docs: int = 64,
    joins: int = 48,
    reconnects: int = 32,
    phase_ms: int = 3000,
) -> Scenario:
    """The composed worst hour: flash crowd AND reconnect herd over a
    peak edit rate — the slow-marked stress scenario."""
    return Scenario(
        name="storm",
        description="composed flash crowd + reconnect herd at peak rate",
        num_docs=num_docs,
        sampled=min(12, num_docs),
        shards=4,
        capacity=768,
        params={"joins": joins, "reconnects": reconnects},
        phases=[
            PhaseSpec("build_up", phase_ms, _edit_gen(60.0)),
            PhaseSpec(
                "landfall",
                phase_ms,
                _compose(
                    _edit_gen(80.0),
                    _join_storm_gen(joins),
                    _reconnect_gen(reconnects),
                ),
                slo_e2e_ms=2000.0,
                slo_objective=0.85,
            ),
            PhaseSpec(
                "aftermath",
                phase_ms,
                _compose(_edit_gen(40.0), _leave_gen(joins)),
            ),
        ],
    )


SCENARIOS: "dict[str, Callable[..., Scenario]]" = {
    "smoke": smoke,
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "reconnect_herd": reconnect_herd,
    "mega_doc": mega_doc,
    "replication_lag": replication_lag,
    "storm": storm,
}

# the default suite bench.py / bench_capture run: fast enough for every
# round, covers the single-instance AND cross-instance paths
BENCH_SUITE = ("smoke", "replication_lag")


def get_scenario(name: str, **overrides) -> Scenario:
    try:
        factory = SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(sorted(SCENARIOS))}"
        ) from None
    return factory(**overrides)
