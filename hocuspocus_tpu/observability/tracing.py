"""Lightweight span tracing for the server hot path.

The reference has no tracing (SURVEY.md §5.1 — closest is the provider's
onMessage/onOutgoingMessage taps, reference
`packages/provider/src/HocuspocusProvider.ts:156-157`, and a commented-out
message logger in `packages/server/src/MessageReceiver.ts:54-59`). This
module is the "real tracing" the TPU build adds: per-message spans, hook
chain spans, merge-plane device-step spans, and — via `UpdateTraceBook`
— end-to-end lifecycle traces that follow one update from the capture
seam through the flush pipeline to broadcast, each stage a span sharing
one monotonically increasing trace id. Spans export as plain dicts or as
Chrome/Perfetto trace-event JSON (`export_chrome_trace`), and device
spans bridge into the JAX profiler when a capture is active.

Design constraints:
- Near-zero cost when disabled: one attribute read + truth test per
  span site, no object allocation.
- No global locks on the hot path: spans complete on the event loop
  thread; the ring buffer is a `collections.deque(maxlen=...)` whose
  append is atomic under the GIL.
- Slow spans survive ring wrap: promotion to a structured log line and
  the `on_slow` callbacks happens at finish time, so a burst that
  overruns `max_spans` cannot hide an outlier.
"""

from __future__ import annotations

import contextvars
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Iterator, Optional

_slow_logger = logging.getLogger("hocuspocus_tpu.tracing")

# ingress mark (see Tracer.ingress_mark): a ContextVar, NOT a tracer
# attribute — the websocket edge awaits hook chains between setting the
# mark and the capture seam consuming it, and concurrent dispatches
# from different sockets run as different asyncio tasks. A shared slot
# would let task B clobber task A's receive timestamp mid-await; the
# context is per-task, so each dispatch sees exactly its own mark.
_ingress_mark: "contextvars.ContextVar[Optional[float]]" = contextvars.ContextVar(
    "hocuspocus_tpu_ingress_mark", default=None
)

# cross-tier trace context (see Tracer.fleet_context): set by the cell's
# relay ingress pump around each relayed frame dispatch, consumed by
# UpdateTraceBook.stamp — a sampled update that crossed the edge tier
# adopts the EDGE's trace id (and skips local sampling: the edge already
# sampled), so the cell's stage spans join the edge's cross-process
# chain. Per-task for the same reason as the ingress mark.
_fleet_ctx: "contextvars.ContextVar[Optional[dict]]" = contextvars.ContextVar(
    "hocuspocus_tpu_fleet_trace_ctx", default=None
)


class Span:
    """One completed (or in-flight) span."""

    __slots__ = ("name", "start", "end", "attributes", "trace_id", "tid")

    def __init__(self, name: str, attributes: Optional[dict] = None) -> None:
        self.name = name
        self.start = time.perf_counter()
        self.end: Optional[float] = None
        self.attributes = attributes
        self.trace_id: Optional[int] = None
        self.tid = threading.get_ident()

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end is None:
            return None
        return (self.end - self.start) * 1000.0

    def set(self, key: str, value: Any) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes[key] = value

    def finish(self) -> "Span":
        self.end = time.perf_counter()
        return self

    def to_dict(self) -> dict:
        record = {
            "name": self.name,
            "start": self.start,
            "duration_ms": self.duration_ms,
            "attributes": self.attributes or {},
        }
        if self.trace_id is not None:
            record["trace_id"] = self.trace_id
        return record


class _NoopSpan:
    __slots__ = ()

    def set(self, key: str, value: Any) -> None:
        pass

    def finish(self) -> "_NoopSpan":
        return self


_NOOP_SPAN = _NoopSpan()


class Tracer:
    """Collects spans into a bounded ring buffer.

    Usage::

        tracer = Tracer(enabled=True)
        with tracer.span("message.apply", doc="report") as sp:
            ...
            sp.set("bytes", 123)
        tracer.export()  # -> list of dicts, oldest first

    Extra knobs:
    - `slow_ms`: spans at/above this duration are promoted to a
      structured WARNING log line and the `on_slow` callbacks (the
      Metrics extension binds `hocuspocus_tpu_slow_spans_total{site=...}`
      there) — independent of the ring, so wrap can't hide them.
    - `sample`: 1-in-N sampling for the update-lifecycle traces
      (`take_sample`), so tracing stays viable at 100k-doc load.
    """

    def __init__(self, enabled: bool = True, max_spans: int = 4096) -> None:
        self.enabled = enabled
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._jax_annotation = None  # lazily resolved TraceAnnotation class
        # slow-span promotion: None disables the check entirely
        self.slow_ms: Optional[float] = None
        self.on_slow: list[Callable[[Span], Any]] = []
        # update-lifecycle trace ids + 1-in-N sampling
        self.sample: int = 1
        self._sample_counter = 0
        self._trace_id = 0
        # perf_counter origin for trace-viewer timestamps (`ts` is
        # microseconds relative to this anchor)
        self._origin_perf = time.perf_counter()

    # -- ingress mark ------------------------------------------------------

    @property
    def ingress_mark(self) -> Optional[float]:
        """The current dispatch's frame-receive timestamp, or None.

        The websocket edge (Connection.handle_message) sets this before
        dispatching and clears it in its finally; UpdateTraceBook.stamp
        reads it at the capture seam, so lifecycle traces born inside
        the dispatch gain an `update.ingress` stage (ws receive ->
        decode -> apply -> capture) and the e2e span truly runs
        socket -> broadcast. Backed by a ContextVar: dispatch tasks
        from different sockets interleave across the hook-chain awaits,
        and each must see only its own mark."""
        return _ingress_mark.get()

    @ingress_mark.setter
    def ingress_mark(self, value: Optional[float]) -> None:
        _ingress_mark.set(value)

    @property
    def fleet_context(self) -> Optional[dict]:
        """The current dispatch's relay trace context (edge-stamped
        trace id + stamps + hop counter), or None when the frame did not
        arrive through the edge tier / was not sampled there."""
        return _fleet_ctx.get()

    @fleet_context.setter
    def fleet_context(self, value: Optional[dict]) -> None:
        _fleet_ctx.set(value)

    # -- recording ---------------------------------------------------------

    @contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[Any]:
        if not self.enabled:
            yield _NOOP_SPAN
            return
        sp = Span(name, attributes or None)
        try:
            yield sp
        finally:
            self._record(sp.finish())

    @contextmanager
    def device_span(self, name: str, **attributes: Any) -> Iterator[Any]:
        """A span that also shows up in a `jax.profiler` trace when one is
        being captured (merge-plane device steps)."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        annotation = self._resolve_jax_annotation()
        if annotation is None:
            with self.span(name, **attributes) as sp:
                yield sp
            return
        with annotation(name), self.span(name, **attributes) as sp:
            yield sp

    def event(self, name: str, **attributes: Any) -> None:
        """Record an instantaneous event as a zero-duration span (state
        transitions, breaker trips — things with a moment, not an
        extent; exported as "i" instant events in the Chrome trace).
        Same near-zero disabled cost as span()."""
        if not self.enabled:
            return
        sp = Span(name, attributes or None)
        sp.end = sp.start  # exactly zero duration: a moment, not an extent
        self._spans.append(sp)

    def add_span(
        self,
        name: str,
        start: float,
        end: float,
        trace_id: Optional[int] = None,
        **attributes: Any,
    ) -> Optional[Span]:
        """Record a span with explicit perf_counter boundaries (the
        update trace book reconstructs stage spans after the fact from
        pipeline timestamps)."""
        if not self.enabled:
            return None
        sp = Span(name, attributes or None)
        sp.start = start
        sp.end = end
        sp.trace_id = trace_id
        self._record(sp)
        return sp

    def _record(self, sp: Span) -> None:
        self._spans.append(sp)
        slow_ms = self.slow_ms
        if slow_ms is not None and (sp.end - sp.start) * 1000.0 >= slow_ms:
            self._promote_slow(sp)

    def _promote_slow(self, sp: Span) -> None:
        try:
            _slow_logger.warning(
                "slow span site=%s duration_ms=%.3f trace_id=%s attrs=%s",
                sp.name,
                (sp.end - sp.start) * 1000.0,
                sp.trace_id,
                sp.attributes or {},
            )
        except Exception:
            pass
        for fn in list(self.on_slow):
            try:
                fn(sp)
            except Exception:
                pass

    # -- trace ids + sampling ----------------------------------------------

    def next_trace_id(self) -> int:
        self._trace_id += 1
        return self._trace_id

    def take_sample(self) -> bool:
        """1-in-`sample` admission for update-lifecycle traces. The
        first update after enabling is always sampled, so a lone manual
        test edit produces a trace."""
        if self.sample <= 1:
            return True
        self._sample_counter += 1
        return self._sample_counter % self.sample == 1

    def _resolve_jax_annotation(self):
        if self._jax_annotation is None:
            try:
                from jax.profiler import TraceAnnotation

                self._jax_annotation = TraceAnnotation
            except Exception:
                self._jax_annotation = False
        return self._jax_annotation or None

    # -- reading -----------------------------------------------------------

    def export(self, clear: bool = False) -> list[dict]:
        spans = [sp.to_dict() for sp in self._spans]
        if clear:
            self._spans.clear()
        return spans

    def export_chrome_trace(self) -> dict:
        """The span ring as Chrome trace-event JSON (the format Perfetto,
        `chrome://tracing` and `ui.perfetto.dev` all open): complete
        ("X") events with microsecond `ts`/`dur`, instantaneous ("i")
        events for zero-duration spans, one `tid` per recording thread,
        and span attributes (incl. the lifecycle trace id) under `args`.

        Cross-tier spans (attribute `node=<role id>`, stamped by the
        fleet trace plumbing) are merged under one synthetic pid PER
        NODE with a matching process_name record, so a single Perfetto
        view shows the full socket→cell→socket path as separate
        role/cell lanes."""
        pid = os.getpid()
        origin = self._origin_perf
        events: list[dict] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": "hocuspocus_tpu"},
            }
        ]
        node_pids: dict[str, int] = {}
        for sp in list(self._spans):
            args = dict(sp.attributes or {})
            if sp.trace_id is not None:
                args["trace_id"] = sp.trace_id
            node = args.get("node")
            if node is None:
                span_pid = pid
            else:
                span_pid = node_pids.get(node)
                if span_pid is None:
                    # synthetic pid lane per fleet node, well clear of
                    # real pid space so lanes never collide
                    span_pid = node_pids[node] = 1_000_000 + len(node_pids)
                    events.append(
                        {
                            "ph": "M",
                            "name": "process_name",
                            "pid": span_pid,
                            "tid": 0,
                            "args": {"name": str(node)},
                        }
                    )
            ts = (sp.start - origin) * 1e6
            end = sp.end if sp.end is not None else sp.start
            dur = (end - sp.start) * 1e6
            base = {
                "name": sp.name,
                "pid": span_pid,
                "tid": sp.tid,
                "ts": round(ts, 3),
                "args": args,
            }
            if dur > 0:
                base["ph"] = "X"
                base["dur"] = round(dur, 3)
            else:
                base["ph"] = "i"
                base["s"] = "t"
            events.append(base)
        try:
            # merge the sampling profiler's recent-stack ring as instant
            # events on the same clock, so flamegraph samples line up
            # with the lifecycle spans in one Perfetto view
            from .profiler import get_profiler

            events.extend(get_profiler().chrome_events(origin, pid))
        except Exception:
            pass
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def clear(self) -> None:
        self._spans.clear()

    def __len__(self) -> int:
        return len(self._spans)


class UpdateTraceBook:
    """Causally links one update's pipeline stages under one trace id.

    The capture seam stamps a sampled update (`stamp`: trace id +
    enqueue timestamp, per doc name); the flush engine moves stamped
    docs through drain (`take_drained`) and closes the device stages at
    the cycle's readback barrier (`complete_cycle`); the broadcast pass
    closes the trace (`finish`). Each boundary timestamp is shared by
    adjacent stages, so the per-stage durations are contiguous and sum
    exactly to the end-to-end latency:

        receive → enqueue:   ingress   (ws receive → decode → apply →
                                        capture; present only when the
                                        tracer's ingress_mark was set,
                                        i.e. the update arrived through
                                        the websocket edge)
        enqueue → drain:     queue_wait
        drain → built:       build
        built → uploaded:    upload
        uploaded → dispatched: device
        dispatched → readback: readback
        readback → broadcast:  broadcast

    Stage spans land in the tracer ring (names `update.<stage>`, shared
    `trace_id`); stage durations feed the labelled `histogram`
    (`hocuspocus_tpu_update_e2e_seconds{stage=...}`) when one is bound.
    Bounded: at most MAX_PENDING stamped-not-yet-flushed and MAX_FLUSHED
    flushed-not-yet-broadcast traces are held; excess stamps are dropped
    (counted), and `drop(name)` discards a doc's traces at
    retire/release so degraded docs can't leak entries.
    """

    MAX_PENDING = 4096
    MAX_FLUSHED = 4096

    def __init__(self, tracer: Optional[Tracer] = None) -> None:
        self.tracer = tracer  # None = the process-default tracer
        self.histogram = None  # labelled Histogram, bound by Metrics
        self.on_slow_flush: Optional[Callable[[str, float], Any]] = None
        self.slow_flush_ms: Optional[float] = None
        # fleet node attribution for cross-tier traces: set by the cell
        # ingress at configure time (the process-global identity is
        # last-writer, wrong in a multi-cell process); None falls back
        # to the process identity
        self.node_id: Optional[str] = None
        self.dropped = 0
        # stamp/finish run on the event loop while take_drained/
        # complete_cycle run on the flush executor thread: the compound
        # dict+counter updates must not interleave (a setdefault/append
        # racing a pop would strand entries and drift the bound
        # counters until MAX_PENDING wedges tracing). Reentrant:
        # complete_cycle closes early-broadcast traces via finish().
        # Never touched on the disabled path.
        self._lock = threading.RLock()
        self._pending: dict[str, list] = {}  # doc -> [(trace_id, t_enqueue)]
        self._flushed: dict[str, list] = {}  # doc -> [trace dict]
        self._pending_count = 0
        self._flushed_count = 0
        # docs with any live (stamped, unclosed) trace — gates the
        # early-broadcast bookkeeping below to traced docs only
        self._live: dict[str, int] = {}
        # broadcasts run optimistically ahead of the device flush (host
        # serve logs), so fan-out can complete while a trace is still
        # pending/in-flight: remember the broadcast time per doc and
        # close the trace at the cycle's readback barrier instead
        self._early_broadcast: dict[str, float] = {}

    def _resolve_tracer(self) -> Tracer:
        return self.tracer if self.tracer is not None else _default

    @property
    def enabled(self) -> bool:
        return self._resolve_tracer().enabled

    def active(self) -> bool:
        """Anything stamped and waiting for a flush? (The flush loop's
        cheap guard — one truth test per batch when tracing is idle.)"""
        return bool(self._pending)

    # -- capture seam --------------------------------------------------------

    def stamp(self, name: str) -> Optional[int]:
        """Stamp one enqueued update with a fresh trace id (respecting
        the tracer's 1-in-N sampling). Returns the id, or None when not
        sampled / tracing disabled / the pending set is full.

        A live cross-tier context (`Tracer.fleet_context`, set by the
        relay ingress pump) means the EDGE already sampled this update:
        the stamp adopts the edge's trace id instead of allocating one
        and skips local sampling, so the cell's stage spans extend the
        edge's chain under one id."""
        tracer = self._resolve_tracer()
        if not tracer.enabled:
            return None
        fleet = tracer.fleet_context
        if fleet is not None and fleet.get("id") is None:
            # a versioned-but-id-less aux (foreign producer) carries no
            # edge sampling decision: fall back to local sampling, or
            # every such update would be traced regardless of `sample`
            fleet = None
        if fleet is None and not tracer.take_sample():
            return None
        with self._lock:
            if self._pending_count >= self.MAX_PENDING:
                self.dropped += 1
                return None
            if fleet is not None:
                trace_id = fleet["id"]
            else:
                trace_id = tracer.next_trace_id()
            t_enqueue = time.perf_counter()
            # a live ingress mark anchors the trace at the websocket
            # receive instead of the capture seam (never later than the
            # enqueue: a stale mark from a previous dispatch is cleared
            # by that dispatch's finally)
            t_receive = tracer.ingress_mark
            if t_receive is not None and t_receive > t_enqueue:
                t_receive = None
            self._pending.setdefault(name, []).append(
                (trace_id, t_enqueue, t_receive, fleet)
            )
            self._pending_count += 1
            self._live[name] = self._live.get(name, 0) + 1
        return trace_id

    def unstamp(self, name: str, trace_id: int) -> None:
        """Retract a stamp whose update was not accepted by the queue
        (deduplicated or degraded mid-enqueue): the flush pipeline will
        never drain it, so it must not linger in the pending set."""
        with self._lock:
            entries = self._pending.get(name)
            if not entries:
                return
            for i, (tid, *_times) in enumerate(entries):
                if tid == trace_id:
                    entries.pop(i)
                    self._pending_count -= 1
                    self._unlive(name, 1)
                    if not entries:
                        self._pending.pop(name, None)
                    return

    # -- flush engine --------------------------------------------------------

    def take_drained(self, names, t_drain: float) -> Optional[list]:
        """Move every pending trace of the given doc names into an
        in-flight batch list, recording the drain timestamp. Returns
        None when none of the names had pending traces."""
        out: Optional[list] = None
        with self._lock:
            for name in names:
                if name is None:
                    continue
                entries = self._pending.pop(name, None)
                if not entries:
                    continue
                self._pending_count -= len(entries)
                if out is None:
                    out = []
                for trace_id, t_enqueue, t_receive, fleet in entries:
                    out.append(
                        {
                            "trace_id": trace_id,
                            "doc": name,
                            "t_enqueue": t_enqueue,
                            "t_receive": t_receive,
                            "t_drain": t_drain,
                            "fleet": fleet,
                        }
                    )
        return out

    def complete_cycle(self, trace_batches, t_sync: float) -> None:
        """Close the device-side stages for every trace drained this
        flush cycle. `trace_batches` is a list of (traces, t_build,
        t_upload, t_dispatch) per batch; `t_sync` is the cycle's single
        readback barrier, shared by every batch."""
        tracer = self._resolve_tracer()
        hist = self.histogram
        with self._lock:
            self._complete_cycle_locked(tracer, hist, trace_batches, t_sync)

    def _complete_cycle_locked(self, tracer, hist, trace_batches, t_sync: float) -> None:
        for traces, t_build, t_upload, t_dispatch in trace_batches:
            for trace in traces:
                trace_id = trace["trace_id"]
                name = trace["doc"]
                t_receive = trace.get("t_receive")
                # cross-tier traces carry a node attribute so the
                # Perfetto export groups this cell's stage spans under
                # its own role/cell lane (pid) in the merged view
                node = (
                    (self.node_id or _fleet_node())
                    if trace.get("fleet") is not None
                    else None
                )
                stages = (
                    ("queue_wait", trace["t_enqueue"], trace["t_drain"]),
                    ("build", trace["t_drain"], t_build),
                    ("upload", t_build, t_upload),
                    ("device", t_upload, t_dispatch),
                    ("readback", t_dispatch, t_sync),
                )
                if t_receive is not None:
                    # the websocket edge stamped this update: the trace
                    # opens at the frame receive, not the capture seam
                    stages = (
                        ("ingress", t_receive, trace["t_enqueue"]),
                    ) + stages
                for stage, s0, s1 in stages:
                    if node is None:
                        tracer.add_span(
                            f"update.{stage}", s0, s1, trace_id=trace_id, doc=name
                        )
                    else:
                        tracer.add_span(
                            f"update.{stage}",
                            s0,
                            s1,
                            trace_id=trace_id,
                            doc=name,
                            node=node,
                        )
                    if hist is not None:
                        hist.observe(max(s1 - s0, 0.0), stage=stage)
                trace["t_sync"] = t_sync
                self._flushed.setdefault(name, []).append(trace)
                self._flushed_count += 1
        if self._early_broadcast:
            # the fan-out already happened (broadcasts build from host
            # serve logs, ahead of the device): close those traces now,
            # with a zero-length broadcast stage ending at the barrier
            for traces, *_ in trace_batches:
                for trace in traces:
                    name = trace["doc"]
                    mark = self._early_broadcast.pop(name, None)
                    if mark is not None:
                        self.finish(name, max(mark, t_sync))
        while self._flushed_count > self.MAX_FLUSHED and self._flushed:
            # oldest-doc shedding: a doc that never broadcasts (degraded
            # mid-flight) must not pin the book
            name, entries = next(iter(self._flushed.items()))
            self._flushed.pop(name)
            self._flushed_count -= len(entries)
            self.dropped += len(entries)
            self._unlive(name, len(entries))

    # -- broadcast -----------------------------------------------------------

    def _unlive(self, name: str, count: int) -> None:
        remaining = self._live.get(name, 0) - count
        if remaining > 0:
            self._live[name] = remaining
        else:
            self._live.pop(name, None)

    def finish(self, name: str, t_now: Optional[float] = None) -> int:
        """Close every flushed trace of `name` at broadcast time: emits
        the broadcast stage span (carrying the end-to-end latency) and
        the broadcast/total histogram observations. Returns the number
        of traces closed."""
        if not self._flushed and not self._live:
            return 0  # fast path: nothing traced for any doc
        with self._lock:
            return self._finish_locked(name, t_now)

    def _finish_locked(self, name: str, t_now: Optional[float]) -> int:
        entries = self._flushed.pop(name, None) if self._flushed else None
        if not entries:
            # the broadcast outran the device pipeline for this doc's
            # trace (still pending or mid-cycle): remember the fan-out
            # moment so complete_cycle closes the trace at the barrier
            if name in self._live:
                while len(self._early_broadcast) >= self.MAX_PENDING:
                    # evict the OLDEST mark only: wiping the table would
                    # strand every other doc's already-broadcast traces
                    self._early_broadcast.pop(
                        next(iter(self._early_broadcast))
                    )
                self._early_broadcast[name] = (
                    time.perf_counter() if t_now is None else t_now
                )
            return 0
        self._flushed_count -= len(entries)
        if t_now is None:
            t_now = time.perf_counter()
        tracer = self._resolve_tracer()
        hist = self.histogram
        # slow-flush promotion threshold: explicit override, else the
        # tracer's slow-span threshold (set by --trace-slow-ms)
        slow_ms = (
            self.slow_flush_ms if self.slow_flush_ms is not None else tracer.slow_ms
        )
        for trace in entries:
            # the trace opens at the websocket receive when the ingress
            # stage exists, else at the capture seam — either way the
            # stage spans partition [t_start, t_now] exactly
            t_start = trace.get("t_receive")
            if t_start is None:
                t_start = trace["t_enqueue"]
            e2e_ms = (t_now - t_start) * 1000.0
            fleet = trace.get("fleet")
            extra_attrs = (
                {} if fleet is None else {"node": self.node_id or _fleet_node()}
            )
            tracer.add_span(
                "update.broadcast",
                trace["t_sync"],
                t_now,
                trace_id=trace["trace_id"],
                doc=name,
                e2e_ms=round(e2e_ms, 3),
                **extra_attrs,
            )
            if fleet is not None:
                # cross-tier return context: echo the edge's stamps plus
                # this process's receive/send boundaries (OUR clock) so
                # the originating edge can close the chain — deposited
                # for the relay envelope of this broadcast frame
                # (observability/fleet.py TraceReturnOutbox)
                self._deposit_fleet_return(name, fleet, t_start, t_now)
            if hist is not None:
                hist.observe(max(t_now - trace["t_sync"], 0.0), stage="broadcast")
                hist.observe(max(t_now - t_start, 0.0), stage="total")
            if (
                slow_ms is not None
                and e2e_ms >= slow_ms
                and self.on_slow_flush is not None
            ):
                try:
                    self.on_slow_flush(name, e2e_ms)
                except Exception:
                    pass
        self._unlive(name, len(entries))
        return len(entries)

    def _deposit_fleet_return(
        self, name: str, fleet: dict, t_receive: float, t_send: float
    ) -> None:
        try:
            from .fleet import get_fleet_view

            view = get_fleet_view()
            view.trace_returns.deposit(
                name,
                {
                    "id": fleet.get("id"),
                    "e": str(fleet.get("e", "")),
                    "d": name,
                    "t0": fleet.get("t0"),
                    "t1": fleet.get("t1"),
                    "h": int(fleet.get("h", 1)) + 1,
                    "tr": t_receive,
                    "ts": t_send,
                    "n": self.node_id or view.node_id or "cell",
                },
            )
        except Exception:
            pass  # tracing must never fail a broadcast

    def finish_all(self, t_now: Optional[float] = None) -> int:
        total = 0
        for name in list(self._flushed):
            total += self.finish(name, t_now)
        return total

    def drop(self, name: str) -> None:
        """Discard a doc's traces (retire/release/degrade: the pipeline
        will never complete them)."""
        if not self._live and not self._early_broadcast:
            return  # fast path: nothing ever stamped for any doc
        with self._lock:
            entries = self._pending.pop(name, None)
            if entries:
                self._pending_count -= len(entries)
            entries = self._flushed.pop(name, None)
            if entries:
                self._flushed_count -= len(entries)
            self._live.pop(name, None)
            self._early_broadcast.pop(name, None)


def _fleet_node() -> str:
    """This process's fleet node id (span `node` attribute for the
    merged cross-process Perfetto view). Lazy import: fleet.py imports
    this module."""
    try:
        from .fleet import get_fleet_view

        return get_fleet_view().node_id or "local"
    except Exception:
        return "local"


# The default tracer every instrumentation site uses. Disabled by default:
# span sites cost one attribute read + branch until somebody enables it.
_default = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _default


def enable_tracing(max_spans: Optional[int] = None) -> Tracer:
    """Enable the process-default tracer. `max_spans=None` (the default)
    preserves the current ring — repeat calls no longer silently rebuild
    a caller-sized deque back to the default size."""
    _default.enabled = True
    if max_spans is not None and _default._spans.maxlen != max_spans:
        _default._spans = deque(_default._spans, maxlen=max_spans)
    return _default


def disable_tracing() -> None:
    _default.enabled = False
