"""Lightweight span tracing for the server hot path.

The reference has no tracing (SURVEY.md §5.1 — closest is the provider's
onMessage/onOutgoingMessage taps, reference
`packages/provider/src/HocuspocusProvider.ts:156-157`, and a commented-out
message logger in `packages/server/src/MessageReceiver.ts:54-59`). This
module is the "real tracing" the TPU build adds: per-message spans, hook
chain spans, and merge-plane device-step spans, exportable as plain dicts
(one JSON-able event per span) and bridged into the JAX profiler when one
is active.

Design constraints:
- Near-zero cost when disabled: one attribute read + truth test per
  span site, no object allocation.
- No global locks on the hot path: spans complete on the event loop
  thread; the ring buffer is a `collections.deque(maxlen=...)` whose
  append is atomic under the GIL.
"""

from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Iterator, Optional


class Span:
    """One completed (or in-flight) span."""

    __slots__ = ("name", "start", "end", "attributes")

    def __init__(self, name: str, attributes: Optional[dict] = None) -> None:
        self.name = name
        self.start = time.perf_counter()
        self.end: Optional[float] = None
        self.attributes = attributes

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end is None:
            return None
        return (self.end - self.start) * 1000.0

    def set(self, key: str, value: Any) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes[key] = value

    def finish(self) -> "Span":
        self.end = time.perf_counter()
        return self

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start": self.start,
            "duration_ms": self.duration_ms,
            "attributes": self.attributes or {},
        }


class _NoopSpan:
    __slots__ = ()

    def set(self, key: str, value: Any) -> None:
        pass

    def finish(self) -> "_NoopSpan":
        return self


_NOOP_SPAN = _NoopSpan()


class Tracer:
    """Collects spans into a bounded ring buffer.

    Usage::

        tracer = Tracer(enabled=True)
        with tracer.span("message.apply", doc="report") as sp:
            ...
            sp.set("bytes", 123)
        tracer.export()  # -> list of dicts, oldest first
    """

    def __init__(self, enabled: bool = True, max_spans: int = 4096) -> None:
        self.enabled = enabled
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._jax_annotation = None  # lazily resolved TraceAnnotation class

    # -- recording ---------------------------------------------------------

    @contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[Any]:
        if not self.enabled:
            yield _NOOP_SPAN
            return
        sp = Span(name, attributes or None)
        try:
            yield sp
        finally:
            self._spans.append(sp.finish())

    @contextmanager
    def device_span(self, name: str, **attributes: Any) -> Iterator[Any]:
        """A span that also shows up in a `jax.profiler` trace when one is
        being captured (merge-plane device steps)."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        annotation = self._resolve_jax_annotation()
        if annotation is None:
            with self.span(name, **attributes) as sp:
                yield sp
            return
        with annotation(name), self.span(name, **attributes) as sp:
            yield sp

    def event(self, name: str, **attributes: Any) -> None:
        """Record an instantaneous event as a zero-duration span (state
        transitions, breaker trips — things with a moment, not an
        extent). Same near-zero disabled cost as span()."""
        if not self.enabled:
            return
        self._spans.append(Span(name, attributes or None).finish())

    def _resolve_jax_annotation(self):
        if self._jax_annotation is None:
            try:
                from jax.profiler import TraceAnnotation

                self._jax_annotation = TraceAnnotation
            except Exception:
                self._jax_annotation = False
        return self._jax_annotation or None

    # -- reading -----------------------------------------------------------

    def export(self, clear: bool = False) -> list[dict]:
        spans = [sp.to_dict() for sp in self._spans]
        if clear:
            self._spans.clear()
        return spans

    def clear(self) -> None:
        self._spans.clear()

    def __len__(self) -> int:
        return len(self._spans)


# The default tracer every instrumentation site uses. Disabled by default:
# span sites cost one attribute read + branch until somebody enables it.
_default = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _default


def enable_tracing(max_spans: int = 4096) -> Tracer:
    _default.enabled = True
    _default._spans = deque(_default._spans, maxlen=max_spans)
    return _default


def disable_tracing() -> None:
    _default.enabled = False
