"""Always-on sampling CPU profiler for the host wire path.

A background daemon thread walks ``sys._current_frames()`` at
``--profile-hz`` (default 99 Hz — deliberately co-prime with common
10/100 Hz timer work, the classic anti-lockstep trick) and folds every
thread's stack into a bounded collapsed-stack table (Brendan Gregg's
flamegraph format: ``root;caller;callee count``). The walk touches only
live frame objects already owned by the interpreter — no tracing hooks,
no sys.settrace — so measured overhead stays well under 1% at the
default rate (guarded by tests/observability/test_profiler_costs.py).

Two consumers sit on top:

- ``GET /debug/profile/cpu`` (observability/extension.py) serves the
  folded table as JSON or raw collapsed text for ``flamegraph.pl`` /
  speedscope.
- The Perfetto export (``Tracer.export_chrome_trace``) merges the
  profiler's recent-sample ring as instant events, so flamegraph time
  aligns with the lifecycle spans on one timeline.

**Triggered burst capture**: the overload controller's event-loop-lag
sampler (server/overload.py) feeds every lag reading into
``note_loop_lag``. When lag crosses ``burst_trigger_ms`` the profiler
latches a *lag episode*, grabs one high-rate burst (default 997 Hz for
0.25 s) on a short-lived thread, and attaches the top culprit stack to
a ``__profiler__`` flight-recorder event. The episode re-arms only
after lag decays below half the threshold — one burst per episode, not
one per sampler tick.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from collections import deque
from typing import Optional

from .metrics import Counter, Gauge

DEFAULT_HZ = 99.0
DEFAULT_MAX_STACKS = 4096
DEFAULT_MAX_DEPTH = 64
OVERFLOW_KEY = "__other__"

DEFAULT_BURST_HZ = 997.0
DEFAULT_BURST_S = 0.25
# matches the overload ladder's AMBER loop-lag bound
# (server/overload.py DEFAULT_THRESHOLDS["loop_lag_ms"][1])
DEFAULT_BURST_TRIGGER_MS = 200.0

_DIGITS = re.compile(r"\d+")


def _module_label(filename: str) -> str:
    # "<frozen importlib._bootstrap>" and friends: keep the dotted name,
    # drop the "<frozen >" wrapper whose space would corrupt the
    # collapsed format
    if filename.startswith("<frozen ") and filename.endswith(">"):
        return filename[len("<frozen "):-1]
    base = os.path.basename(filename)
    if base.endswith(".py"):
        base = base[:-3]
    return re.sub(r"\s+", "_", base) or "?"


def _thread_label(name: str) -> str:
    """Stable per-role label: worker pools churn through numbered names
    (``Thread-7``, ``ThreadPoolExecutor-0_3``, ``asyncio_2``); folding
    must aggregate them, not mint one root per short-lived thread.
    CPython 3.10+ appends the target (``Thread-5 (_do_shutdown)``) —
    spaces would corrupt the ``stack count`` collapsed format, so any
    non-identifier run collapses to ``_``."""
    label = _DIGITS.sub("N", name or "Thread")
    return re.sub(r"[^\w.:-]+", "_", label).strip("_") or "Thread"


def _fold(
    frame,
    root: str,
    labels: Optional[dict] = None,
    max_depth: int = DEFAULT_MAX_DEPTH,
) -> tuple[str, str]:
    """(folded stack rooted at the thread label, leaf frame label).

    ``labels`` memoizes the ``mod.func`` string per code object — the
    same frames recur sample after sample, and skipping the basename +
    f-string work on every walk is what keeps the 99 Hz steady-state
    sampler under its 1% overhead budget."""
    parts: list[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        code = frame.f_code
        label = labels.get(code) if labels is not None else None
        if label is None:
            label = f"{_module_label(code.co_filename)}.{code.co_name}"
            if labels is not None:
                if len(labels) > 16384:
                    labels.clear()
                labels[code] = label
        parts.append(label)
        frame = frame.f_back
        depth += 1
    leaf = parts[0] if parts else "?"
    parts.append(root)
    parts.reverse()
    return ";".join(parts), leaf


class SamplingProfiler:
    """Process-wide sampling profiler (one instance via get_profiler()).

    Not started by default — the Metrics extension calls
    ``ensure_started()`` at configure time, so bare library use pays
    nothing. ``hz <= 0`` disables the steady-state sampler entirely
    (``--profile-hz=0``); burst capture still works when asked.
    """

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        max_stacks: int = DEFAULT_MAX_STACKS,
        ring_size: int = 512,
    ) -> None:
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.burst_hz = DEFAULT_BURST_HZ
        self.burst_s = DEFAULT_BURST_S
        self.burst_trigger_ms = DEFAULT_BURST_TRIGGER_MS
        self._lock = threading.Lock()
        self._stacks: dict[str, int] = {}
        # per-code-object "mod.func" memo + tid -> normalized root memo:
        # touched only from the sampler threads, rebuilt when the live
        # thread set changes
        self._code_labels: dict = {}
        self._roots: dict[int, str] = {}
        # whole-stack memo: the folded label depends only on the
        # code-object chain (module.func per frame, no line numbers), so
        # an idle thread parked on the same stack costs one frame walk
        # plus one dict hit per tick instead of a 40-way string join
        self._fold_cache: dict = {}
        # parked-thread memo: tid -> ((id(frame), f_lasti, id(f_back)),
        # folded, leaf). A thread blocked in sleep/select keeps the
        # identical top frame between ticks; re-walking its 30-deep
        # stack every 10 ms is where a naive sampler burns its budget
        self._parked: dict[int, tuple] = {}
        # recent samples for the Perfetto merge: (perf_ts, tid, leaf, folded)
        self._ring: deque = deque(maxlen=ring_size)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._samples = 0
        self._dropped = 0
        self._busy_s = 0.0
        self._started_perf: Optional[float] = None
        self._wall_s_prev = 0.0  # accumulated across start/stop cycles
        # burst state
        self._episode_active = False
        self._bursts = 0
        self._burst_thread: Optional[threading.Thread] = None
        self._last_burst: Optional[dict] = None
        # metrics (adopted by the Metrics extension via register())
        self.overhead_gauge = Gauge(
            "hocuspocus_profile_overhead_fraction",
            "Measured sampling-profiler overhead as a fraction of wall time",
            fn=self.overhead_fraction,
        )
        self.samples_gauge = Gauge(
            "hocuspocus_profile_samples_total",
            "Stack samples folded by the CPU profiler since start/reset",
            fn=lambda: float(self._samples),
        )
        self.bursts_counter = Counter(
            "hocuspocus_profile_lag_bursts_total",
            "High-rate burst captures triggered by event-loop-lag episodes",
        )

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def configure(
        self,
        hz: Optional[float] = None,
        burst_trigger_ms: Optional[float] = None,
    ) -> "SamplingProfiler":
        if hz is not None:
            self.hz = float(hz)
        if burst_trigger_ms is not None:
            self.burst_trigger_ms = float(burst_trigger_ms)
        return self

    def ensure_started(self) -> "SamplingProfiler":
        if self.hz > 0 and not self.running:
            self.start()
        return self

    def start(self) -> "SamplingProfiler":
        if self.running or self.hz <= 0:
            return self
        self._stop.clear()
        self._started_perf = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, name="hocuspocus-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        thread = self._thread
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=2.0)
        if self._started_perf is not None:
            self._wall_s_prev += time.perf_counter() - self._started_perf
            self._started_perf = None
        self._thread = None

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._ring.clear()
            self._samples = 0
            self._dropped = 0
            self._busy_s = 0.0
            self._wall_s_prev = 0.0
            if self._started_perf is not None:
                self._started_perf = time.perf_counter()
            self._episode_active = False
            self._bursts = 0
            self._last_burst = None

    # -- sampling ------------------------------------------------------------

    def _loop(self) -> None:
        period = 1.0 / max(self.hz, 1e-3)
        next_t = time.perf_counter()
        while not self._stop.is_set():
            next_t += period
            delay = next_t - time.perf_counter()
            if delay > 0:
                if self._stop.wait(delay):
                    break
            else:
                # fell behind (suspend, debugger): re-anchor instead of
                # machine-gunning catch-up samples
                next_t = time.perf_counter()
            # thread_time, not perf_counter: under load the sampler
            # spends most of its wall time queued for the GIL (up to the
            # 5 ms switch interval per sample) — that wait steals nothing
            # from the workers, so the overhead metric charges only the
            # CPU the walk itself burns
            t0 = time.thread_time()
            self._sample_once()
            self._busy_s += time.thread_time() - t0

    def _sample_once(self, into: Optional[dict] = None) -> int:
        """Fold one walk of every live thread (minus the caller's own).
        ``into`` captures into a private dict (burst mode) instead of
        the steady-state table + ring."""
        own = threading.get_ident()
        try:
            frames = sys._current_frames()
        except Exception:
            return 0
        roots = self._roots
        if any(tid not in roots for tid in frames):
            # thread set changed: one enumerate() to refresh the memo
            # (also drops labels for threads that have exited)
            roots = self._roots = {
                t.ident: _thread_label(t.name)
                for t in threading.enumerate()
                if t.ident is not None
            }
            self._parked = {
                tid: hit for tid, hit in self._parked.items() if tid in frames
            }
        now = time.perf_counter()
        captured = 0
        batch: list[tuple[int, str, str]] = []
        labels = self._code_labels
        fold_cache = self._fold_cache
        parked = self._parked
        for tid, frame in frames.items():
            if tid == own:
                continue
            top_key = (id(frame), frame.f_lasti, id(frame.f_back))
            hit = parked.get(tid)
            if hit is not None and hit[0] == top_key:
                folded, leaf = hit[1], hit[2]
                captured += 1
                if into is not None:
                    into[folded] = into.get(folded, 0) + 1
                else:
                    batch.append((tid, leaf, folded))
                continue
            root = roots.get(tid, "Thread")
            codes = []
            depth = 0
            walker = frame
            while walker is not None and depth < DEFAULT_MAX_DEPTH:
                codes.append(walker.f_code)
                walker = walker.f_back
                depth += 1
            key = (root, tuple(codes))
            hit = fold_cache.get(key)
            if hit is not None:
                folded, leaf = hit
            else:
                folded, leaf = _fold(frame, root, labels)
                if len(fold_cache) > 8192:
                    fold_cache.clear()
                fold_cache[key] = (folded, leaf)
            parked[tid] = (top_key, folded, leaf)
            captured += 1
            if into is not None:
                into[folded] = into.get(folded, 0) + 1
            else:
                batch.append((tid, leaf, folded))
        if batch:
            with self._lock:
                for tid, leaf, folded in batch:
                    if (
                        folded not in self._stacks
                        and len(self._stacks) >= self.max_stacks
                    ):
                        self._dropped += 1
                        folded = OVERFLOW_KEY
                    self._stacks[folded] = self._stacks.get(folded, 0) + 1
                    self._samples += 1
                    self._ring.append((now, tid, leaf, folded))
        return captured

    # -- output --------------------------------------------------------------

    def collapsed(self) -> str:
        """Folded-stack text, one ``stack count`` line, sorted by stack
        for deterministic output under thread churn."""
        with self._lock:
            items = sorted(self._stacks.items())
        return "\n".join(f"{stack} {count}" for stack, count in items)

    def overhead_fraction(self) -> float:
        """Sampler-thread CPU seconds spent walking stacks, as a
        fraction of wall time profiled."""
        wall = self._wall_s_prev
        if self._started_perf is not None:
            wall += time.perf_counter() - self._started_perf
        if wall <= 0:
            return 0.0
        return self._busy_s / wall

    def stats(self) -> dict:
        with self._lock:
            unique = len(self._stacks)
        return {
            "running": self.running,
            "hz": self.hz,
            "samples": int(self._samples),
            "unique_stacks": unique,
            "dropped_stacks": int(self._dropped),
            "overhead_fraction": round(self.overhead_fraction(), 6),
            "burst_trigger_ms": self.burst_trigger_ms,
            "bursts_triggered": int(self._bursts),
            "last_burst": self._last_burst,
        }

    def top_stacks(self, n: int = 5) -> list[dict]:
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: (-kv[1], kv[0]))[:n]
            total = self._samples
        return [
            {
                "stack": stack,
                "samples": count,
                "share": round(count / total, 4) if total else 0.0,
            }
            for stack, count in items
        ]

    def chrome_events(self, origin_perf: float, pid: int) -> list[dict]:
        """Recent samples as Perfetto instant events (merged into
        Tracer.export_chrome_trace so stacks land on the span timeline)."""
        with self._lock:
            ring = list(self._ring)
        return [
            {
                "name": f"cpu_sample:{leaf}",
                "cat": "profiler",
                "ph": "i",
                "s": "t",
                "pid": pid,
                "tid": tid,
                "ts": round((ts - origin_perf) * 1e6, 3),
                "args": {"stack": folded},
            }
            for ts, tid, leaf, folded in ring
        ]

    def metrics(self) -> tuple:
        return (self.overhead_gauge, self.samples_gauge, self.bursts_counter)

    # -- triggered burst capture ----------------------------------------------

    def note_loop_lag(self, lag_ms: float) -> None:
        """Fed by the overload controller's loop-lag sampler. Fires ONE
        burst per lag episode: latch at ``burst_trigger_ms``, re-arm at
        half of it (same hysteresis shape as the brownout ladder)."""
        if self.burst_trigger_ms <= 0:
            return
        if lag_ms >= self.burst_trigger_ms:
            if not self._episode_active:
                self._episode_active = True
                self._bursts += 1
                self.bursts_counter.inc()
                self._start_burst(lag_ms)
        elif lag_ms < self.burst_trigger_ms / 2.0:
            self._episode_active = False

    def _start_burst(self, lag_ms: float) -> None:
        if self._burst_thread is not None and self._burst_thread.is_alive():
            return
        thread = threading.Thread(
            target=self._run_burst,
            args=(lag_ms,),
            name="hocuspocus-profiler-burst",
            daemon=True,
        )
        self._burst_thread = thread
        thread.start()

    def _run_burst(self, lag_ms: float) -> None:
        burst: dict[str, int] = {}
        period = 1.0 / max(self.burst_hz, 1.0)
        deadline = time.perf_counter() + max(self.burst_s, period)
        samples = 0
        while time.perf_counter() < deadline:
            samples += self._sample_once(into=burst)
            time.sleep(period)
        top = sorted(burst.items(), key=lambda kv: (-kv[1], kv[0]))
        top_stack, top_count = top[0] if top else ("", 0)
        self._last_burst = {
            "lag_ms": round(lag_ms, 1),
            "samples": samples,
            "top_stack": top_stack,
            "top_share": round(top_count / samples, 4) if samples else 0.0,
        }
        try:
            from .flight_recorder import get_flight_recorder

            get_flight_recorder().record(
                "__profiler__",
                "lag_burst",
                lag_ms=round(lag_ms, 1),
                samples=samples,
                top_stack=top_stack[:400],
                top_share=self._last_burst["top_share"],
            )
        except Exception:
            pass


_default = SamplingProfiler()


def get_profiler() -> SamplingProfiler:
    """Process-wide profiler singleton (same pattern as
    get_wire_telemetry / get_flight_recorder)."""
    return _default
