"""`Metrics` extension: lifecycle counters + `/metrics` endpoint.

Fills the observability hole called out in SURVEY.md §5.5 (the reference
has "No Prometheus/OTel"; its only counters are
`getDocumentsCount`/`getConnectionsCount`, reference
`packages/server/src/Hocuspocus.ts:138-160`). Add to a server like any
other extension::

    Server(extensions=[Metrics()])

and scrape `GET /metrics`. Load/store latencies are measured between the
on_*/after_* hook pairs; live gauges (connections, documents) read the
instance at scrape time.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time
from typing import Optional

from ..server.types import Extension, Payload
from .costs import get_cost_ledger
from .device_watch import compile_metrics
from .fleet import build_digest, get_fleet_view, stamp_header
from .flight_recorder import get_flight_recorder
from .metrics import MetricsRegistry
from .profiler import get_profiler
from .slo import SloEngine, counter_ratio_slo, fraction_slo, latency_slo
from .tracing import get_tracer
from .wire import get_wire_telemetry


class Metrics(Extension):
    # run before ordinary extensions so latency measurement brackets them
    priority = 1000

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        path: str = "/metrics",
        expose_tracer: bool = False,
        debug_endpoints: bool = True,
        slo_e2e_p99_ms: float = 50.0,
        slo_error_rate: float = 0.001,
        slo_fleet_e2e_ms: float = 250.0,
        slo_sample_interval_s: float = 15.0,
    ) -> None:
        self.registry = registry or MetricsRegistry()
        self.path = path
        self.expose_tracer = expose_tracer
        # /debug/trace (Perfetto JSON), /debug/profile (on-demand jax
        # profiler capture), /debug/docs[/<name>] (flight recorder),
        # /debug/slo (burn-rate rollup), /debug/loadgen (scenario-run
        # timeline), /debug/fleet (federated telemetry rollup)
        self.debug_endpoints = debug_endpoints
        self._instance = None
        self._plane_owner = None  # extension owning plane(s), for /debug/docs
        self._cell_owner = None  # multi-device cell plane (labelled gauges)
        self._slow_span_cb = None
        self._slo_task: Optional[asyncio.Task] = None

        reg = self.registry
        self.connects = reg.counter(
            "hocuspocus_connects_total", "WebSocket connections accepted"
        )
        self.disconnects = reg.counter(
            "hocuspocus_disconnects_total", "WebSocket connections closed"
        )
        self.changes = reg.counter(
            "hocuspocus_document_changes_total", "Document change events"
        )
        self.loads = reg.counter(
            "hocuspocus_document_loads_total", "Documents loaded into memory"
        )
        self.stores = reg.counter(
            "hocuspocus_document_stores_total", "Document store (persist) events"
        )
        self.unloads = reg.counter(
            "hocuspocus_document_unloads_total", "Documents unloaded from memory"
        )
        self.awareness_updates = reg.counter(
            "hocuspocus_awareness_updates_total", "Awareness update events"
        )
        self.stateless = reg.counter(
            "hocuspocus_stateless_messages_total", "Stateless messages received"
        )
        self.http_requests = reg.counter(
            "hocuspocus_http_requests_total", "Non-websocket HTTP requests"
        )
        self.load_seconds = reg.histogram(
            "hocuspocus_document_load_seconds", "onLoadDocument → afterLoadDocument"
        )
        self.store_seconds = reg.histogram(
            "hocuspocus_document_store_seconds", "onStoreDocument → afterStoreDocument"
        )
        # update-lifecycle stage latencies (docs/guides/observability.md):
        # one series per pipeline stage — queue_wait/build/upload/device/
        # readback/broadcast plus the contiguous total — fed by the
        # plane's UpdateTraceBook for every sampled traced update
        self.update_e2e = reg.histogram(
            "hocuspocus_tpu_update_e2e_seconds",
            "End-to-end update lifecycle latency by pipeline stage",
        )
        self.slow_spans = reg.counter(
            "hocuspocus_tpu_slow_spans_total",
            "Spans promoted past the --trace-slow-ms threshold, by site",
        )
        # wire-path telemetry (observability/wire.py): the socket-edge
        # counters/gauges/histograms are process-global collectors; the
        # registry adopts them so they render on this server's /metrics
        self.wire = get_wire_telemetry()
        for metric in self.wire.metrics():
            reg.register(metric)
        # overload control plane (server/overload.py): ladder state,
        # transitions, shed accounting, admission counters and signal
        # gauges — adopted like the wire collector so every deployment
        # scraping /metrics can alert on brownouts
        from ..server.overload import get_overload_controller

        for metric in get_overload_controller().metrics():
            try:
                reg.register(metric)
            except ValueError:
                pass  # already adopted (shared registry, repeat bind)
        # per-frame cost ledger + sampling CPU profiler (observability/
        # costs.py, observability/profiler.py): process-global collectors
        # adopted like the wire telemetry — the ledger's site counters,
        # the derived headroom gauge and the profiler's overhead/burst
        # series all render on this server's /metrics in deterministic
        # (sorted) order
        self.costs = get_cost_ledger()
        for metric in self.costs.metrics():
            try:
                reg.register(metric)
            except ValueError:
                pass  # already adopted (shared registry, repeat bind)
        self.profiler = get_profiler()
        for metric in self.profiler.metrics():
            try:
                reg.register(metric)
            except ValueError:
                pass  # already adopted (shared registry, repeat bind)
        # compile tracker exposition (observability/device_watch.py):
        # shared by every plane/shard in the process
        for metric in compile_metrics():
            reg.register(metric)
        # native codec availability (native/__init__.py): status gauge
        # set at first get_codec() resolution — a silent fallback to the
        # slow Python codec must be visible on /metrics
        from ..native import codec_info_metrics

        for metric in codec_info_metrics():
            try:
                reg.register(metric)
            except ValueError:
                pass  # already adopted (shared registry, repeat bind)
        # SLO engine (observability/slo.py): e2e latency + wire error
        # rate by default; the breaker-open fraction target joins when a
        # supervised plane binds. Thresholds snap to histogram bucket
        # bounds for exact good/bad counting.
        self.slo = SloEngine(sample_interval_s=slo_sample_interval_s)
        self.slo.add(
            latency_slo(
                "update_e2e_latency",
                self.update_e2e,
                threshold_s=slo_e2e_p99_ms / 1000.0,
                objective=0.99,
                stage="total",
                # description generated by the factory: it reports the
                # EFFECTIVE (bucket-snapped) threshold, not the request
            )
        )
        self.slo.add(
            counter_ratio_slo(
                "wire_error_rate",
                self.wire.messages_in,
                self.wire.errors,
                objective=1.0 - slo_error_rate,
                description=(
                    f"{1.0 - slo_error_rate:.2%} of inbound messages handled "
                    "without closing the channel"
                ),
            )
        )
        # fleet view (observability/fleet.py): the federated-telemetry
        # singleton — adopted like the wire collector, plus the fleet
        # cross-tier e2e target (--slo-fleet-e2e-ms) fed by the
        # edge-to-edge histogram. A process that never sees cross-tier
        # traffic produces no observations, so the target simply never
        # votes (no traffic != breach).
        self.fleet = get_fleet_view().enable()
        for metric in self.fleet.metrics():
            try:
                reg.register(metric)
            except ValueError:
                pass  # already adopted (shared registry, repeat bind)
        self.slo.add(
            latency_slo(
                "fleet_e2e_latency",
                self.fleet.e2e_histogram,
                threshold_s=slo_fleet_e2e_ms / 1000.0,
                objective=0.99,
                stage="total",
            )
        )
        for metric in self.slo.metrics():
            reg.register(metric)

    # -- lifecycle ---------------------------------------------------------

    async def on_configure(self, data: Payload) -> None:
        instance = data.instance
        self._instance = instance
        # light the socket edge: wire-telemetry sites cost one attribute
        # read until this flips
        self.wire.enable()
        # light the per-frame cost ledger and start the always-on
        # sampling profiler (hz<=0, e.g. --profile-hz=0, keeps it off);
        # the burst trigger rides the overload controller's loop-lag
        # sampler — membership-checked so repeat configures (and the
        # singleton profiler across test servers) install it once, and
        # re-installed here after every OverloadController.reset()
        self.costs.enable()
        self.profiler.ensure_started()
        from ..server.overload import get_overload_controller

        controller = get_overload_controller()
        if self.profiler.note_loop_lag not in controller.on_loop_lag:
            controller.on_loop_lag.append(self.profiler.note_loop_lag)
        # default fleet identity (role extensions force their own later:
        # CellIngress at configure, EdgeGateway at listen)
        self.fleet.set_identity("monolith", f"monolith-{os.getpid()}", force=False)
        self._set_build_info()
        # slow-span promotion feeds the labelled counter even when the
        # span ring has wrapped (tracing.Tracer._promote_slow fires at
        # finish time, not export time)
        if self._slow_span_cb is None:
            self._slow_span_cb = lambda sp: self.slow_spans.inc(site=sp.name)
            get_tracer().on_slow.append(self._slow_span_cb)
        self.registry.gauge(
            "hocuspocus_documents",
            "Documents currently in memory",
            fn=lambda: instance.get_documents_count(),
        )
        self.registry.gauge(
            "hocuspocus_connections",
            "Open connections (websocket + direct)",
            fn=lambda: instance.get_connections_count(),
        )
        # TPU merge plane health (degradations, serve traffic): surface
        # every plane counter so a 100k-doc deployment can alert on docs
        # silently falling off the device path. The key set is complete
        # by construction: MergePlane pre-declares every counter in
        # __init__ and retire_doc uses strict key access.
        # durability plane (storage/extension.py): WAL append/commit/
        # recovery counters + the store-quarantine population — the
        # crash-safety story must be alertable, not just logged
        self.registry.gauge(
            "hocuspocus_store_quarantined_docs",
            "Documents whose store chain exhausted its retries (kept "
            "loaded + WAL retained; /healthz reports degraded)",
            fn=lambda: len(getattr(instance, "quarantine", ()) or ()),
        )
        for extension in getattr(instance.configuration, "extensions", []):
            if callable(getattr(extension, "wal_stats", None)):
                self._bind_durability_metrics(extension)
                break
        for extension in getattr(instance.configuration, "extensions", []):
            supervisor = getattr(extension, "supervisor", None)
            if supervisor is not None and hasattr(supervisor, "snapshot"):
                # supervised plane: the runtime (and its counters) may
                # not exist yet — bind the supervisor surface now and
                # the plane metrics at hot-attach time
                self._bind_supervisor_metrics(supervisor)
                break
            if self._bind_plane_metrics(extension):
                break  # one plane per server

    def _set_build_info(self) -> None:
        """`hocuspocus_tpu_build_info 1` with version/backend/device
        labels — the standard join target for dashboards ("which build
        is this scrape from?"). Refreshed at every scrape (labels go
        stale otherwise: on the CLI TPU path jax is imported by the
        supervisor's worker thread AFTER configure) and must NEVER
        force backend init — `jax.default_backend()`/`device_count()`
        block on PJRT discovery, which is exactly the boot hang the
        plane supervisor exists to avoid. Only ALREADY-initialized
        backends are reported; until one exists the labels read
        backend="none"."""
        from .. import __version__

        backend = "none"
        device_count = 0
        if "jax" in sys.modules:
            try:
                # read the registry of initialized backends without
                # triggering initialization (a plain dict read)
                from jax._src import xla_bridge

                backends = getattr(xla_bridge, "_backends", None) or {}
                if backends:
                    # prefer the accelerator when both it and the cpu
                    # fallback backend are initialized
                    name = next(
                        (n for n in backends if n != "cpu"), next(iter(backends))
                    )
                    backend = str(name)
                    device_count = int(backends[name].device_count())
            except Exception:
                backend = "unknown"
        gauge = self.registry.gauge(
            "hocuspocus_tpu_build_info",
            "Build/runtime identity (constant 1; labels carry the data)",
        )
        gauge.clear()
        gauge.set(
            1.0,
            version=str(__version__),
            backend=backend,
            device_count=str(device_count),
        )

    def health_status(self) -> dict:
        """SLO rollup folded into `Hocuspocus.get_health()` / `/healthz`:
        a target breaching its multi-window burn-rate rule downgrades
        the server to "degraded" — the same verdict `/debug/slo` and the
        burn-rate gauges report, so the supervisor story and the SLO
        story can't disagree."""
        self.slo.maybe_sample()
        status = self.slo.status()
        breaching = [
            name for name, slo in status["slos"].items() if slo["breaching"]
        ]
        return {
            "state": "burning" if breaching else "ok",
            "degraded": bool(breaching),
            "breaching": breaching,
            "slos": {
                name: {
                    window: stats["burn_rate"]
                    for window, stats in slo["windows"].items()
                }
                for name, slo in status["slos"].items()
            },
        }

    def _bind_plane_metrics(self, owner) -> bool:
        """Register the plane-counter gauges for `owner` (an extension
        with `.plane`, or the sharded router with `.shards`). Returns
        True when a plane surface was found and bound."""
        reg = self.registry
        # device-lane arbiter telemetry (tpu/scheduler.py): wait
        # histograms per class, queue depths, occupancy, preemption/
        # starvation/deferral counters — adopted like the wire collector
        lane = getattr(owner, "lane", None)
        if lane is not None and callable(getattr(lane, "metrics", None)):
            for metric in lane.metrics():
                try:
                    reg.register(metric)
                except ValueError:
                    pass  # already adopted (shared lane, repeat bind)
        plane = getattr(owner, "plane", None)
        counters = getattr(plane, "counters", None)
        if isinstance(counters, dict):
            self._plane_owner = owner
            self._bind_trace_book(plane)
            for key in counters:
                # keys like "plane_broadcasts" already carry the prefix
                metric = f"hocuspocus_tpu_plane_{key.removeprefix('plane_')}"
                reg.gauge(
                    metric,
                    f"TPU merge plane counter: {key}",
                    fn=(lambda c=counters, k=key: c[k]),
                )
            reg.gauge(
                "hocuspocus_tpu_plane_arena_rows_in_use",
                "Arena rows (sequences) currently allocated on the plane",
                fn=(lambda p=plane: p.num_docs - len(p.free)),
            )
            reg.gauge(
                "hocuspocus_tpu_plane_ops_integrated",
                "Ops integrated by the device since start",
                fn=(lambda p=plane: p.total_integrated),
            )
            # flush-stage pipeline gauges (docs/guides/tpu-merge-
            # pipeline.md): last cycle's build/upload/device times,
            # dispatched (K, B) shape, busy width and upload volume —
            # how an operator sees host work scale with BUSY docs, not
            # the resident population
            for key in getattr(plane, "flush_stats", {}):
                reg.gauge(
                    f"hocuspocus_tpu_plane_flush_{key}",
                    f"TPU merge plane flush stage stat: {key} (last cycle)",
                    fn=(lambda p=plane, k=key: p.flush_stats[k]),
                )
            # arena occupancy (docs/guides/tpu-residency.md): capacity
            # pressure must be visible BEFORE admission starts failing.
            # free + live + retired partition the arena; retired rows
            # are allocated-but-degraded (bound to docs off the device
            # path until unload or compaction reclaims them).
            reg.gauge(
                "hocuspocus_tpu_plane_slots_free",
                "Arena rows on the free list (admission headroom)",
                fn=(lambda p=plane: len(p.free)),
            )
            reg.gauge(
                "hocuspocus_tpu_plane_slots_live",
                "Arena rows bound to live (plane-served) docs",
                fn=(lambda p=plane: int(p.slot_live.sum())),
            )
            reg.gauge(
                "hocuspocus_tpu_plane_slots_retired",
                "Arena rows held by retired/degraded docs until unload",
                fn=(
                    lambda p=plane: p.num_docs
                    - len(p.free)
                    - int(p.slot_live.sum())
                ),
            )
            # residency subsystem stats (evicted population, hydration
            # queue/latency, compaction timings)
            for key in getattr(plane, "residency_stats", {}):
                reg.gauge(
                    f"hocuspocus_tpu_plane_residency_{key}",
                    f"TPU plane residency stat: {key}",
                    fn=(lambda p=plane, k=key: p.residency_stats[k]),
                )
            # HBM watch (observability/device_watch.py): arena/staging
            # live bytes, the biggest single-cycle upload, and the
            # cumulative readback-barrier stall time
            if hasattr(plane, "memory_stats"):
                for key in plane.memory_stats():
                    reg.gauge(
                        f"hocuspocus_tpu_plane_{key}",
                        f"TPU plane device-memory stat: {key}",
                        fn=(lambda p=plane, k=key: p.memory_stats()[k]),
                    )
            return True
        shards = getattr(owner, "shards", None)
        if shards:
            self._plane_owner = owner
            # multi-device cell plane (tpu/cells.py): adopt its labelled
            # per-device gauges (docs/rows/lane-depth/HBM/work per chip,
            # migration counters, placement epoch) alongside the summed
            # shard-style aggregates below; the series refresh at scrape
            # time (on_request) from a live load snapshot
            if callable(getattr(owner, "cell_metrics", None)):
                self._cell_owner = owner
                for metric in owner.cell_metrics():
                    try:
                        reg.register(metric)
                    except ValueError:
                        pass  # already adopted (shared registry, repeat bind)
            for shard in shards:
                self._bind_trace_book(shard.plane)
            for key in shards[0].plane.counters:
                metric = f"hocuspocus_tpu_plane_{key.removeprefix('plane_')}"
                reg.gauge(
                    metric,
                    f"TPU merge plane counter (summed over shards): {key}",
                    fn=(lambda o=owner, k=key: o.counters.get(k, 0)),
                )
            reg.gauge(
                "hocuspocus_tpu_plane_arena_rows_in_use",
                "Arena rows (sequences) allocated, summed over shards",
                fn=(
                    lambda o=owner: sum(
                        s.plane.num_docs - len(s.plane.free) for s in o.shards
                    )
                ),
            )
            reg.gauge(
                "hocuspocus_tpu_plane_ops_integrated",
                "Ops integrated by the device since start, summed over shards",
                fn=(
                    lambda o=owner: sum(s.plane.total_integrated for s in o.shards)
                ),
            )
            # stage times/widths aren't summable across shards: report
            # the worst shard (the one an operator would chase)
            for key in getattr(shards[0].plane, "flush_stats", {}):
                reg.gauge(
                    f"hocuspocus_tpu_plane_flush_{key}",
                    f"TPU merge plane flush stage stat: {key} (max over shards)",
                    fn=(
                        lambda o=owner, k=key: max(
                            s.plane.flush_stats[k] for s in o.shards
                        )
                    ),
                )
            reg.gauge(
                "hocuspocus_tpu_plane_slots_free",
                "Arena rows on the free lists, summed over shards",
                fn=(lambda o=owner: sum(len(s.plane.free) for s in o.shards)),
            )
            reg.gauge(
                "hocuspocus_tpu_plane_slots_live",
                "Arena rows bound to live docs, summed over shards",
                fn=(
                    lambda o=owner: sum(
                        int(s.plane.slot_live.sum()) for s in o.shards
                    )
                ),
            )
            reg.gauge(
                "hocuspocus_tpu_plane_slots_retired",
                "Arena rows held by retired docs, summed over shards",
                fn=(
                    lambda o=owner: sum(
                        s.plane.num_docs
                        - len(s.plane.free)
                        - int(s.plane.slot_live.sum())
                        for s in o.shards
                    )
                ),
            )
            # depth/population stats sum; latency quantiles report the
            # worst shard, like the flush stage times above
            for key in getattr(shards[0].plane, "residency_stats", {}):
                if key.endswith("_ms"):
                    fn = lambda o=owner, k=key: max(
                        s.plane.residency_stats[k] for s in o.shards
                    )
                else:
                    fn = lambda o=owner, k=key: sum(
                        s.plane.residency_stats[k] for s in o.shards
                    )
                reg.gauge(
                    f"hocuspocus_tpu_plane_residency_{key}",
                    f"TPU plane residency stat: {key} (over shards)",
                    fn=fn,
                )
            if hasattr(shards[0].plane, "memory_stats"):
                # bytes/stall totals sum across shards; the upload PEAK
                # is a per-cycle maximum — summing would report an
                # upload no single cycle ever performed (same worst-
                # shard convention as the stage times above)
                for key in shards[0].plane.memory_stats():
                    if key == "upload_bytes_peak":
                        fn = lambda o=owner, k=key: max(
                            s.plane.memory_stats()[k] for s in o.shards
                        )
                        how = "max over shards"
                    else:
                        fn = lambda o=owner, k=key: sum(
                            s.plane.memory_stats()[k] for s in o.shards
                        )
                        how = "summed over shards"
                    reg.gauge(
                        f"hocuspocus_tpu_plane_{key}",
                        f"TPU plane device-memory stat: {key} ({how})",
                        fn=fn,
                    )
            return True
        return False

    def _bind_durability_metrics(self, durability) -> None:
        """One gauge per WAL stat (hocuspocus_wal_*): appended records/
        bytes, fsyncs, group-commit batch sizes, append errors, and the
        recovery report (replayed records/bytes, torn tails)."""
        # read the live stats dict directly: wal_stats() copies it, and
        # ~15 gauges x one copy each per scrape is pure garbage churn
        stats = durability.wal.stats
        for key in stats:
            self.registry.gauge(
                f"hocuspocus_wal_{key}",
                f"Write-ahead log stat: {key} (docs/guides/durability.md)",
                fn=(lambda s=stats, k=key: s[k]),
            )

    def _bind_trace_book(self, plane) -> None:
        """Point the plane's update-lifecycle trace book at the labelled
        e2e histogram, and route slow-flush promotions into the per-doc
        flight recorder."""
        book = getattr(plane, "update_traces", None)
        if book is None:
            return
        book.histogram = self.update_e2e
        if book.on_slow_flush is None:
            recorder = get_flight_recorder()
            book.on_slow_flush = lambda name, ms: recorder.record(
                name, "slow_flush", e2e_ms=round(ms, 3)
            )

    def _bind_supervisor_metrics(self, supervisor) -> None:
        """Plane supervisor surface (tpu/supervisor.py): state, breaker,
        transition counters and canary latency. Bound at configure time
        — before supervision starts at listen time — so no transition
        or probe is ever missed."""
        reg = self.registry
        reg.gauge(
            "hocuspocus_tpu_supervisor_state",
            "Plane supervisor state (0=initializing 1=ready 2=degraded 3=broken)",
            fn=supervisor.state_code,
        )
        reg.gauge(
            "hocuspocus_tpu_supervisor_breaker_state",
            "Plane circuit breaker state (0=closed 1=open 2=half_open)",
            fn=supervisor.breaker_code,
        )
        reg.gauge(
            "hocuspocus_tpu_supervisor_breaker_consecutive_failures",
            "Consecutive canary failures feeding the breaker",
            fn=(lambda b=supervisor.breaker: b.consecutive_failures),
        )
        reg.gauge(
            "hocuspocus_tpu_supervisor_canary_latency_seconds",
            "Most recent canary merge latency (0 until the first probe)",
            fn=(lambda s=supervisor: s.last_canary_latency or 0.0),
        )
        canary = reg.histogram(
            "hocuspocus_tpu_supervisor_canary_seconds",
            "Watchdog canary merge latency",
        )
        supervisor.on_canary.append(canary.observe)
        transitions = reg.counter(
            "hocuspocus_tpu_supervisor_transitions_total",
            "Supervisor state transitions",
        )
        supervisor.on_transition.append(
            lambda frm, to: transitions.inc(from_state=frm, to_state=to)
        )
        breaker_transitions = reg.counter(
            "hocuspocus_tpu_supervisor_breaker_transitions_total",
            "Circuit breaker state transitions",
        )
        supervisor.breaker.on_transition.append(
            lambda frm, to: breaker_transitions.inc(from_state=frm, to_state=to)
        )
        for key in supervisor.counters:
            reg.gauge(
                f"hocuspocus_tpu_supervisor_{key}",
                f"Plane supervisor counter: {key}",
                fn=(lambda c=supervisor.counters, k=key: c[k]),
            )
        # the plane's own counters bind the moment a runtime attaches
        supervisor.on_attach.append(self._bind_plane_metrics)
        # breaker-open fraction SLO: each engine sample observes the
        # breaker state, so the windowed fraction is time-open at
        # sample-interval resolution
        if not any(t.name == "breaker_open_fraction" for t in self.slo.targets):
            self.slo.add(
                fraction_slo(
                    "breaker_open_fraction",
                    lambda b=supervisor.breaker: b.state != "closed",
                    objective=0.99,
                    description=(
                        "plane circuit breaker closed for 99% of sampled time"
                    ),
                )
            )

    async def on_listen(self, data: Payload) -> None:
        # background burn-rate sampler: scrape-driven sampling alone
        # would leave windows empty on servers nobody is scraping yet
        if self._slo_task is None or self._slo_task.done():
            self._slo_task = asyncio.ensure_future(self._slo_sampler())
        # seed the fleet view so a fresh monolith answers /debug/fleet
        # with itself before the first sampler tick
        self._ingest_local_digest()

    async def _slo_sampler(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.slo.sample_interval_s)
                self.slo.maybe_sample()
                self._ingest_local_digest()
        except asyncio.CancelledError:
            pass

    def _ingest_local_digest(self) -> None:
        """Monolith-role federation: processes with no relay lane still
        show up in their own /debug/fleet (and any co-resident view).
        Edge/cell roles publish richer digests themselves — this ingest
        defers to them."""
        if self.fleet.role not in (None, "monolith"):
            return
        try:
            self.fleet.ingest(
                build_digest(
                    role=self.fleet.role or "monolith",
                    node_id=self.fleet.node_id or f"monolith-{os.getpid()}",
                    instance=self._instance,
                    interval_s=self.slo.sample_interval_s,
                )
            )
        except Exception:
            pass  # the sampler must never die to a digest

    async def connected(self, data: Payload) -> None:
        self.connects.inc()
        name = getattr(data, "document_name", None)
        if name:
            document = getattr(getattr(data, "connection", None), "document", None)
            get_flight_recorder().record(
                name,
                "connect",
                connections=document.get_connections_count()
                if document is not None
                else None,
            )

    async def on_disconnect(self, data: Payload) -> None:
        self.disconnects.inc()
        name = getattr(data, "document_name", None)
        if name:
            # clients_count in the disconnect payload is taken AFTER the
            # connection was removed: the audience remaining
            get_flight_recorder().record(
                name, "disconnect", connections=getattr(data, "clients_count", None)
            )

    async def on_change(self, data: Payload) -> None:
        self.changes.inc()

    # Load/store latency start times ride on the hook payload (the same
    # Payload object reaches the on_* and after_* hooks), so an aborted
    # chain cannot leak bookkeeping.

    async def on_load_document(self, data: Payload) -> None:
        data._metrics_started = time.perf_counter()

    async def after_load_document(self, data: Payload) -> None:
        self.loads.inc()
        started = getattr(data, "_metrics_started", None)
        if started is not None:
            self.load_seconds.observe(time.perf_counter() - started)

    async def on_store_document(self, data: Payload) -> None:
        data._metrics_started = time.perf_counter()

    async def after_store_document(self, data: Payload) -> None:
        self.stores.inc()
        started = getattr(data, "_metrics_started", None)
        if started is not None:
            self.store_seconds.observe(time.perf_counter() - started)

    async def after_unload_document(self, data: Payload) -> None:
        self.unloads.inc()

    async def on_awareness_update(self, data: Payload) -> None:
        self.awareness_updates.inc()

    async def on_stateless(self, data: Payload) -> None:
        self.stateless.inc()

    async def on_destroy(self, data: Payload) -> None:
        if self._slo_task is not None:
            self._slo_task.cancel()
            self._slo_task = None
        # unbind the global-tracer callback so test servers (one Metrics
        # instance each) don't accumulate dead counters on the tracer
        if self._slow_span_cb is not None:
            try:
                get_tracer().on_slow.remove(self._slow_span_cb)
            except ValueError:
                pass
            self._slow_span_cb = None

    # -- scrape + debug endpoints ------------------------------------------

    async def on_request(self, data: Payload) -> None:
        request = data.request
        path = getattr(getattr(request, "rel_url", None), "path", None) or getattr(
            request, "path", ""
        )
        if path == self.path:
            # keep the burn-rate gauges and build-info labels fresh
            self.slo.maybe_sample()
            self._set_build_info()
            if self._cell_owner is not None:
                try:
                    self._cell_owner.refresh_cell_metrics()
                except Exception:
                    pass  # a mid-teardown cell must not fail the scrape
            try:
                # hocuspocus_fleet_* rollup gauges re-label from the
                # current peer table at scrape time (like the cell gauges)
                self.fleet.refresh_gauges()
            except Exception:
                pass
            body = self.registry.expose()
            if self.expose_tracer:
                import json

                spans = get_tracer().export()
                body += "\n# tracer\n" + "\n".join(
                    "# " + json.dumps(span) for span in spans[-100:]
                ) + "\n"
            from aiohttp import web

            # Prometheus text exposition format 0.0.4: scrapers content-
            # negotiate on the version parameter. Series order is
            # deterministic (registry, label-set and bucket iteration
            # are all sorted), so consecutive scrapes diff cleanly.
            data.response = web.Response(
                body=body.encode("utf-8"),
                headers={
                    "Content-Type": "text/plain; version=0.0.4; charset=utf-8"
                },
            )
            # Raising aborts the rest of the hook chain and the default
            # "Welcome" response; the server serves `data.response` instead
            # (same mechanism as reference request interception,
            # `packages/server/src/Server.ts:114-137`).
            error = _ServeMetrics()
            error.response = data.response
            raise error
        if path == "/healthz" and self._instance is not None:
            # the supervised-plane extension serves this too (same
            # payload); Metrics covers deployments without a plane —
            # e.g. a CPU server whose durability quarantine must still
            # degrade the balancer health check. Repo-wide convention
            # (pinned by test_healthz_endpoint_reports_plane_state):
            # "degraded" still answers HTTP 200 — the server SERVES,
            # degraded is a steer signal for body-parsing probes, not a
            # kill signal that would drop every live session
            # healthz keeps its own payload contract (no debug header):
            # balancer probes parse it, and extra keys buy them nothing
            self._serve_json(data, self._instance.get_health(), stamp=False)
        if self.debug_endpoints:
            if path == "/debug/slo":
                self.slo.maybe_sample()
                status = self.slo.status()
                # overload ladder state rides the SLO surface: burn
                # rates say the budget is going, the rung says what the
                # server is already doing about it
                from ..server.overload import get_overload_controller

                status["overload"] = get_overload_controller().status()
                self._serve_json(data, status)
            if path == "/debug/fleet":
                # federated telemetry rollup (docs/guides/observability.md
                # fleet view): every live role/cell this process knows
                # about, from digests on the relay control channel plus
                # its own — the one pane for "is the fleet healthy?"
                self._serve_json(data, self.fleet.status())
            if path == "/debug/loadgen":
                # live scenario-run timeline (docs/guides/load-testing.md):
                # the loadgen runner narrates into a process-global
                # singleton; imported lazily so serving /metrics never
                # pulls the loadgen package (and its server/tpu imports)
                from ..loadgen.timeline import get_loadgen_timeline

                self._serve_json(data, get_loadgen_timeline().status())
            if path == "/debug/scheduler":
                self._serve_json(data, self._scheduler_overview())
            if path == "/debug/trace":
                self._serve_json(data, get_tracer().export_chrome_trace())
            if path == "/debug/docs":
                self._serve_json(data, self._docs_overview())
            if path.startswith("/debug/docs/"):
                from urllib.parse import unquote

                name = unquote(path[len("/debug/docs/") :])
                self._serve_json(
                    data,
                    {"doc": name, "events": get_flight_recorder().events(name)},
                )
            if path == "/debug/costs":
                # per-frame cost ledger table + headroom model
                # (docs/guides/observability.md "profiling & cost attribution")
                self._serve_json(data, self.costs.table(wire=self.wire))
            if path in ("/debug/profile", "/debug/profile/device"):
                # one /debug/profile/{device,cpu} namespace; the bare
                # path stays a device alias for existing tooling
                self._serve_json(data, await self._run_profile(request))
            if path == "/debug/profile/cpu":
                self._serve_cpu_profile(data, request)
        self.http_requests.inc()

    def _serve_json(self, data: Payload, payload: dict, stamp: bool = True) -> None:
        import json

        from aiohttp import web

        if stamp and isinstance(payload, dict):
            # every /debug payload carries the consistent attributable
            # header {"generated_utc", "role", "node_id"} — aggregated
            # or archived captures stay traceable to their source
            payload = stamp_header(payload)
        data.response = web.Response(
            text=json.dumps(payload), content_type="application/json"
        )
        error = _ServeMetrics()
        error.response = data.response
        raise error

    def _serve_cpu_profile(self, data: Payload, request) -> None:
        """`GET /debug/profile/cpu`: the sampling profiler's folded-stack
        table. Default JSON `{stats, collapsed}` with the standard
        stamped debug header; `?format=collapsed` returns the raw
        collapsed-stack text for flamegraph.pl / speedscope (every line
        stays `stack count`-parseable, so the stamp rides in X- headers
        instead)."""
        query = getattr(getattr(request, "rel_url", None), "query", None)
        if query is None:
            query = getattr(request, "query", None) or {}
        fmt = str(query.get("format", "json"))
        profiler = self.profiler
        if fmt in ("collapsed", "folded", "raw"):
            from aiohttp import web

            stamp = stamp_header({})
            data.response = web.Response(
                text=profiler.collapsed() + "\n",
                content_type="text/plain",
                headers={
                    "X-Generated-Utc": str(stamp["generated_utc"]),
                    "X-Role": str(stamp["role"]),
                    "X-Node-Id": str(stamp["node_id"]),
                },
            )
            error = _ServeMetrics()
            error.response = data.response
            raise error
        self._serve_json(
            data,
            {"stats": profiler.stats(), "collapsed": profiler.collapsed()},
        )

    async def _run_profile(self, request) -> dict:
        """On-demand `jax.profiler` capture: `GET /debug/profile?secs=N`
        traces the device for N seconds and returns the artifact
        directory (open it with TensorBoard's profile plugin or convert
        with xprof). Device spans (`Tracer.device_span`) annotate the
        capture via jax.profiler.TraceAnnotation."""
        query = getattr(getattr(request, "rel_url", None), "query", None)
        if query is None:
            query = getattr(request, "query", None) or {}
        try:
            secs = float(query.get("secs", 3.0))
        except (TypeError, ValueError):
            secs = 3.0
        secs = min(max(secs, 0.1), 60.0)
        try:
            import jax
        except Exception as error:
            return {"error": f"jax unavailable: {error!r}"}
        import tempfile

        artifact = tempfile.mkdtemp(prefix="hocuspocus-tpu-profile-")
        try:
            jax.profiler.start_trace(artifact)
        except Exception as error:
            return {"error": f"profiler start failed: {error!r}"}
        try:
            await asyncio.sleep(secs)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        return {"artifact": artifact, "seconds": secs}

    def _planes(self) -> list:
        owner = self._plane_owner
        if owner is None:
            return []
        plane = getattr(owner, "plane", None)
        if plane is not None and hasattr(plane, "_busy_slots"):
            return [plane]
        shards = getattr(owner, "shards", None)
        if shards:
            return [shard.plane for shard in shards]
        return []

    def _scheduler_overview(self) -> dict:
        """`/debug/scheduler`: the device-lane arbiter's state (classes,
        queue depths, occupancy, preemption/starvation accounting) plus
        every shard's batching-governor snapshot
        (docs/guides/tpu-scheduling.md)."""
        owner = self._plane_owner
        if owner is None:
            return {"scheduler": None, "note": "no merge plane bound"}
        runtime = getattr(owner, "runtime", None)
        if runtime is not None:
            owner = runtime  # supervised: the runtime holds lane/governor
        snapshot_fn = getattr(owner, "scheduler_snapshot", None)
        if callable(snapshot_fn):
            return snapshot_fn()
        return {"scheduler": None, "note": "plane owner has no scheduler"}

    def _docs_overview(self, top_k: int = 20) -> dict:
        """`/debug/docs`: top-K busiest docs (driven by the planes' busy
        slot sets + queue depths) and the flight recorder's
        recently-eventful docs."""
        rows: dict[str, dict] = {}
        for plane in self._planes():
            for slot in list(plane._busy_slots):
                name = plane.slot_owner.get(slot)
                if name is None:
                    continue
                row = rows.setdefault(
                    name, {"doc": name, "busy_slots": 0, "queued_ops": 0}
                )
                row["busy_slots"] += 1
                row["queued_ops"] += len(plane.queues.get(slot) or ())
        busiest = sorted(
            rows.values(), key=lambda row: -row["queued_ops"]
        )[:top_k]
        return {
            "busiest": busiest,
            "docs": get_flight_recorder().docs()[: max(top_k, 50)],
        }


class _ServeMetrics(Exception):
    """Internal: short-circuits the on_request chain with a response."""

    def __str__(self) -> str:  # suppress hook-chain error logging
        return ""
