"""Per-frame cost ledger + wire-saturation headroom model.

Cheap ``perf_counter_ns`` counters at the host codec choke points —
the sites ROADMAP 2(a) says to "profile and crush". Each instrumented
seam pays one ``ledger.enabled`` attribute read when the ledger is off
(the same contract as WireTelemetry), and three dict increments when
on. Keyed by ``(site, MessageType name)`` and exposed as

    hocuspocus_profile_frame_cost_ns{site=,type=}
    hocuspocus_profile_frames_total{site=,type=}
    hocuspocus_profile_frame_bytes_total{site=,type=}

plus the derived gauge ``hocuspocus_profile_headroom_frames_per_s``.

Site catalogue (docs/guides/observability.md "profiling & cost attribution"):

- ``frame_decode``   loop  — full inbound dispatch (decode -> handlers
                             done), same window + byte count as
                             ``hocuspocus_wire_handle_seconds`` /
                             ``bytes_in`` (server/message_receiver.py)
- ``frame_encode``   loop  — broadcast frame build (protocol/frames.py)
- ``coalesce``       loop  — per-tick update merge (server/fanout.py)
- ``fanout_tick``    loop  — one broadcast tick's socket writes
- ``varint_header``  detail— header parse inside frame_decode
- ``apply_update``   detail— CRDT apply inside frame_decode
- ``envelope_decode`` detail— relay envelope decode (edge gateway/cell
                             loops — separate processes, so kept out of
                             the server headroom sum)
- ``wal_append``     off   — WAL group commit (executor thread)

**Batch amortization** (``record_batch``): a batched codec call (one
Python->C++ crossing for N frames — parse_frame_headers_batch,
build_update_frames_batch, native coalesce) records its TOTAL ns once
with ``count=N``, so the per-(site,type) ``frames`` counter advances by
N and every derived ns/frame figure is the *amortized* per-frame cost.
The headroom model needs no special casing: loop-site totals are summed
and divided by ingress frames exactly as before, which is precisely the
amortized accounting a batched wire path should report.

**Headroom model**: sustainable frames/s per process =
1 / Σ(per-frame cost on the event-loop thread). Only the non-
overlapping ``loop`` sites enter the sum (``detail`` sites re-measure
slices *inside* frame_decode; ``wal_append`` runs off-loop), each
normalized per *ingress* frame so egress-side work (fan-out, encode)
is charged back to the frame that caused it. The number rides on
fleet digests (observability/fleet.py) so ``/debug/fleet`` shows
per-node headroom, and the ``wire_saturation`` bench pass checks it
against measured saturation (within 2x).
"""

from __future__ import annotations

import time
from typing import Optional

from .metrics import Counter, Gauge

# non-overlapping event-loop-thread sites: these sum to the per-frame
# loop cost the headroom model divides into
LOOP_SITES = ("frame_decode", "frame_encode", "coalesce", "fanout_tick")
# attribution detail measured INSIDE frame_decode (excluded from the
# headroom sum — counting them again would double-charge the frame);
# envelope_decode runs on edge gateway/cell loops (separate processes)
DETAIL_SITES = ("varint_header", "apply_update", "envelope_decode")
# off-loop work (executor threads): visible in the table, not in headroom
OFF_LOOP_SITES = ("wal_append",)
SITES = LOOP_SITES + DETAIL_SITES + OFF_LOOP_SITES


class CostLedger:
    """Process-global per-frame cost accounting (get_cost_ledger()).

    Disabled by default: library users pay one attr read per seam.
    The Metrics extension enables it at configure time.
    """

    def __init__(self) -> None:
        self.enabled = False
        self.cost_ns = Counter(
            "hocuspocus_profile_frame_cost_ns",
            "Cumulative ns spent per codec site, by site and MessageType",
        )
        self.frames = Counter(
            "hocuspocus_profile_frames_total",
            "Frames accounted per codec site, by site and MessageType",
        )
        self.bytes = Counter(
            "hocuspocus_profile_frame_bytes_total",
            "Payload bytes accounted per codec site, by site and MessageType",
        )
        self.headroom_gauge = Gauge(
            "hocuspocus_profile_headroom_frames_per_s",
            "Modeled sustainable frames/s: 1 / sum(per-frame loop-thread cost)",
            fn=self.headroom_frames_per_s,
        )

    def enable(self) -> "CostLedger":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        self.cost_ns._values.clear()
        self.frames._values.clear()
        self.bytes._values.clear()

    # -- hot path ------------------------------------------------------------

    def record(self, site: str, type_name: str, ns: int, nbytes: int = 0) -> None:
        self.cost_ns.inc(ns, site=site, type=type_name)
        self.frames.inc(site=site, type=type_name)
        if nbytes:
            self.bytes.inc(nbytes, site=site, type=type_name)

    def record_batch(
        self, site: str, type_name: str, ns: int, count: int, nbytes: int = 0
    ) -> None:
        """One batched codec call covering ``count`` frames: total ``ns``
        recorded once, frame counter advanced by ``count`` so every
        derived ns/frame figure is the amortized per-frame cost."""
        if count <= 0:
            return
        self.cost_ns.inc(ns, site=site, type=type_name)
        self.frames.inc(count, site=site, type=type_name)
        if nbytes:
            self.bytes.inc(nbytes, site=site, type=type_name)

    # -- aggregation ---------------------------------------------------------

    def _site_totals(self) -> dict:
        """{site: {"ns": total_ns, "frames": n, "bytes": b}} across types."""
        out: dict[str, dict] = {}
        for key, ns in self.cost_ns._values.items():
            labels = dict(key)
            site = labels.get("site", "?")
            agg = out.setdefault(site, {"ns": 0.0, "frames": 0.0, "bytes": 0.0})
            agg["ns"] += ns
        for key, count in self.frames._values.items():
            site = dict(key).get("site", "?")
            out.setdefault(site, {"ns": 0.0, "frames": 0.0, "bytes": 0.0})[
                "frames"
            ] += count
        for key, nbytes in self.bytes._values.items():
            site = dict(key).get("site", "?")
            out.setdefault(site, {"ns": 0.0, "frames": 0.0, "bytes": 0.0})[
                "bytes"
            ] += nbytes
        return out

    def ingress_frames(self) -> int:
        return int(
            sum(
                count
                for key, count in self.frames._values.items()
                if dict(key).get("site") == "frame_decode"
            )
        )

    def loop_ns_per_frame(self) -> float:
        """Σ(loop-site ns) normalized per ingress frame; 0.0 = no data."""
        ingress = self.ingress_frames()
        if ingress <= 0:
            return 0.0
        totals = self._site_totals()
        loop_ns = sum(totals.get(site, {}).get("ns", 0.0) for site in LOOP_SITES)
        return loop_ns / ingress

    def headroom_frames_per_s(self) -> float:
        per_frame = self.loop_ns_per_frame()
        if per_frame <= 0:
            return 0.0
        return 1e9 / per_frame

    def top_costs(self, n: int = 5) -> list[dict]:
        """Top-N (site, type) cells by total ns — the ranked hit-list
        the next host-path perf PR starts from."""
        totals = sum(self.cost_ns._values.values())
        cells = []
        for key, ns in self.cost_ns._values.items():
            labels = dict(key)
            frames = self.frames._values.get(key, 0.0)
            cells.append(
                {
                    "site": labels.get("site", "?"),
                    "type": labels.get("type", "?"),
                    "total_ns": int(ns),
                    "frames": int(frames),
                    "ns_per_frame": round(ns / frames, 1) if frames else 0.0,
                    "share": round(ns / totals, 4) if totals else 0.0,
                }
            )
        cells.sort(key=lambda c: (-c["total_ns"], c["site"], c["type"]))
        return cells[:n]

    def table(self, wire=None) -> dict:
        """The /debug/costs payload: per-(site,type) ns/frame and
        bytes/frame, each site's share of accounted wall, the headroom
        model's inputs and output, and (when wire telemetry has data)
        the measured handle p50/p99 per type — quantiles guarded on
        ``series_count`` so an empty label set never leaks the 0.0
        sentinel into the table (PR-15 convention)."""
        site_totals = self._site_totals()
        wall_ns = sum(agg["ns"] for agg in site_totals.values()) or 0.0
        rows = []
        for key in sorted(self.cost_ns._values):
            labels = dict(key)
            site, type_name = labels.get("site", "?"), labels.get("type", "?")
            ns = self.cost_ns._values[key]
            frames = self.frames._values.get(key, 0.0)
            nbytes = self.bytes._values.get(key, 0.0)
            rows.append(
                {
                    "site": site,
                    "type": type_name,
                    "frames": int(frames),
                    "total_ms": round(ns / 1e6, 3),
                    "ns_per_frame": round(ns / frames, 1) if frames else 0.0,
                    "bytes_per_frame": round(nbytes / frames, 1) if frames else 0.0,
                    "share_of_wall": round(ns / wall_ns, 4) if wall_ns else 0.0,
                }
            )
        handle_quantiles = {}
        if wire is None:
            try:
                from .wire import get_wire_telemetry

                wire = get_wire_telemetry()
            except Exception:
                wire = None
        if wire is not None:
            hist = getattr(wire, "handle_seconds", None)
            if hist is not None:
                types = {dict(key).get("type") for key in self.frames._values}
                for type_name in sorted(t for t in types if t):
                    # empty-labelset sentinel guard: quantile() returns
                    # 0.0 for a series that was never observed
                    if not hist.series_count(type=type_name):
                        continue
                    handle_quantiles[type_name] = {
                        "p50_ms": round(hist.quantile(0.5, type=type_name) * 1e3, 3),
                        "p99_ms": round(hist.quantile(0.99, type=type_name) * 1e3, 3),
                    }
        return {
            "enabled": self.enabled,
            "rows": rows,
            "sites": {
                "loop": list(LOOP_SITES),
                "detail": list(DETAIL_SITES),
                "off_loop": list(OFF_LOOP_SITES),
            },
            "ingress_frames": self.ingress_frames(),
            "loop_ns_per_frame": round(self.loop_ns_per_frame(), 1),
            "headroom_frames_per_s": round(self.headroom_frames_per_s(), 1),
            "wire_handle_quantiles_ms": handle_quantiles,
            "top_costs": self.top_costs(),
        }

    def metrics(self) -> tuple:
        return (self.cost_ns, self.frames, self.bytes, self.headroom_gauge)


_default = CostLedger()


def get_cost_ledger() -> CostLedger:
    """Process-wide cost-ledger singleton (same pattern as
    get_wire_telemetry)."""
    return _default


def now_ns() -> int:
    return time.perf_counter_ns()
