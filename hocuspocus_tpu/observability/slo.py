"""SLO engine: declarative targets + multi-window burn rates.

Raw histograms answer "how slow was it?"; nobody pages on a histogram.
This module rolls the existing labelled metrics up into the question
the north star actually asks — *is the service healthy enough for
millions of users?* — using the standard SRE formulation:

- an **SLO target** declares an objective over an event stream ("99% of
  updates complete under 50 ms", "99.9% of messages handle without
  error", "the breaker is closed 99% of the time"),
- the **burn rate** over a window is the observed bad-event fraction
  divided by the error budget (1 - objective): burn 1.0 spends the
  budget exactly at the sustainable rate, burn 14.4 exhausts a 30-day
  budget in ~2 days,
- burn is computed over **two windows** (5m and 1h): the long window
  proves the problem is real, the short window proves it is *still*
  happening — a target is `breaching` only when both exceed the alert
  threshold (the Google SRE multi-window, multi-burn-rate rule).

Collectors are cumulative `(total, bad)` callables sampled on a fixed
cadence into a bounded ring; window deltas never touch the hot path.
The engine exports `hocuspocus_tpu_slo_burn_rate{slo=,window=}` /
`_slo_error_rate` / `_slo_breaching` gauges (adopted into the `Metrics`
registry), serves `GET /debug/slo`, and feeds
`Metrics.health_status()` so `Hocuspocus.get_health()` / `/healthz`
tell the same story the SLO dashboard does.
"""

from __future__ import annotations

import time
from bisect import bisect_right
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .metrics import Counter, Gauge, Histogram

# window name -> seconds; ordered short -> long (the breach rule reads
# "every window over threshold")
DEFAULT_WINDOWS = (("5m", 300.0), ("1h", 3600.0))

# burn rate that pages: ~2% of a 30-day budget spent in one hour
DEFAULT_ALERT_BURN_RATE = 14.4


@dataclass
class SloTarget:
    """One declarative objective over a cumulative (total, bad) stream."""

    name: str
    description: str
    objective: float  # e.g. 0.99 -> 1% error budget
    collect: Callable[[], "tuple[float, float]"]
    kind: str = "error_rate"

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


def histogram_good_total(
    histogram: Histogram, threshold: float, **labels
) -> "tuple[int, int]":
    """(total, good) observations of one labelled series, where good
    means value <= threshold (bucket-resolution: the threshold should
    sit on a bucket bound for exact counting)."""
    series = histogram._series.get(tuple(sorted(labels.items())))
    if series is None:
        return 0, 0
    counts, _sum, total = series
    cut = bisect_right(histogram.buckets, threshold)
    return total, sum(counts[:cut])


def snap_to_bucket(histogram: Histogram, threshold: float) -> float:
    """Nearest bucket bound to `threshold`. Good/bad counting is
    bucket-resolution: an off-bound threshold would silently count the
    whole (prev_bound, threshold] range as bad, so thresholds SNAP and
    the effective value is surfaced in the target description."""
    if not histogram.buckets:
        return threshold
    return min(histogram.buckets, key=lambda bound: abs(bound - threshold))


def latency_slo(
    name: str,
    histogram: Histogram,
    threshold_s: float,
    objective: float = 0.99,
    stage: str = "total",
    description: Optional[str] = None,
) -> SloTarget:
    """Quantile-style objective from a labelled histogram: `objective`
    of observations must complete within `threshold_s` (p99 < 50ms ==
    objective 0.99, threshold 0.05). The threshold snaps to the nearest
    bucket bound — counting is exact at bounds and wrong everywhere
    else."""
    effective = snap_to_bucket(histogram, threshold_s)

    def collect() -> "tuple[float, float]":
        total, good = histogram_good_total(histogram, effective, stage=stage)
        return total, total - good

    suffix = (
        ""
        if effective == threshold_s
        else f" (snapped from {threshold_s * 1000:g}ms to a bucket bound)"
    )
    return SloTarget(
        name=name,
        description=description
        or f"{objective:.0%} of '{stage}' observations <= {effective * 1000:g}ms{suffix}",
        objective=objective,
        collect=collect,
        kind="latency",
    )


def counter_ratio_slo(
    name: str,
    total_counter: Counter,
    bad_counter: Counter,
    objective: float = 0.999,
    description: Optional[str] = None,
) -> SloTarget:
    """Error-rate objective from two counters (all label sets summed)."""

    def collect() -> "tuple[float, float]":
        total = sum(total_counter._values.values())
        bad = sum(bad_counter._values.values())
        return total, bad

    return SloTarget(
        name=name,
        description=description or f"{objective:.1%} of events without error",
        objective=objective,
        collect=collect,
        kind="error_rate",
    )


class FractionProbe:
    """Adapts an instantaneous 0/1 probe ("is the breaker open right
    now?") to the cumulative (total, bad) collector contract: each
    engine sample counts one observation, so the window fraction is
    time-in-state at sample resolution."""

    def __init__(self, probe: Callable[[], bool]) -> None:
        self.probe = probe
        self.total = 0
        self.bad = 0

    def __call__(self) -> "tuple[float, float]":
        self.total += 1
        try:
            if self.probe():
                self.bad += 1
        except Exception:
            pass
        return self.total, self.bad


def fraction_slo(
    name: str,
    probe: Callable[[], bool],
    objective: float = 0.99,
    description: Optional[str] = None,
) -> SloTarget:
    return SloTarget(
        name=name,
        description=description
        or f"bad-state fraction under {1 - objective:.1%} of sampled time",
        objective=objective,
        collect=FractionProbe(probe),
        kind="fraction",
    )


@dataclass
class _WindowStat:
    burn_rate: Optional[float]
    error_rate: Optional[float]
    total: float
    bad: float
    covered_s: float


class SloEngine:
    """Samples collectors on a cadence, computes windowed burn rates."""

    def __init__(
        self,
        targets: Sequence[SloTarget] = (),
        windows: Sequence["tuple[str, float]"] = DEFAULT_WINDOWS,
        sample_interval_s: float = 15.0,
        alert_burn_rate: float = DEFAULT_ALERT_BURN_RATE,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.targets: "list[SloTarget]" = list(targets)
        self.windows = tuple(windows)
        self.sample_interval_s = sample_interval_s
        self.alert_burn_rate = alert_burn_rate
        self._clock = clock
        longest = max((secs for _, secs in self.windows), default=3600.0)
        # +2: one spare sample past the window tail so the delta anchor
        # exists, one for the in-progress interval
        self._samples: deque = deque(
            maxlen=int(longest / max(sample_interval_s, 1e-3)) + 2
        )
        self._last_sample: Optional[float] = None
        # exported gauges (adopted into the Metrics registry)
        self.burn_gauge = Gauge(
            "hocuspocus_tpu_slo_burn_rate",
            "SLO burn rate by target and window (1.0 = budget spent exactly "
            "at the sustainable rate)",
        )
        self.error_rate_gauge = Gauge(
            "hocuspocus_tpu_slo_error_rate",
            "Observed bad-event fraction by target and window",
        )
        self.breaching_gauge = Gauge(
            "hocuspocus_tpu_slo_breaching",
            "1 when a target's burn rate exceeds the alert threshold on "
            "every window (multi-window rule)",
        )

    def add(self, target: SloTarget) -> SloTarget:
        self.targets.append(target)
        return target

    def metrics(self):
        return (self.burn_gauge, self.error_rate_gauge, self.breaching_gauge)

    # -- sampling ------------------------------------------------------------

    def maybe_sample(self) -> bool:
        """Sample if the cadence elapsed (the scrape/debug endpoints and
        the background ticker both call this; double-driving is safe)."""
        now = self._clock()
        if (
            self._last_sample is not None
            and now - self._last_sample < self.sample_interval_s
        ):
            return False
        self.sample(now)
        return True

    def sample(self, now: Optional[float] = None) -> None:
        if now is None:
            now = self._clock()
        snapshot = {}
        for target in self.targets:
            try:
                total, bad = target.collect()
            except Exception:
                continue
            snapshot[target.name] = (float(total), float(bad))
        self._samples.append((now, snapshot))
        self._last_sample = now
        self._update_gauges(now)

    # -- reading -------------------------------------------------------------

    def _window_stat(
        self, target: SloTarget, window_s: float, now: float
    ) -> _WindowStat:
        """Delta between the newest sample and the newest sample at or
        before the window start (standard rate() anchoring: a partial
        window reports over the time actually covered)."""
        if not self._samples:
            return _WindowStat(None, None, 0.0, 0.0, 0.0)
        newest_t, newest = self._samples[-1]
        anchor_t, anchor = self._samples[0]
        for t, snapshot in reversed(self._samples):
            if t <= now - window_s:
                anchor_t, anchor = t, snapshot
                break
        cur = newest.get(target.name)
        old = anchor.get(target.name)
        if cur is None:
            return _WindowStat(None, None, 0.0, 0.0, 0.0)
        if old is None:
            old = (0.0, 0.0)
        total = max(cur[0] - old[0], 0.0)
        bad = max(cur[1] - old[1], 0.0)
        covered = max(newest_t - anchor_t, 0.0)
        if total <= 0:
            return _WindowStat(None, None, total, bad, covered)
        error_rate = bad / total
        return _WindowStat(
            error_rate / target.error_budget, error_rate, total, bad, covered
        )

    def burn_rate(self, name: str, window: str) -> Optional[float]:
        target = next((t for t in self.targets if t.name == name), None)
        window_s = dict(self.windows).get(window)
        if target is None or window_s is None:
            return None
        return self._window_stat(target, window_s, self._clock()).burn_rate

    def breaching(self, target: SloTarget, now: Optional[float] = None) -> bool:
        """Multi-window rule: every window's burn rate over threshold.
        Windows without traffic don't breach, and neither do windows
        without full coverage — during early uptime the 1h window
        would otherwise degenerate to "since start" and a startup
        reconnect blip could drain a freshly restarted instance. Until
        an hour of samples exists, the long window simply can't vote."""
        if now is None:
            now = self._clock()
        slack = max(self.sample_interval_s, 1.0)
        for _name, window_s in self.windows:
            stat = self._window_stat(target, window_s, now)
            if stat.burn_rate is None:
                return False
            if stat.covered_s + slack < window_s:
                return False  # partial window: not enough history to vote
            if stat.burn_rate < self.alert_burn_rate:
                return False
        return bool(self.windows)

    def status(self) -> dict:
        """JSON-able rollup for /debug/slo and get_health()."""
        now = self._clock()
        slos = {}
        any_breaching = False
        for target in self.targets:
            windows = {}
            for name, window_s in self.windows:
                stat = self._window_stat(target, window_s, now)
                windows[name] = {
                    "burn_rate": None
                    if stat.burn_rate is None
                    else round(stat.burn_rate, 4),
                    "error_rate": None
                    if stat.error_rate is None
                    else round(stat.error_rate, 6),
                    "total": stat.total,
                    "bad": stat.bad,
                    "covered_s": round(stat.covered_s, 1),
                }
            is_breaching = self.breaching(target, now)
            any_breaching = any_breaching or is_breaching
            slos[target.name] = {
                "description": target.description,
                "kind": target.kind,
                "objective": target.objective,
                "error_budget": target.error_budget,
                "breaching": is_breaching,
                "windows": windows,
            }
        return {
            "healthy": not any_breaching,
            "alert_burn_rate": self.alert_burn_rate,
            "sample_interval_s": self.sample_interval_s,
            "samples": len(self._samples),
            "slos": slos,
        }

    def _update_gauges(self, now: float) -> None:
        for target in self.targets:
            for name, window_s in self.windows:
                stat = self._window_stat(target, window_s, now)
                self.burn_gauge.set(
                    stat.burn_rate if stat.burn_rate is not None else 0.0,
                    slo=target.name,
                    window=name,
                )
                self.error_rate_gauge.set(
                    stat.error_rate if stat.error_rate is not None else 0.0,
                    slo=target.name,
                    window=name,
                )
            self.breaching_gauge.set(
                1.0 if self.breaching(target, now) else 0.0, slo=target.name
            )
