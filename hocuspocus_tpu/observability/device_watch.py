"""Device runtime watch: compile events, HBM footprint, readback stalls.

The flush engine's stage gauges (PR 2) say where a cycle spent time;
this module says what the XLA runtime underneath was doing:

- **CompileTracker** wraps every jitted entry point the plane dispatches
  (warm-grid warmup, canary probes, live flush batches) and classifies
  each dispatch per (site, shape) key: the first dispatch of a key is a
  *fresh compile* (it pays XLA/Mosaic compilation inline), every later
  one is a *cache hit*. Durations land in
  `hocuspocus_tpu_compile_seconds{kind=}` and counts in
  `hocuspocus_tpu_compile_events_total{kind=,site=,shape=}`. Fresh
  compiles at shapes the warm grid should have covered are the
  recompile-storm signal: past `storm_threshold` of them inside
  `storm_window_s`, the tracker emits a structured WARNING log and a
  `compile_storm` flight-recorder event under `__plane__`.
- **pytree_nbytes** sizes the plane's device state / staging buffers so
  arena live-byte gauges can watch HBM pressure next to the occupancy
  gauges (slots say *rows*; these say *bytes*).

Always cheap: one set lookup + dict increments per device dispatch, no
locks (dispatches already run under the plane's step lock).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterator, Optional

from .flight_recorder import get_flight_recorder
from .metrics import Counter, Histogram

_storm_logger = logging.getLogger("hocuspocus_tpu.device_watch")

# compile-oriented buckets: cache hits are sub-millisecond dispatches,
# cold Mosaic compiles run tens of seconds on a real TPU
COMPILE_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def shape_label(shape) -> str:
    """(16, 4) -> "16x4" — the Prometheus label for a batch shape."""
    if isinstance(shape, (tuple, list)):
        return "x".join(str(int(dim)) for dim in shape)
    return str(shape)


# process-shared metric objects: every plane's tracker (incl. each shard
# of a sharded deployment) feeds the same exposition family — which
# matches the runtime, since XLA's compilation cache is process-wide
_compile_seconds = Histogram(
    "hocuspocus_tpu_compile_seconds",
    "Jitted dispatch wall time, by kind (compile = first call at a "
    "(site, shape) key, hit = cached program)",
    buckets=COMPILE_BUCKETS,
)
_compile_events = Counter(
    "hocuspocus_tpu_compile_events_total",
    "Jitted dispatches by kind/site/shape",
)
_compile_storms = Counter(
    "hocuspocus_tpu_compile_storms_total",
    "Recompile storms detected (fresh compiles past the warm grid)",
)


def compile_metrics():
    """The shared compile metric objects, for registry adoption."""
    return (_compile_seconds, _compile_events, _compile_storms)


class CompileTracker:
    """First-compile vs cache-hit classification per (site, shape)."""

    def __init__(
        self, storm_window_s: float = 60.0, storm_threshold: int = 3
    ) -> None:
        self.storm_window_s = storm_window_s
        self.storm_threshold = storm_threshold
        self.compile_seconds = _compile_seconds
        self.compile_events = _compile_events
        self.storms = _compile_storms
        self._seen: set = set()
        self._warmed = False
        # timestamps of post-warmup fresh compiles inside the storm window
        self._recent: deque[float] = deque()
        self.fresh_compiles = 0
        self.cache_hits = 0
        self.last_compile_s: Optional[float] = None

    def mark_warmed(self) -> None:
        """The warm grid completed: from here on, fresh compiles are
        unexpected (a shape the grid missed, or the runtime dropped its
        cache) and count toward the storm detector."""
        self._warmed = True

    def seen(self, site: str, shape) -> bool:
        return (site, shape_label(shape)) in self._seen

    def mark_covered(self, site: str, shape) -> None:
        """The process-wide jit cache already holds this (site, shape)
        program — another plane's warm pass compiled it (tpu/scheduler.py
        shared warm registry). Seed the seen set so this plane's live
        dispatches classify as the cache hits they are, without charging
        a fresh compile this tracker never paid (and without the storm
        detector firing on a warmed-elsewhere shape)."""
        self._seen.add((site, shape_label(shape)))

    def observe(
        self, site: str, shape, seconds: float, warmup: bool = False
    ) -> str:
        """Record one dispatch; returns "compile" or "hit"."""
        label = shape_label(shape)
        key = (site, label)
        fresh = key not in self._seen
        if fresh:
            self._seen.add(key)
            self.fresh_compiles += 1
            self.last_compile_s = seconds
        else:
            self.cache_hits += 1
        kind = "compile" if fresh else "hit"
        self.compile_events.inc(kind=kind, site=site, shape=label)
        self.compile_seconds.observe(seconds, kind=kind)
        if fresh and not warmup and self._warmed:
            self._note_unexpected_compile(site, label, seconds)
        return kind

    @contextmanager
    def track(self, site: str, shape, warmup: bool = False) -> Iterator[None]:
        started = time.perf_counter()
        try:
            yield
        finally:
            self.observe(site, shape, time.perf_counter() - started, warmup=warmup)

    def _note_unexpected_compile(self, site: str, label: str, seconds: float) -> None:
        now = time.monotonic()
        self._recent.append(now)
        while self._recent and now - self._recent[0] > self.storm_window_s:
            self._recent.popleft()
        if len(self._recent) < self.storm_threshold:
            return
        count = len(self._recent)
        self._recent.clear()  # one storm per burst, then re-arm
        self.storms.inc()
        try:
            _storm_logger.warning(
                "recompile storm: %d fresh compiles within %.0fs after the "
                "warm grid (latest site=%s shape=%s %.3fs) — the flush "
                "shapes have drifted off the warmed (k, b) buckets",
                count,
                self.storm_window_s,
                site,
                label,
                seconds,
            )
        except Exception:
            pass
        get_flight_recorder().record(
            "__plane__",
            "compile_storm",
            compiles=count,
            window_s=self.storm_window_s,
            site=site,
            shape=label,
        )

    def snapshot(self) -> dict:
        return {
            "fresh_compiles": self.fresh_compiles,
            "cache_hits": self.cache_hits,
            "shapes_seen": len(self._seen),
            "storms": sum(self.storms._values.values()),
            "warmed": self._warmed,
            "last_compile_s": self.last_compile_s,
        }


def pytree_nbytes(tree) -> int:
    """Total bytes of every array leaf in a (possibly nested) structure.

    Works for jax arrays, numpy arrays and namedtuple/tuple states; any
    leaf without `.nbytes` counts zero. Never imports jax itself."""
    total = 0
    stack = [tree]
    while stack:
        node = stack.pop()
        nbytes = getattr(node, "nbytes", None)
        if nbytes is not None and not isinstance(node, (str, bytes)):
            try:
                total += int(nbytes)
                continue
            except Exception:
                continue
        if isinstance(node, (tuple, list)):
            stack.extend(node)
        elif isinstance(node, dict):
            stack.extend(node.values())
        elif hasattr(node, "_fields"):  # namedtuple without tuple iter
            stack.extend(getattr(node, field) for field in node._fields)
    return total
