"""Metrics primitives + Prometheus text exposition.

The reference exposes only ad-hoc counters (`getDocumentsCount`,
`getConnectionsCount` — reference `packages/server/src/Hocuspocus.ts:138-160`)
and has "No Prometheus/OTel" (SURVEY.md §5.5). This registry is the
framework-native replacement: counters, gauges and fixed-bucket
histograms rendered in the Prometheus text format, served by the
`Metrics` extension at `/metrics`.

Everything runs on the asyncio event-loop thread; increments are plain
float adds (no locks needed under the GIL).
"""

from __future__ import annotations

import math
from bisect import bisect_left
from typing import Callable, Iterable, Optional, Sequence


def _escape_label_value(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    value = float(value)
    if value != value:
        return "NaN"
    if value.is_integer() and abs(value) < 1e17:
        return str(int(value))
    # shortest round-trip decimal: the smallest %g precision whose
    # output parses back to the same double (repr-style, but without
    # repr's exponent/format quirks leaking into the exposition —
    # float32-ish inputs like 0.30000000000000004 keep every digit they
    # genuinely need and nothing more)
    for precision in range(1, 18):
        text = format(value, f".{precision}g")
        if float(text) == value:
            return text
    return format(value, ".17g")


class Counter:
    """Monotonically increasing counter, optionally labelled."""

    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        if not self._values:
            yield f"{self.name} 0"
            return
        for key, value in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(value)}"


class Gauge:
    """Settable value; can also track a live callable (e.g. connection
    counts read straight off the instance at scrape time). Optionally
    labelled: `set(1.0, slo="e2e", window="5m")` keeps one series per
    label set, exposed in sorted label order (deterministic scrapes)."""

    def __init__(
        self, name: str, help: str, fn: Optional[Callable[[], float]] = None
    ) -> None:
        self.name = name
        self.help = help
        self._fn = fn
        self._series: dict[tuple, float] = {}

    def set(self, value: float, **labels: str) -> None:
        self._series[tuple(sorted(labels.items()))] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        if self._fn is not None and not labels:
            return float(self._fn())
        return self._series.get(tuple(sorted(labels.items())), 0.0)

    def clear(self) -> None:
        """Drop every labelled series (for gauges whose label VALUES
        change over time — e.g. build_info's backend label once the
        runtime attaches — so stale series don't linger)."""
        self._series.clear()

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        if self._fn is not None:
            yield f"{self.name} {_fmt_value(float(self._fn()))}"
            return
        if not self._series:
            yield f"{self.name} 0"
            return
        for key, value in sorted(self._series.items()):
            yield f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(value)}"


DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


class Histogram:
    """Fixed-bucket histogram (seconds by convention, like Prometheus),
    optionally labelled: `observe(value, stage="build")` keeps one
    bucket series per label set, exposed with the labels merged into
    each `_bucket`/`_sum`/`_count` sample. Bucket lookup is a `bisect`
    over the sorted bounds — this sits on the per-update hot path once
    the e2e lifecycle histograms are wired in."""

    def __init__(
        self, name: str, help: str, buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        # labels key -> [bucket counts (+1 for +Inf), sum, total]
        self._series: dict[tuple, list] = {}

    def _series_for(self, labels: dict) -> list:
        key = tuple(sorted(labels.items()))
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = [
                [0] * (len(self.buckets) + 1),
                0.0,
                0,
            ]
        return series

    def observe(self, value: float, **labels: str) -> None:
        series = self._series_for(labels)
        # first bucket whose bound >= value (le semantics); past the
        # end = the +Inf bucket
        series[0][bisect_left(self.buckets, value)] += 1
        series[1] += value
        series[2] += 1

    @property
    def count(self) -> int:
        return sum(series[2] for series in self._series.values())

    @property
    def sum(self) -> float:
        return sum(series[1] for series in self._series.values())

    def series_count(self, **labels: str) -> int:
        series = self._series.get(tuple(sorted(labels.items())))
        return 0 if series is None else series[2]

    def quantile(self, q: float, **labels: str) -> float:
        """Estimated q-quantile for one label set (linear interpolation
        within the landing bucket, like PromQL's histogram_quantile).

        Degenerate label sets return the documented sentinel **0.0**:
        a missing series, a series with zero observations, or a
        histogram built with no finite buckets (where every observation
        lands in +Inf and no bound can localize the quantile). Callers
        that must distinguish "no data" from "fast" should guard on
        `series_count(**labels)` first — rollups (e.g. FleetView) skip
        empty series rather than averaging sentinel zeros in."""
        series = self._series.get(tuple(sorted(labels.items())))
        if series is None or series[2] == 0 or not self.buckets:
            return 0.0
        target = q * series[2]
        cumulative = 0
        for i, bound in enumerate(self.buckets):
            prev = cumulative
            cumulative += series[0][i]
            if cumulative >= target:
                lower = self.buckets[i - 1] if i > 0 else 0.0
                in_bucket = series[0][i]
                frac = (target - prev) / in_bucket if in_bucket else 0.0
                return lower + (bound - lower) * frac
        # every counted observation sits past the last finite bound
        # (the +Inf bucket): report the last bound, the best the
        # bucket resolution can say
        return self.buckets[-1]

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        series = self._series or {(): [[0] * (len(self.buckets) + 1), 0.0, 0]}
        for key in sorted(series):
            counts, total_sum, total = series[key]
            labels = dict(key)
            cumulative = 0
            for bound, count in zip(self.buckets, counts):
                cumulative += count
                yield (
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**labels, 'le': _fmt_value(bound)})} {cumulative}"
                )
            cumulative += counts[-1]
            yield (
                f"{self.name}_bucket"
                f"{_fmt_labels({**labels, 'le': '+Inf'})} {cumulative}"
            )
            yield f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total_sum)}"
            yield f"{self.name}_count{_fmt_labels(labels)} {total}"


class MetricsRegistry:
    """Holds metrics and renders the exposition document."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Counter(name, help)
            self._metrics[name] = metric
        if not isinstance(metric, Counter):
            raise TypeError(f"metric {name!r} already registered as {type(metric).__name__}")
        return metric

    def gauge(
        self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None
    ) -> Gauge:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Gauge(name, help, fn)
            self._metrics[name] = metric
        if not isinstance(metric, Gauge):
            raise TypeError(f"metric {name!r} already registered as {type(metric).__name__}")
        if fn is not None:
            metric._fn = fn
        return metric

    def histogram(
        self, name: str, help: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Histogram(name, help, buckets)
            self._metrics[name] = metric
        if not isinstance(metric, Histogram):
            raise TypeError(f"metric {name!r} already registered as {type(metric).__name__}")
        return metric

    def register(self, metric) -> None:
        """Adopt a pre-built metric object (Counter/Gauge/Histogram) into
        this registry's exposition — how process-global collectors (the
        wire telemetry singleton, the compile tracker) surface on one
        server's /metrics without being constructed by it."""
        existing = self._metrics.get(metric.name)
        if existing is not None and existing is not metric:
            raise ValueError(f"metric {metric.name!r} already registered")
        self._metrics[metric.name] = metric

    def expose(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].expose())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"
