"""Metrics primitives + Prometheus text exposition.

The reference exposes only ad-hoc counters (`getDocumentsCount`,
`getConnectionsCount` — reference `packages/server/src/Hocuspocus.ts:138-160`)
and has "No Prometheus/OTel" (SURVEY.md §5.5). This registry is the
framework-native replacement: counters, gauges and fixed-bucket
histograms rendered in the Prometheus text format, served by the
`Metrics` extension at `/metrics`.

Everything runs on the asyncio event-loop thread; increments are plain
float adds (no locks needed under the GIL).
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, Optional, Sequence


def _escape_label_value(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class Counter:
    """Monotonically increasing counter, optionally labelled."""

    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        if not self._values:
            yield f"{self.name} 0"
            return
        for key, value in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(value)}"


class Gauge:
    """Settable value; can also track a live callable (e.g. connection
    counts read straight off the instance at scrape time)."""

    def __init__(
        self, name: str, help: str, fn: Optional[Callable[[], float]] = None
    ) -> None:
        self.name = name
        self.help = help
        self._fn = fn
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = value

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self._value -= amount

    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        return self._value

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        yield f"{self.name} {_fmt_value(self.value())}"


DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


class Histogram:
    """Fixed-bucket histogram (seconds by convention, like Prometheus)."""

    def __init__(
        self, name: str, help: str, buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._total = 0

    def observe(self, value: float) -> None:
        self._sum += value
        self._total += 1
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                self._counts[i] += 1
                return
        self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._total

    @property
    def sum(self) -> float:
        return self._sum

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        cumulative = 0
        for bound, count in zip(self.buckets, self._counts):
            cumulative += count
            yield f'{self.name}_bucket{{le="{_fmt_value(bound)}"}} {cumulative}'
        cumulative += self._counts[-1]
        yield f'{self.name}_bucket{{le="+Inf"}} {cumulative}'
        yield f"{self.name}_sum {_fmt_value(self._sum)}"
        yield f"{self.name}_count {self._total}"


class MetricsRegistry:
    """Holds metrics and renders the exposition document."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Counter(name, help)
            self._metrics[name] = metric
        if not isinstance(metric, Counter):
            raise TypeError(f"metric {name!r} already registered as {type(metric).__name__}")
        return metric

    def gauge(
        self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None
    ) -> Gauge:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Gauge(name, help, fn)
            self._metrics[name] = metric
        if not isinstance(metric, Gauge):
            raise TypeError(f"metric {name!r} already registered as {type(metric).__name__}")
        if fn is not None:
            metric._fn = fn
        return metric

    def histogram(
        self, name: str, help: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Histogram(name, help, buckets)
            self._metrics[name] = metric
        if not isinstance(metric, Histogram):
            raise TypeError(f"metric {name!r} already registered as {type(metric).__name__}")
        return metric

    def expose(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].expose())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"
