"""Fleet observability plane: telemetry federation + cross-tier tracing glue.

PR 13/14 split the monolith into stateless edges relaying to multi-device
merge cells, but every observability surface stayed per-process: answering
"is the fleet healthy?" meant curling N processes and stitching the
answers by hand. This module is the single pane:

- **Digests.** Every role (edge / cell / monolith) publishes a compact
  periodic telemetry digest — health rung, SLO burn rates, lane/queue
  depths, session counts, placement epoch, per-device cell stats — on the
  existing ``{prefix}:cells`` relay control channel (`edge/relay.DIGEST`
  envelopes). `build_digest` assembles one from the process-global
  collectors plus whatever the publishing role passes in `extra`.

- **`FleetView`.** A process-global singleton (like the wire collector,
  enabled by the `Metrics` extension) ingesting digests into a bounded
  per-peer ring. It serves ``GET /debug/fleet`` (role table, per-cell /
  per-device rollups, placement-epoch skew detection, stale-peer
  flagging), exports ``hocuspocus_fleet_*`` rollup gauges, and records
  topology transitions (`peer_up` / `peer_stale` / `peer_down` /
  `epoch_skew_detected`) in the flight recorder's ``__fleet__`` ring —
  silent drift is diagnosable after the fact, mirroring the
  ``__edge__``/``__overload__`` conventions.

- **Cross-tier trace plumbing.** `ClockOffsetEstimator` turns the edge's
  relay PING/PONG exchange into a smoothed peer-clock offset (NTP-style
  RTT midpoint), and `TraceReturnOutbox` carries a traced update's
  return context from the cell's trace book to the relay envelope headed
  back to the originating edge. The edge folds any one-way skew into the
  two relay spans (clamped at zero) so the full
  ``edge_ingress → relay_out → [cell stages] → relay_return →
  edge_egress`` chain still sums exactly to the edge-to-edge e2e — which
  feeds the ``hocuspocus_fleet_e2e_seconds`` histogram and the
  ``--slo-fleet-e2e-ms`` target.

Rollups skip peers that do not report a field (an edge has no documents;
a freshly-booted cell has no burn rates yet) instead of averaging zeros
in, and quantile reads guard on the observation count so an empty
histogram contributes nothing.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import deque
from typing import Any, Optional

from .flight_recorder import get_flight_recorder
from .metrics import Counter, Gauge, Histogram

DIGEST_VERSION = 1

# peers are stale after max(floor, STALE_INTERVALS x their own declared
# publish interval), and down after DOWN_FACTOR x the stale threshold;
# down peers are FORGOTTEN (rings, state, offsets dropped) once quiet
# past FORGET_FACTOR x the stale threshold — edges default to per-boot
# uuid identities, so a churning fleet mints new node ids forever and
# an unevicted peer table would grow without bound. MAX_PEERS is the
# hard backstop (oldest non-up peers shed first).
STALE_FLOOR_S = 5.0
STALE_INTERVALS = 3.0
DOWN_FACTOR = 5.0
FORGET_FACTOR = 20.0
MAX_PEERS = 256

# cross-tier stage names (the edge-side spans; the cell's interior
# stages are the existing update-lifecycle chain)
EDGE_STAGES = ("edge_ingress", "relay_out", "relay_return", "edge_egress")


def utc_stamp(ts: Optional[float] = None) -> str:
    """ISO-8601 UTC second-resolution stamp for attributable payloads."""
    return time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time() if ts is None else ts)
    )


def stamp_header(payload: dict) -> dict:
    """The consistent top-level `{"generated_utc", "role", "node_id"}`
    header every /debug endpoint stamps, so aggregated or archived
    payloads stay attributable. Existing keys are never overwritten."""
    view = get_fleet_view()
    header = {
        "generated_utc": utc_stamp(),
        "role": view.role or "monolith",
        "node_id": view.node_id or f"pid-{_pid()}",
    }
    for key, value in header.items():
        payload.setdefault(key, value)
    return payload


def _pid() -> int:
    import os

    return os.getpid()


# -- digest assembly ----------------------------------------------------------


# digest publication identity: a per-process boot token + monotonic
# sequence lets FleetView.ingest drop the same published digest fanning
# back in through co-resident subscribers WITHOUT keying on the
# publisher's wall clock (an NTP step-back must never silently mute a
# live peer) and without confusing a restarted cell reusing its node id
# (new boot token => always fresh)
_BOOT = uuid.uuid4().hex[:12]
_digest_seq = itertools.count(1)


def build_digest(
    role: str,
    node_id: str,
    instance: Any = None,
    interval_s: Optional[float] = None,
    extra: Optional[dict] = None,
) -> dict:
    """One compact telemetry digest for `node_id`, pulled from the
    process-global collectors plus the publishing role's `extra` fields
    (which win on key collisions — an edge knows its own session count
    better than the instance walk does)."""
    digest: dict = {
        "v": DIGEST_VERSION,
        "role": role,
        "node_id": node_id,
        "ts_utc": time.time(),
        "boot": _BOOT,
        "seq": next(_digest_seq),
    }
    if interval_s is not None:
        digest["interval_s"] = interval_s
    try:
        from ..server.overload import RUNG_NAMES, get_overload_controller

        controller = get_overload_controller()
        digest["rung"] = (
            RUNG_NAMES[controller.rung] if controller.enabled else "green"
        )
    except Exception:
        digest["rung"] = "green"
    try:
        from .wire import get_wire_telemetry

        wire = get_wire_telemetry()
        if wire.enabled:
            digest["queues"] = {
                "send_queue_depth": wire.queue_depth_total(),
                "inbox_depth": wire.inbox_depth_total(),
            }
    except Exception:
        pass
    try:
        # per-node wire-saturation headroom (observability/costs.py):
        # every role publishes through build_digest, so /debug/fleet
        # shows the whole fleet's modeled frames/s budget in one table
        from .costs import get_cost_ledger

        ledger = get_cost_ledger()
        if ledger.enabled:
            headroom = ledger.headroom_frames_per_s()
            if headroom > 0:
                digest["headroom_frames_per_s"] = round(headroom, 1)
    except Exception:
        pass
    if instance is not None:
        _fold_instance(digest, instance)
    if extra:
        digest.update(extra)
    return digest


def _fold_instance(digest: dict, instance: Any) -> None:
    """Session/doc counts, SLO burn rates and per-device cell stats
    read off the instance's extension set (best-effort: a digest must
    never fail its publisher)."""
    try:
        digest["sessions"] = int(instance.get_connections_count())
        digest["docs"] = int(instance.get_documents_count())
    except Exception:
        pass
    extensions = getattr(instance, "_extensions", None)
    if extensions is None:
        extensions = getattr(
            getattr(instance, "configuration", None), "extensions", []
        )
    for ext in extensions or []:
        slo = getattr(ext, "slo", None)
        if slo is not None and hasattr(slo, "targets"):
            burns: dict = {}
            breaching: list = []
            try:
                # keep the windows warm: a digest built before the first
                # sampler tick must still carry burn rates — read the
                # engine's exported gauges (last computed values, 0.0
                # when a window has no traffic yet)
                slo.maybe_sample()
                for key, value in slo.burn_gauge._series.items():
                    labels = dict(key)
                    name = labels.get("slo")
                    window = labels.get("window")
                    if name and window:
                        burns.setdefault(name, {})[window] = round(value, 4)
                for target in slo.targets:
                    if slo.breaching(target):
                        breaching.append(target.name)
            except Exception:
                pass
            if burns:
                digest["slo_burn"] = burns
            if breaching:
                digest["slo_breaching"] = breaching
        cell_stats = getattr(ext, "cell_stats", None)
        if callable(cell_stats):
            try:
                digest["cells"] = [
                    {
                        key: stat.get(key)
                        for key in (
                            "cell",
                            "device",
                            "healthy",
                            "docs",
                            "rows_in_use",
                            "pending_ops",
                            "lane_queue_depth",
                            "work_units",
                        )
                    }
                    for stat in cell_stats()
                ]
                placement = getattr(ext, "placement", None)
                if placement is not None:
                    digest["placement_epoch"] = int(placement.epoch)
            except Exception:
                pass
        lane = getattr(ext, "lane", None)
        if lane is not None and callable(getattr(lane, "queue_depths", None)):
            try:
                digest.setdefault("queues", {})["lane_depth"] = int(
                    sum(lane.queue_depths())
                )
            except Exception:
                pass


# -- clock-offset estimation --------------------------------------------------


class ClockOffsetEstimator:
    """Peer-clock offset from PING/PONG round trips: the classic NTP
    midpoint — ``offset = t_peer - (t_sent + rtt/2)`` — smoothed with an
    EWMA, preferring low-RTT samples (a congested round trip bounds the
    one-way skew poorly, so it moves the estimate less)."""

    __slots__ = ("offset_s", "rtt_s", "samples", "_alpha")

    def __init__(self, alpha: float = 0.3) -> None:
        self.offset_s = 0.0
        self.rtt_s: Optional[float] = None
        self.samples = 0
        self._alpha = alpha

    def observe(self, t_sent: float, t_peer: float, t_recv: float) -> float:
        """Fold one round trip (all perf_counter seconds: `t_sent` and
        `t_recv` on OUR clock, `t_peer` on the peer's) into the
        estimate; returns the new smoothed offset (peer - local)."""
        rtt = max(t_recv - t_sent, 0.0)
        sample = t_peer - (t_sent + rtt / 2.0)
        if self.samples == 0:
            self.offset_s = sample
            self.rtt_s = rtt
        else:
            # a high-RTT sample carries more midpoint uncertainty:
            # shrink its weight by how much worse it is than the best
            weight = self._alpha
            if self.rtt_s is not None and rtt > 0 and self.rtt_s > 0:
                weight *= min(self.rtt_s / rtt, 1.0)
            self.offset_s += weight * (sample - self.offset_s)
            self.rtt_s = min(self.rtt_s, rtt) if self.rtt_s is not None else rtt
        self.samples += 1
        return self.offset_s


# -- cross-tier trace return path ---------------------------------------------


class TraceReturnOutbox:
    """Holds finished cross-tier trace contexts between the cell's trace
    book closing a trace (the flush cycle's readback barrier — which
    lands AFTER the encode-once broadcast frame already left, fan-out
    being host-decoupled) and the cell's relay machinery shipping them
    back to the stamping edge as TRACE_RET envelopes. `add_waker` is
    the cell's wake-up seam: deposits can come from the flush executor
    thread, so callbacks must be thread-safe (the cell ingress uses
    `call_soon_threadsafe`). Bounded: returns nobody drains (no cell
    role bound) are shed oldest-first with accounting, never leaked."""

    MAX_PENDING = 1024

    def __init__(self) -> None:
        # doc -> list of return contexts, insertion-ordered. Deposits
        # arrive from the flush executor thread while the cell drains on
        # the event loop: the compound dict+counter updates take a real
        # lock (same discipline as UpdateTraceBook's RLock — GIL
        # atomicity does not cover a setdefault racing a drain swap).
        self._lock = threading.Lock()
        self._pending: "dict[str, list[dict]]" = {}
        self.pending = 0
        self.dropped = 0
        # wake-up subscribers (one per serving cell in this process):
        # a SET, not a slot — one cell's teardown must not unhook its
        # in-process siblings
        self._wakers: "set[Any]" = set()

    def add_waker(self, callback: Any) -> None:
        self._wakers.add(callback)

    def remove_waker(self, callback: Any) -> None:
        self._wakers.discard(callback)

    def deposit(self, doc: str, context: dict) -> None:
        with self._lock:
            while self.pending >= self.MAX_PENDING and self._pending:
                key = next(iter(self._pending))
                shed = self._pending.pop(key)
                self.pending -= len(shed)
                self.dropped += len(shed)
            self._pending.setdefault(doc, []).append(context)
            self.pending += 1
        for callback in list(self._wakers):
            try:
                callback()
            except Exception:
                pass  # a broken drain seam must not fail the trace close

    def take(self, doc: str) -> "Optional[list[dict]]":
        with self._lock:
            contexts = self._pending.pop(doc, None)
            if contexts:
                self.pending -= len(contexts)
            return contexts

    def take_all(self) -> "dict[str, list[dict]]":
        with self._lock:
            drained, self._pending = self._pending, {}
            self.pending = 0
            return drained

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self.pending = 0


# -- the aggregator -----------------------------------------------------------


class FleetView:
    """Bounded per-peer digest rings + the /debug/fleet rollup."""

    def __init__(self, max_digests_per_peer: int = 32) -> None:
        self.enabled = False
        self.role: Optional[str] = None
        self.node_id: Optional[str] = None
        self.max_digests_per_peer = max_digests_per_peer
        # peer node_id -> deque of digests (newest last)
        self.peers: "dict[str, deque]" = {}
        # peer node_id -> {"last_seen": monotonic, "state": up|stale|down}
        self._peer_state: "dict[str, dict]" = {}
        self._skew_roles: "set[str]" = set()  # roles currently flagged
        self.counters = {
            "digests_ingested": 0,
            "digests_invalid": 0,
            "peers_marked_down": 0,
        }
        self.trace_returns = TraceReturnOutbox()
        self.offsets: "dict[str, ClockOffsetEstimator]" = {}
        # set via attach_autoscale by the elastic-fleet controller
        self.autoscale_status = None
        # cross-tier e2e: the edge-to-edge latency series the fleet SLO
        # targets (stage="total"), plus the four edge-side stages
        self.e2e_histogram = Histogram(
            "hocuspocus_fleet_e2e_seconds",
            "Cross-tier (edge→cell→edge) update latency by stage "
            "(docs/guides/observability.md fleet view)",
        )
        self.digests_total = Counter(
            "hocuspocus_fleet_digests_ingested_total",
            "Telemetry digests ingested into the fleet view, by role",
        )
        self.peers_gauge = Gauge(
            "hocuspocus_fleet_peers",
            "Live (non-stale) fleet peers by role",
        )
        # fn gauges read the LAST-swept peer states: the scrape handler
        # calls refresh_gauges() (one sweep) right before exposition, so
        # per-gauge re-sweeps would just repeat the same table walk
        self.stale_gauge = Gauge(
            "hocuspocus_fleet_stale_peers",
            "Fleet peers whose digests went quiet past their threshold",
            fn=lambda: len(self._stale_ids()),
        )
        self.sessions_gauge = Gauge(
            "hocuspocus_fleet_sessions",
            "Client sessions summed over fresh fleet peers",
            fn=lambda: self._sum_field("sessions"),
        )
        self.docs_gauge = Gauge(
            "hocuspocus_fleet_docs",
            "Documents summed over fresh fleet peers",
            fn=lambda: self._sum_field("docs"),
        )
        self.epoch_skew_gauge = Gauge(
            "hocuspocus_fleet_epoch_skew",
            "1 when fresh peers of a role disagree on placement epoch",
        )

    # -- identity / lifecycle ----------------------------------------------

    def enable(self) -> "FleetView":
        self.enabled = True
        return self

    def set_identity(
        self, role: str, node_id: str, force: bool = True
    ) -> None:
        if force or self.role is None:
            self.role = role
            self.node_id = node_id

    def offset_for(self, peer_id: str) -> ClockOffsetEstimator:
        estimator = self.offsets.get(peer_id)
        if estimator is None:
            estimator = self.offsets[peer_id] = ClockOffsetEstimator()
        return estimator

    def attach_autoscale(self, status_fn) -> None:
        """The elastic-fleet controller (fleet/controller.py) hangs its
        live status here; `/debug/fleet` renders it as the `autoscale`
        section. Pass None to detach (controller teardown)."""
        self.autoscale_status = status_fn

    def reset(self) -> None:
        """Back to a cold state (tests / scenario-runner isolation):
        peers, counters, offsets, identity, the e2e histogram and the
        trace outbox all clear; enablement persists. The next role to
        configure claims the identity again."""
        self.role = None
        self.node_id = None
        self.autoscale_status = None
        self.peers.clear()
        self._peer_state.clear()
        self._skew_roles.clear()
        self.offsets.clear()
        self.trace_returns.clear()
        for key in self.counters:
            self.counters[key] = 0
        self.e2e_histogram._series.clear()
        self.digests_total._values.clear()
        self.peers_gauge._series.clear()
        self.epoch_skew_gauge._series.clear()

    # -- ingest -------------------------------------------------------------

    def ingest(self, digest: Any) -> bool:
        """Fold one digest (local or off the control channel) into the
        per-peer ring. Returns False (counted) for malformed digests."""
        if (
            not isinstance(digest, dict)
            or digest.get("v") != DIGEST_VERSION
            or not digest.get("node_id")
            or not digest.get("role")
        ):
            self.counters["digests_invalid"] += 1
            return False
        node_id = str(digest["node_id"])
        state = self._peer_state.get(node_id)
        boot = digest.get("boot")
        seq = digest.get("seq")
        if (
            state is not None
            and boot is not None
            and isinstance(seq, int)
            and state.get("boot") == boot
            and state.get("last_seq") is not None
            and seq <= state["last_seq"]
        ):
            # one published digest fans back in once per co-resident
            # subscriber (the publisher ingests locally AND every role
            # in this process watches the control channel): the echoes
            # would inflate the ingest counters and burn the bounded
            # ring N-fold, so a digest not newer (same boot, seq not
            # above the high-water mark) than the peer's latest is
            # acknowledged without re-ingesting. Keyed on boot+seq, not
            # the publisher's wall clock: a clock step-back must never
            # mute a live peer, and a restarted cell reusing its node id
            # carries a fresh boot token.
            return True
        ring = self.peers.get(node_id)
        if ring is None:
            ring = self.peers[node_id] = deque(maxlen=self.max_digests_per_peer)
        ring.append(digest)
        now = time.monotonic()
        if state is None:
            state = self._peer_state[node_id] = {"last_seen": now, "state": "up"}
            get_flight_recorder().record(
                "__fleet__", "peer_up", peer=node_id, role=digest["role"]
            )
        else:
            if state["state"] != "up":
                get_flight_recorder().record(
                    "__fleet__", "peer_up", peer=node_id, role=digest["role"]
                )
            state["last_seen"] = now
            state["state"] = "up"
        if boot is not None and isinstance(seq, int):
            state["boot"] = boot
            state["last_seq"] = seq
        self.counters["digests_ingested"] += 1
        self.digests_total.inc(role=str(digest["role"]))
        self._sweep(now)
        return True

    def mark_down(self, node_id: str) -> None:
        """An explicit departure (CELL_DOWN on the control channel):
        flip the peer to down without waiting out the stale window."""
        state = self._peer_state.get(node_id)
        if state is None or state["state"] == "down":
            return
        state["state"] = "down"
        self.counters["peers_marked_down"] += 1
        get_flight_recorder().record("__fleet__", "peer_down", peer=node_id)

    # -- freshness ----------------------------------------------------------

    def _stale_after(self, node_id: str) -> float:
        ring = self.peers.get(node_id)
        interval = None
        if ring:
            interval = ring[-1].get("interval_s")
        if not interval:
            return STALE_FLOOR_S
        return max(STALE_FLOOR_S, STALE_INTERVALS * float(interval))

    def _sweep(self, now: Optional[float] = None) -> None:
        """Re-evaluate peer freshness + epoch skew, recording each
        transition once in the __fleet__ ring (called on ingest and on
        every status/metrics read — no timer needed)."""
        if now is None:
            now = time.monotonic()
        forgotten = []
        for node_id, state in self._peer_state.items():
            age = now - state["last_seen"]
            threshold = self._stale_after(node_id)
            if state["state"] == "down":
                if age > FORGET_FACTOR * threshold:
                    forgotten.append(node_id)
                continue
            if state["state"] == "up" and age > threshold:
                state["state"] = "stale"
                get_flight_recorder().record(
                    "__fleet__",
                    "peer_stale",
                    peer=node_id,
                    age_s=round(age, 1),
                    threshold_s=round(threshold, 1),
                )
            elif state["state"] == "stale" and age > DOWN_FACTOR * threshold:
                state["state"] = "down"
                get_flight_recorder().record(
                    "__fleet__", "peer_down", peer=node_id, age_s=round(age, 1)
                )
        for node_id in forgotten:
            self._forget_peer(node_id)
        if len(self._peer_state) > MAX_PEERS:
            # hard backstop: shed non-up peers first (the __fleet__ ring
            # keeps their down transition for forensics), then — when a
            # fleet genuinely outgrows the cap and every peer is fresh —
            # the quietest up peers too, so the cap really caps
            evictable = sorted(
                (state["state"] == "up", state["last_seen"], node_id)
                for node_id, state in self._peer_state.items()
            )
            for _up, _seen, node_id in evictable[
                : len(self._peer_state) - MAX_PEERS
            ]:
                self._forget_peer(node_id)
        skew = self._epoch_skew()
        for role, info in skew.items():
            if info["skew"] and role not in self._skew_roles:
                self._skew_roles.add(role)
                get_flight_recorder().record(
                    "__fleet__",
                    "epoch_skew_detected",
                    role=role,
                    epochs=",".join(
                        f"{peer}={epoch}" for peer, epoch in info["epochs"].items()
                    ),
                )
            elif not info["skew"]:
                self._skew_roles.discard(role)

    def _forget_peer(self, node_id: str) -> None:
        self.peers.pop(node_id, None)
        self._peer_state.pop(node_id, None)
        self.offsets.pop(node_id, None)

    def peer_state(self, node_id: str) -> Optional[str]:
        state = self._peer_state.get(node_id)
        return None if state is None else state["state"]

    def _fresh_ids(self) -> "list[str]":
        """Up peers per the LAST sweep (no re-evaluation — callers that
        are entry points sweep once and pass results down rather than
        re-walking the table per read)."""
        return [
            node_id
            for node_id, state in self._peer_state.items()
            if state["state"] == "up"
        ]

    def _stale_ids(self) -> "list[str]":
        return sorted(
            node_id
            for node_id, state in self._peer_state.items()
            if state["state"] != "up"
        )

    def fresh_peers(self) -> "list[str]":
        self._sweep()
        return self._fresh_ids()

    def stale_peers(self) -> "list[str]":
        self._sweep()
        return self._stale_ids()

    def _latest(self, node_id: str) -> Optional[dict]:
        ring = self.peers.get(node_id)
        return ring[-1] if ring else None

    def _sum_field(self, field: str, fresh: "Optional[list[str]]" = None) -> int:
        """Sum a digest field over FRESH peers, skipping peers that do
        not report it — an edge has no docs and a booting cell has no
        sessions yet; averaging zeros in would understate the fleet.
        `fresh=None` reads the last-swept states (the scrape path and
        status() both sweep once up front)."""
        total = 0
        for node_id in self._fresh_ids() if fresh is None else fresh:
            digest = self._latest(node_id)
            value = None if digest is None else digest.get(field)
            if value is not None:
                total += int(value)
        return total

    def _epoch_skew(self) -> "dict[str, dict]":
        """Per-role epoch agreement over fresh (up) peers. Skew is only
        meaningful where peers derive an epoch from a SHARED event
        stream, and each role now has one: edge router epochs ride the
        control channel (as before), and — since the roster went
        dynamic (fleet/roster.py) — cells fold the same control-channel
        membership transitions into a `roster_epoch` published in their
        digests. Cell *placement* epochs remain local per-instance
        bookkeeping: reported, never flagged."""
        placement_by_role: "dict[str, dict[str, int]]" = {}
        roster_by_role: "dict[str, dict[str, int]]" = {}
        for node_id, state in self._peer_state.items():
            if state["state"] != "up":
                continue
            digest = self._latest(node_id)
            if digest is None:
                continue
            role = str(digest["role"])
            if digest.get("placement_epoch") is not None:
                placement_by_role.setdefault(role, {})[node_id] = int(
                    digest["placement_epoch"]
                )
            if digest.get("roster_epoch") is not None:
                roster_by_role.setdefault(role, {})[node_id] = int(
                    digest["roster_epoch"]
                )
        result: "dict[str, dict]" = {}
        for role in set(placement_by_role) | set(roster_by_role):
            epochs = placement_by_role.get(role, {})
            rosters = roster_by_role.get(role, {})
            skew = (
                role == "edge" and len(set(epochs.values())) > 1
            ) or len(set(rosters.values())) > 1
            result[role] = {
                "epochs": epochs,
                "roster_epochs": rosters,
                "skew": skew,
            }
        return result

    # -- cross-tier latency --------------------------------------------------

    def record_cross_tier(self, stage: str, seconds: float) -> None:
        self.e2e_histogram.observe(max(seconds, 0.0), stage=stage)

    def cross_tier_quantiles(self) -> Optional[dict]:
        """p50/p99 of the edge-to-edge e2e series, or None when no
        cross-tier trace has completed (never a fabricated zero)."""
        count = self.e2e_histogram.series_count(stage="total")
        if count == 0:
            return None
        return {
            "p50_ms": round(
                self.e2e_histogram.quantile(0.5, stage="total") * 1000.0, 3
            ),
            "p99_ms": round(
                self.e2e_histogram.quantile(0.99, stage="total") * 1000.0, 3
            ),
            "count": count,
        }

    # -- exposition ----------------------------------------------------------

    def metrics(self) -> tuple:
        """Metric objects for MetricsRegistry.register adoption."""
        return (
            self.e2e_histogram,
            self.digests_total,
            self.peers_gauge,
            self.stale_gauge,
            self.sessions_gauge,
            self.docs_gauge,
            self.epoch_skew_gauge,
        )

    def refresh_gauges(self) -> None:
        """Re-label the rollup gauges from the current peer table
        (called at scrape time by the Metrics extension)."""
        self._sweep()
        by_role: "dict[str, int]" = {}
        for node_id, state in self._peer_state.items():
            if state["state"] != "up":
                continue
            digest = self._latest(node_id)
            if digest is not None:
                role = str(digest["role"])
                by_role[role] = by_role.get(role, 0) + 1
        self.peers_gauge._series.clear()
        for role, count in by_role.items():
            self.peers_gauge.set(count, role=role)
        self.epoch_skew_gauge._series.clear()
        for role, info in self._epoch_skew().items():
            self.epoch_skew_gauge.set(1.0 if info["skew"] else 0.0, role=role)

    def status(self) -> dict:
        """The `/debug/fleet` payload. One sweep up front; every
        freshness-derived section below reads the swept states instead
        of re-walking the table."""
        self._sweep()
        fresh = self._fresh_ids()
        now = time.monotonic()
        peers: dict = {}
        roles: "dict[str, list]" = {}
        cells: dict = {}
        for node_id in sorted(self._peer_state):
            state = self._peer_state[node_id]
            digest = self._latest(node_id)
            if digest is None:
                continue
            role = str(digest["role"])
            roles.setdefault(role, []).append(node_id)
            entry = {
                "role": role,
                "state": state["state"],
                "age_s": round(now - state["last_seen"], 2),
                "rung": digest.get("rung"),
                "digests": len(self.peers.get(node_id) or ()),
            }
            for key in (
                "sessions",
                "docs",
                "placement_epoch",
                "roster_epoch",
                "slo_burn",
                "slo_breaching",
                "queues",
                "headroom_frames_per_s",
                "edge",
                "cell",
                "replica",
            ):
                value = digest.get(key)
                if value is not None:
                    entry[key] = value
            replica = digest.get("replica")
            if isinstance(replica, dict):
                # the hot-doc followers column: how many follower
                # subscriptions this cell is serving (owner side) and
                # how many docs it follows (replica side)
                entry["followers"] = sum(
                    len(owned.get("followers") or ())
                    for owned in (replica.get("owned") or {}).values()
                )
                entry["following"] = len(replica.get("following") or ())
            peers[node_id] = entry
            if digest.get("cells") is not None:
                cells[node_id] = digest["cells"]
        payload = {
            "peers": peers,
            "roles": {role: sorted(ids) for role, ids in sorted(roles.items())},
            "cells": cells,
            "epoch_skew": self._epoch_skew(),
            "stale_peers": self._stale_ids(),
            "totals": {
                "peers": len(peers),
                "fresh": len(fresh),
                "sessions": self._sum_field("sessions", fresh),
                "docs": self._sum_field("docs", fresh),
                "followers": sum(
                    entry.get("followers", 0)
                    for node_id, entry in peers.items()
                    if self._peer_state[node_id]["state"] == "up"
                ),
            },
            "cross_tier_e2e_ms": self.cross_tier_quantiles(),
            "counters": dict(self.counters),
        }
        if self.autoscale_status is not None:
            # live controller state (roster, last decision, park
            # reason) — attached by FleetControllerExtension, and a
            # status read must never take /debug/fleet down with it
            try:
                payload["autoscale"] = self.autoscale_status()
            except Exception:
                payload["autoscale"] = {"error": "unavailable"}
        return stamp_header(payload)


# The process-default view every role publishes into. Disabled by
# default; the Metrics extension enables it (like the wire collector).
_default = FleetView()


def get_fleet_view() -> FleetView:
    return _default
