"""Wire-path telemetry: the websocket edge of the observation boundary.

PR 4 lit the merge path from the capture seam to broadcast; this module
lights the other half of the request path — the socket edge. One
process-global collector (same singleton pattern as `get_tracer` /
`get_flight_recorder`) that the hot-path seams write into:

- per-`MessageType` ingress/egress message + byte counters and
  handle-latency histograms (`Connection.handle_message` →
  `MessageReceiver`),
- sync-step latency by step (step1/step2/update) and auth
  (Auth-frame → hook chain complete) latency,
- per-connection send-queue depth (summed live gauge), the high-water
  mark, and backpressure-watermark crossings
  (`CallbackWebSocketTransport`),
- socket churn: sockets opened/closed and close-code counters
  (`ClientConnection` / the websocket host),
- mini_redis pub/sub fan-out counters (publishes, deliveries, injected
  drops) so the cross-instance path is countable in tests and dev.

Disabled by default: every instrumentation site costs one attribute
read + truth test until the `Metrics` extension (or a test) calls
`enable()`. The metric objects are the plain primitives from
`metrics.py`; `Metrics` adopts them into its registry via
`MetricsRegistry.register`, so they render on `/metrics` with the rest
of the exposition. Errors feed the SLO engine's error-rate objective
(`observability/slo.py`).
"""

from __future__ import annotations

import weakref
from typing import Iterable, Optional

from ..protocol.message import MessageType
from .metrics import Counter, Gauge, Histogram

# var-uint sync submessage ids (protocol/sync.py) -> label values
_SYNC_STEP_NAMES = {0: "step1", 1: "step2", 2: "update"}

# queue depth at/above which a send() counts as a backpressure event
# (per crossing, not per queued frame: the counter increments when a
# connection's queue climbs past the watermark, and re-arms once it
# drains below)
DEFAULT_BACKPRESSURE_WATERMARK = 64


def message_type_name(message_type: int) -> str:
    try:
        return MessageType(message_type).name
    except ValueError:
        return f"unknown_{int(message_type)}"


class WireTelemetry:
    """Socket-edge counters/gauges/histograms, shared process-wide."""

    def __init__(self, backpressure_watermark: int = DEFAULT_BACKPRESSURE_WATERMARK) -> None:
        self.enabled = False
        self.backpressure_watermark = backpressure_watermark
        self.messages_in = Counter(
            "hocuspocus_wire_messages_in_total",
            "Inbound websocket messages handled, by MessageType",
        )
        self.messages_out = Counter(
            "hocuspocus_wire_messages_out_total",
            "Outbound websocket messages sent, by MessageType",
        )
        self.bytes_in = Counter(
            "hocuspocus_wire_bytes_in_total",
            "Inbound websocket payload bytes, by MessageType",
        )
        self.bytes_out = Counter(
            "hocuspocus_wire_bytes_out_total",
            "Outbound websocket payload bytes, by MessageType",
        )
        self.handle_seconds = Histogram(
            "hocuspocus_wire_handle_seconds",
            "Inbound message handle latency (decode -> dispatch done), by MessageType",
        )
        self.sync_step_seconds = Histogram(
            "hocuspocus_wire_sync_step_seconds",
            "Sync submessage handle latency by step (step1/step2/update)",
        )
        self.auth_seconds = Histogram(
            "hocuspocus_wire_auth_seconds",
            "Auth frame arrival -> onConnect/onAuthenticate hook chain complete",
        )
        self.errors = Counter(
            "hocuspocus_wire_errors_total",
            "Message-handling failures that closed a document channel, by kind",
        )
        self.sockets_opened = Counter(
            "hocuspocus_wire_sockets_opened_total",
            "Client sockets (ClientConnection sessions) opened",
        )
        self.sockets_closed = Counter(
            "hocuspocus_wire_sockets_closed_total",
            "Client sockets closed, by websocket close code",
        )
        self.channel_closes = Counter(
            "hocuspocus_wire_channel_closes_total",
            "Per-document channel closes, by close code",
        )
        self.send_queue_depth = Gauge(
            "hocuspocus_wire_send_queue_depth",
            "Frames queued across live transports (summed)",
            fn=self._total_queue_depth,
        )
        self.send_queue_peak = Gauge(
            "hocuspocus_wire_send_queue_peak",
            "Deepest single-transport send queue observed since start",
        )
        self.backpressure_events = Counter(
            "hocuspocus_wire_backpressure_total",
            "Send-queue watermark crossings (queue climbed past the watermark)",
        )
        self.fanout_coalesced = Histogram(
            "hocuspocus_wire_fanout_coalesced_updates",
            "Updates merged into one broadcast frame per document tick",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),  # counts, not seconds
        )
        self.fanout_sends_elided = Counter(
            "hocuspocus_wire_fanout_sends_elided_total",
            "Per-connection sends avoided by the fan-out engine, by reason "
            "(coalesce: burst merged into one frame; catchup: frame dropped "
            "for a connection in catch-up tier)",
        )
        self.catchup_tier_transitions = Counter(
            "hocuspocus_wire_catchup_tier_total",
            "Slow-consumer catch-up tier transitions (enter/exit)",
        )
        self.sync_cache_events = Counter(
            "hocuspocus_wire_sync_cache_total",
            "Join-storm sync cache lookups by result (hit/miss/eviction)"
            " and encode path (device/host)",
        )
        self.send_queue_overflows = Counter(
            "hocuspocus_wire_send_queue_overflow_total",
            "Transports closed because their send queue hit the bound",
        )
        self.pubsub_publishes = Counter(
            "hocuspocus_wire_pubsub_publishes_total",
            "mini_redis PUBLISH commands handled",
        )
        self.pubsub_deliveries = Counter(
            "hocuspocus_wire_pubsub_deliveries_total",
            "mini_redis messages fanned out to subscribers",
        )
        self.pubsub_dropped = Counter(
            "hocuspocus_wire_pubsub_dropped_total",
            "mini_redis publish deliveries dropped, by reason (injected "
            "fault / slow-subscriber disconnect)",
        )
        # -- cross-instance replication lane (net/resp.py pipelined
        # client + extensions/redis.py publish coalescing / inbound
        # inbox) ------------------------------------------------------
        self.redis_pipeline_depth = Gauge(
            "hocuspocus_redis_pipeline_depth",
            "Commands buffered or awaiting their ack across live "
            "pipelined Redis clients (summed)",
            fn=self._total_pipeline_depth,
        )
        self.redis_flush_batch = Histogram(
            "hocuspocus_redis_flush_batch_commands",
            "Commands shipped per pipelined flush (one write+drain)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),  # counts
        )
        self.redis_publish_flush_seconds = Histogram(
            "hocuspocus_redis_publish_flush_seconds",
            "Oldest-command wait from enqueue to its flush write",
        )
        self.redis_reply_errors = Counter(
            "hocuspocus_redis_reply_errors_total",
            "Error replies consumed by the pipelined reply reader",
        )
        self.redis_inbox_depth = Gauge(
            "hocuspocus_redis_inbox_depth",
            "Inbound replication frames queued across per-doc inboxes "
            "(summed over live Redis extensions)",
            fn=self._total_inbox_depth,
        )
        self.redis_inbox_drained = Histogram(
            "hocuspocus_redis_inbox_drained_frames",
            "Inbound frames consumed per doc per inbox drain",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),  # counts
        )
        self.redis_inbox_overflows = Counter(
            "hocuspocus_redis_inbox_overflow_total",
            "Inbound frames dropped by a full per-doc inbox (each "
            "triggers an anti-entropy SyncStep1 exchange)",
        )
        self.redis_frames_saved = Counter(
            "hocuspocus_redis_frames_saved_total",
            "Cross-instance publishes avoided by per-tick replication "
            "coalescing, by direction (publish/apply)",
        )
        # live transports (weak: an abandoned transport must not leak
        # through the gauge); per-transport watermark armed state rides
        # in the map value
        self._transports: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        # live pipelined redis clients (expose `.pending`) and Redis
        # extensions (expose `.inbox_depth()`), weakly held for the
        # depth gauges — closed/collected instances fall out on their own
        self._redis_pipelines: "weakref.WeakSet" = weakref.WeakSet()
        self._redis_inbox_sources: "weakref.WeakSet" = weakref.WeakSet()
        # egress header-parse cache (see record_egress_frame): identity
        # of the last frame parsed + its type (strong ref on purpose —
        # object identity is only trustworthy while the object lives)
        self._egress_last_frame: Optional[bytes] = None
        self._egress_last_type: int = -1

    def enable(self) -> "WireTelemetry":
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    # -- ingress / egress ----------------------------------------------------

    def record_ingress(self, message_type: int, nbytes: int, seconds: float) -> None:
        name = message_type_name(message_type)
        self.messages_in.inc(type=name)
        self.bytes_in.inc(nbytes, type=name)
        self.handle_seconds.observe(seconds, type=name)

    def record_egress(self, message_type: int, nbytes: int) -> None:
        name = message_type_name(message_type)
        self.messages_out.inc(type=name)
        self.bytes_out.inc(nbytes, type=name)

    def record_egress_frame(self, data: bytes) -> None:
        """Egress accounting from a raw frame. Broadcasts send ONE frame
        object to N connections, so the header parse is cached by
        object identity — a 10k-subscriber fan-out parses once, not
        10k times."""
        if data is self._egress_last_frame:
            message_type = self._egress_last_type
        else:
            try:
                from ..protocol.frames import parse_frame_header

                _name, message_type, _offset = parse_frame_header(data)
            except Exception:
                return
            self._egress_last_frame = data
            self._egress_last_type = message_type
        self.record_egress(message_type, len(data))

    # -- broadcast fan-out engine (server/fanout.py) -------------------------

    def record_fanout_frame(self, coalesced: int, sends_saved: int) -> None:
        """One broadcast tick shipped `coalesced` merged updates as one
        frame, saving `sends_saved` per-connection sends vs per-update
        fan-out."""
        self.fanout_coalesced.observe(float(coalesced))
        if sends_saved > 0:
            self.fanout_sends_elided.inc(sends_saved, reason="coalesce")

    def record_catchup_elided(self, count: int = 1) -> None:
        self.fanout_sends_elided.inc(count, reason="catchup")

    def record_tier(self, transition: str) -> None:
        self.catchup_tier_transitions.inc(transition=transition)

    def record_sync_cache(
        self, result: str, count: int = 1, path: str = "host"
    ) -> None:
        """path labels the serve's delete-set read route: "device" when
        the packed on-device catch-up encode is active for the doc,
        "host" for the full-row gather (pack disabled or degraded)."""
        self.sync_cache_events.inc(count, result=result, path=path)

    def _sync_cache_total(self, result: str) -> float:
        """Sum one result across path labels (device/host)."""
        return sum(
            value
            for key, value in self.sync_cache_events._values.items()
            if dict(key).get("result") == result
        )

    def record_queue_overflow(self) -> None:
        self.send_queue_overflows.inc()

    def record_sync_step(self, sync_type: int, seconds: float) -> None:
        step = _SYNC_STEP_NAMES.get(int(sync_type), f"unknown_{int(sync_type)}")
        self.sync_step_seconds.observe(seconds, step=step)

    def record_auth(self, seconds: float, ok: bool) -> None:
        self.auth_seconds.observe(seconds, outcome="ok" if ok else "denied")

    def record_error(self, kind: str) -> None:
        self.errors.inc(kind=kind)

    # -- connection churn ----------------------------------------------------

    def record_socket_opened(self) -> None:
        self.sockets_opened.inc()

    def record_socket_closed(self, code: int) -> None:
        self.sockets_closed.inc(code=str(int(code)))

    def record_channel_close(self, code: Optional[int]) -> None:
        self.channel_closes.inc(code=str(int(code)) if code is not None else "none")

    # -- send queues ---------------------------------------------------------

    def track_transport(self, transport) -> None:
        """Register a live transport whose `queue.qsize()` feeds the
        depth gauge. Weakly held — GC'd transports fall out on their
        own; `untrack_transport` drops them eagerly at close."""
        self._transports[transport] = {"armed": True}

    def untrack_transport(self, transport) -> None:
        self._transports.pop(transport, None)

    def note_send_queued(self, transport) -> None:
        """Called after a frame is queued: updates the peak gauge and
        counts watermark crossings (once per excursion)."""
        try:
            depth = transport.queue.qsize()
        except Exception:
            return
        if depth > self.send_queue_peak.value():
            self.send_queue_peak.set(depth)
        entry = self._transports.get(transport)
        if entry is None:
            return
        if depth >= self.backpressure_watermark:
            if entry["armed"]:
                entry["armed"] = False
                self.backpressure_events.inc()
        elif depth <= self.backpressure_watermark // 2:
            entry["armed"] = True

    def _total_queue_depth(self) -> int:
        total = 0
        for transport in list(self._transports):
            try:
                total += transport.queue.qsize()
            except Exception:
                continue
        return total

    # -- overload-controller signal reads (server/overload.py) ---------------

    def queue_depth_total(self) -> int:
        """Summed live send-queue depth (the overload ladder's
        send_queue_depth signal; same read as the gauge)."""
        return self._total_queue_depth()

    def inbox_depth_total(self) -> int:
        """Summed inbound replication inbox depth."""
        return self._total_inbox_depth()

    def backpressure_total(self) -> float:
        """Cumulative watermark crossings (the ladder differentiates
        this into a rate)."""
        return float(sum(self.backpressure_events._values.values()))

    # -- pub/sub -------------------------------------------------------------

    def record_publish(self, delivered: int, dropped: bool = False) -> None:
        if dropped:
            self.pubsub_dropped.inc()
            return
        self.pubsub_publishes.inc()
        if delivered:
            self.pubsub_deliveries.inc(delivered)

    # -- cross-instance replication lane -------------------------------------

    def track_redis_pipeline(self, client) -> None:
        """Register a pipelined client whose `.pending` feeds the depth
        gauge (weakly held)."""
        self._redis_pipelines.add(client)

    def track_redis_inbox(self, source) -> None:
        """Register an inbox owner whose `.inbox_depth()` feeds the
        inbound depth gauge (weakly held)."""
        self._redis_inbox_sources.add(source)

    def record_redis_flush(self, batch_size: int, oldest_wait_seconds: float) -> None:
        self.redis_flush_batch.observe(float(batch_size))
        self.redis_publish_flush_seconds.observe(oldest_wait_seconds)

    def record_redis_reply_error(self) -> None:
        self.redis_reply_errors.inc()

    def record_redis_inbox_drain(self, frames: int) -> None:
        self.redis_inbox_drained.observe(float(frames))

    def record_redis_inbox_overflow(self, count: int = 1) -> None:
        self.redis_inbox_overflows.inc(count)

    def record_redis_frames_saved(self, count: int, direction: str = "publish") -> None:
        if count > 0:
            self.redis_frames_saved.inc(count, direction=direction)

    def _total_pipeline_depth(self) -> int:
        total = 0
        for client in list(self._redis_pipelines):
            try:
                total += client.pending
            except Exception:
                continue
        return total

    def _total_inbox_depth(self) -> int:
        total = 0
        for source in list(self._redis_inbox_sources):
            try:
                total += source.inbox_depth()
            except Exception:
                continue
        return total

    # -- registry binding ----------------------------------------------------

    def metrics(self) -> Iterable:
        """Every metric object, for MetricsRegistry.register adoption."""
        return (
            self.messages_in,
            self.messages_out,
            self.bytes_in,
            self.bytes_out,
            self.handle_seconds,
            self.sync_step_seconds,
            self.auth_seconds,
            self.errors,
            self.sockets_opened,
            self.sockets_closed,
            self.channel_closes,
            self.send_queue_depth,
            self.send_queue_peak,
            self.backpressure_events,
            self.fanout_coalesced,
            self.fanout_sends_elided,
            self.catchup_tier_transitions,
            self.sync_cache_events,
            self.send_queue_overflows,
            self.pubsub_publishes,
            self.pubsub_deliveries,
            self.pubsub_dropped,
            self.redis_pipeline_depth,
            self.redis_flush_batch,
            self.redis_publish_flush_seconds,
            self.redis_reply_errors,
            self.redis_inbox_depth,
            self.redis_inbox_drained,
            self.redis_inbox_overflows,
            self.redis_frames_saved,
        )

    # -- reading (bench / tests) ---------------------------------------------

    def totals(self) -> dict:
        """Aggregate snapshot for the bench's wire_load pass."""
        return {
            "messages_in": sum(self.messages_in._values.values()),
            "messages_out": sum(self.messages_out._values.values()),
            "bytes_in": sum(self.bytes_in._values.values()),
            "bytes_out": sum(self.bytes_out._values.values()),
            "send_queue_peak": self.send_queue_peak.value(),
            "backpressure_events": sum(self.backpressure_events._values.values()),
            "errors": sum(self.errors._values.values()),
            "sends_elided_coalesce": self.fanout_sends_elided.value(reason="coalesce"),
            "sends_elided_catchup": self.fanout_sends_elided.value(reason="catchup"),
            "tier_entries": self.catchup_tier_transitions.value(transition="enter"),
            "tier_exits": self.catchup_tier_transitions.value(transition="exit"),
            "sync_cache_hits": self._sync_cache_total("hit"),
            "sync_cache_misses": self._sync_cache_total("miss"),
            "queue_overflows": sum(self.send_queue_overflows._values.values()),
            "pubsub_publishes": sum(self.pubsub_publishes._values.values()),
            "pubsub_deliveries": sum(self.pubsub_deliveries._values.values()),
            "pubsub_dropped": sum(self.pubsub_dropped._values.values()),
            "redis_reply_errors": sum(self.redis_reply_errors._values.values()),
            "redis_inbox_overflows": sum(self.redis_inbox_overflows._values.values()),
            "redis_frames_saved": sum(self.redis_frames_saved._values.values()),
        }


_default = WireTelemetry()


def get_wire_telemetry() -> WireTelemetry:
    return _default
