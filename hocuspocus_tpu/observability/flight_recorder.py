"""Per-document flight recorder: a bounded ring of lifecycle events.

At 100k docs, aggregate gauges say *that* docs are degrading, never
*which* doc did what when. This recorder keeps the last N lifecycle
events per document — load, unload, evict, hydrate, compact, retire,
recycle, degrade, breaker-degrade, slow flush — so an operator can ask
"what happened to `reports/q3`?" and get a timeline, queryable at
`GET /debug/docs/<name>` (and a busiest-docs table at `/debug/docs`),
both served by the `Metrics` extension.

Always on and deliberately tiny: one OrderedDict move-to-end plus a
deque append per event, recorded only at lifecycle edges (never per
update), with both the per-doc ring and the doc population bounded
(LRU eviction of the least-recently-eventful doc).
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Any, Optional


class FlightRecorder:
    """Bounded per-doc event rings with an LRU-bounded doc population."""

    def __init__(self, max_docs: int = 1024, max_events: int = 64) -> None:
        self.max_docs = max_docs
        self.max_events = max_events
        self._docs: "OrderedDict[str, deque]" = OrderedDict()
        self.total_events = 0
        self.evicted_docs = 0

    def record(self, name: str, event: str, **attrs: Any) -> None:
        ring = self._docs.get(name)
        if ring is None:
            while len(self._docs) >= self.max_docs:
                self._docs.popitem(last=False)
                self.evicted_docs += 1
            ring = deque(maxlen=self.max_events)
            self._docs[name] = ring
        else:
            self._docs.move_to_end(name)
        entry = {"ts": time.time(), "event": event}
        if attrs:
            entry.update(attrs)
        ring.append(entry)
        self.total_events += 1

    def events(self, name: str) -> list[dict]:
        ring = self._docs.get(name)
        return [] if ring is None else list(ring)

    def docs(self) -> list[dict]:
        """Per-doc summaries, most-recently-eventful first."""
        out = []
        for name in reversed(self._docs):
            ring = self._docs[name]
            last = ring[-1] if ring else None
            out.append(
                {
                    "doc": name,
                    "events": len(ring),
                    "last_event": None if last is None else last["event"],
                    "last_ts": None if last is None else last["ts"],
                }
            )
        return out

    def forget(self, name: str) -> None:
        self._docs.pop(name, None)

    def clear(self) -> None:
        self._docs.clear()
        self.total_events = 0
        self.evicted_docs = 0

    def __len__(self) -> int:
        return len(self._docs)


_default = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _default
