"""Observability: span tracing, metrics registry, Prometheus endpoint.

New capability beyond the reference (SURVEY.md §5.1/§5.5 record that the
reference ships no tracing and no metrics exporter).
"""

from .extension import Metrics
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .tracing import Tracer, disable_tracing, enable_tracing, get_tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Metrics",
    "MetricsRegistry",
    "Tracer",
    "disable_tracing",
    "enable_tracing",
    "get_tracer",
]
