"""Observability: span tracing, metrics registry, Prometheus endpoint,
per-doc flight recorder and the update-lifecycle trace pipeline.

New capability beyond the reference (SURVEY.md §5.1/§5.5 record that the
reference ships no tracing and no metrics exporter).
"""

from .costs import CostLedger, get_cost_ledger
from .device_watch import CompileTracker
from .extension import Metrics
from .profiler import SamplingProfiler, get_profiler
from .fleet import (
    ClockOffsetEstimator,
    FleetView,
    build_digest,
    get_fleet_view,
    stamp_header,
)
from .flight_recorder import FlightRecorder, get_flight_recorder
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .slo import SloEngine, SloTarget, counter_ratio_slo, fraction_slo, latency_slo
from .tracing import (
    Tracer,
    UpdateTraceBook,
    disable_tracing,
    enable_tracing,
    get_tracer,
)
from .wire import WireTelemetry, get_wire_telemetry

__all__ = [
    "ClockOffsetEstimator",
    "CompileTracker",
    "CostLedger",
    "Counter",
    "FleetView",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Metrics",
    "MetricsRegistry",
    "SamplingProfiler",
    "SloEngine",
    "SloTarget",
    "Tracer",
    "UpdateTraceBook",
    "WireTelemetry",
    "build_digest",
    "counter_ratio_slo",
    "disable_tracing",
    "enable_tracing",
    "fraction_slo",
    "get_cost_ledger",
    "get_fleet_view",
    "get_flight_recorder",
    "get_profiler",
    "get_tracer",
    "get_wire_telemetry",
    "latency_slo",
    "stamp_header",
]
