"""Observability: span tracing, metrics registry, Prometheus endpoint,
per-doc flight recorder and the update-lifecycle trace pipeline.

New capability beyond the reference (SURVEY.md §5.1/§5.5 record that the
reference ships no tracing and no metrics exporter).
"""

from .extension import Metrics
from .flight_recorder import FlightRecorder, get_flight_recorder
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .tracing import (
    Tracer,
    UpdateTraceBook,
    disable_tracing,
    enable_tracing,
    get_tracer,
)

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Metrics",
    "MetricsRegistry",
    "Tracer",
    "UpdateTraceBook",
    "disable_tracing",
    "enable_tracing",
    "get_flight_recorder",
    "get_tracer",
]
