"""Elastic fleet: the control-plane layer that closes the scaling loop.

Every mechanism this package drives already exists lower in the stack —
PR-15 fleet digests measure per-cell load, the PR-14 evict-snapshot →
hydrate rail migrates docs under live edits, PR-13 drain handoff retires
cells with zero acked loss, and the PR-12 brownout ladder says when the
plane is too stressed to churn topology. `fleet/` is the part that was
missing: a controller that *decides* (controller.py) and a roster that
lets cells on OTHER hosts join the decision space (roster.py).

CRDT convergence is placement-independent, so cells can be added,
drained, and rehomed under live edits without coordinating on the data
itself — the controller only ever moves *where* merges happen, never
*what* they produce.
"""

from .controller import FleetController, FleetControllerExtension
from .roster import AdmissionGate, PeerRoster, cell_host, qualify_cell_id

__all__ = [
    "AdmissionGate",
    "FleetController",
    "FleetControllerExtension",
    "PeerRoster",
    "cell_host",
    "qualify_cell_id",
]
