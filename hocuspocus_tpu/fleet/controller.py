"""Fleet autoscaling: digest-driven decisions, warm-spare actuation.

Two layers, deliberately separated:

`FleetController` is the pure decision core. Per tick it consumes
digest-shaped per-cell stats (the same fields PR-15 fleet digests
carry: work-unit *rates*, lane queue depth, arena occupancy), folds
them into one normalized fleet-load signal, and answers with a single
decision — ``hold``, ``scale_up``, ``scale_down``, or ``park``. All
state that makes it flap-proof lives here and nowhere else, mirroring
the PR-12 brownout ladder's discipline:

* **streaks** — a threshold crossing must persist for ``hold_ticks``
  consecutive ticks before it acts; an oscillating signal resets the
  streak every flip and never scales anything;
* **cooldown** — every action buys ``cooldown_ticks`` of mandatory
  holds, so the fleet settles (migrations complete, rates stop lying)
  before the next decision;
* **projection** — scale-down additionally requires that the survivors
  could absorb the load below ``projected_max``, so the controller
  never removes a cell it would have to re-add next tick;
* **park** — while the OverloadController sits at BROWNOUT-1 or above,
  every decision is ``park``: load signals under brownout are shaped
  by shedding, and topology churn is exactly the deferrable work the
  ladder exists to stop. Unparking re-arms a full cooldown before the
  first post-brownout action.

`FleetControllerExtension` is the driver: an asyncio tick loop that
samples the co-installed multi-device plane (`tpu/cells.py`), converts
its cumulative dispatch counters into rates, feeds the core, and
actuates — scale-up activates a warm-spare cell (arena and registry
were never torn down, so rejoining is one placement-epoch bump),
scale-down migrates every doc off the coldest cell over the
evict-snapshot→hydrate rail and *then* parks it (overrides land before
the epoch bump: placement-epoch-safe by construction). Deployments
where a "cell" is a whole process (the edge tier) inject their own
actuators — e.g. ``scale_down=server.drain`` for the PR-13 handoff.

Everything the controller does is observable: decisions land in the
``__autoscale__`` flight-recorder ring, `hocuspocus_fleet_autoscale_*`
metrics export the roster and signal, and `GET /debug/fleet` carries a
live ``autoscale`` section via the FleetView attachment seam.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Callable, Optional

from ..observability.fleet import get_fleet_view
from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import Counter, Gauge
from ..server.types import Extension, Payload

RING = "__autoscale__"


class FleetController:
    """Pure decision core — stats in, one decision out. No clocks, no
    I/O: tests drive it tick-by-tick with injected digests."""

    def __init__(
        self,
        num_cells: int,
        min_cells: int = 1,
        max_cells: Optional[int] = None,
        up_threshold: float = 0.75,
        down_threshold: float = 0.35,
        projected_max: Optional[float] = None,
        hold_ticks: int = 3,
        cooldown_ticks: int = 5,
        work_target: float = 150.0,
        lane_target: float = 64.0,
        occupancy_target: float = 0.85,
        history: int = 64,
    ) -> None:
        self.num_cells = max(int(num_cells), 1)
        self.min_cells = max(int(min_cells), 1)
        self.max_cells = (
            self.num_cells if max_cells is None else min(int(max_cells), self.num_cells)
        )
        self.up_threshold = float(up_threshold)
        self.down_threshold = float(down_threshold)
        # the load the survivors would carry after a scale-down; default
        # midway between the thresholds so a removal can never land the
        # fleet straight back in scale-up territory
        self.projected_max = (
            (self.up_threshold + self.down_threshold) / 2.0
            if projected_max is None
            else float(projected_max)
        )
        self.hold_ticks = max(int(hold_ticks), 1)
        self.cooldown_ticks = max(int(cooldown_ticks), 0)
        self.work_target = max(float(work_target), 1e-9)
        self.lane_target = max(float(lane_target), 1e-9)
        self.occupancy_target = max(float(occupancy_target), 1e-9)
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self.tick = 0
        self.parked = False
        self.park_reason: Optional[str] = None
        self.signal: Optional[float] = None
        self.last_decision: Optional[dict] = None
        self.decisions: "deque[dict]" = deque(maxlen=max(int(history), 1))
        self.counters = {
            "ticks": 0,
            "scale_ups": 0,
            "scale_downs": 0,
            "holds": 0,
            "parks": 0,
            "unparks": 0,
        }

    # -- signal ---------------------------------------------------------------

    def cell_load(self, cell: dict) -> float:
        """One cell's normalized load: the hottest of its signals. Max,
        not mean — a saturated lane on an otherwise idle cell is still
        a reason to keep capacity."""
        work = float(cell.get("work_rate") or 0.0) / self.work_target
        lane = float(cell.get("lane_queue_depth") or 0.0) / self.lane_target
        occupancy = float(cell.get("occupancy") or 0.0) / self.occupancy_target
        return max(work, lane, occupancy)

    # -- decision table ---------------------------------------------------------

    def observe(
        self,
        cells: "list[dict]",
        scaling_allowed: bool = True,
        park_reason: Optional[str] = None,
    ) -> dict:
        """One tick: digest-shaped per-cell stats (``healthy`` marks
        active members; unhealthy entries are the warm-spare pool) plus
        the brownout park signal, out comes the decision."""
        self.tick += 1
        self.counters["ticks"] += 1
        active = [c for c in cells if c.get("healthy")]
        spares = [c for c in cells if not c.get("healthy")]
        if active:
            loads = [self.cell_load(c) for c in active]
            self.signal = sum(loads) / len(loads)
        else:
            self.signal = None

        if not scaling_allowed:
            # hard park: never fight the overload plane. Streaks reset
            # (brownout-shaped signals prove nothing) and the cooldown
            # re-arms so unparking starts from a clean slate.
            reason = park_reason or "overload"
            newly_parked = not self.parked
            if newly_parked:
                self.parked = True
                self.counters["parks"] += 1
            self.park_reason = reason
            self._up_streak = self._down_streak = 0
            self._cooldown = self.cooldown_ticks
            return self._decide("park", None, reason, record=newly_parked)
        if self.parked:
            self.parked = False
            self.park_reason = None
            self.counters["unparks"] += 1
            self._decide("unpark", None, "scaling_resumed", record=True)

        if self._cooldown > 0:
            self._cooldown -= 1
            return self._decide("hold", None, "cooldown")
        if self.signal is None:
            return self._decide("hold", None, "no_active_cells")

        if self.signal >= self.up_threshold:
            self._down_streak = 0
            self._up_streak += 1
            if self._up_streak < self.hold_ticks:
                return self._decide("hold", None, "up_streak_building")
            if not spares or len(active) >= self.max_cells:
                return self._decide("hold", None, "no_spare_capacity")
            self._up_streak = 0
            self._cooldown = self.cooldown_ticks
            target = min(spares, key=lambda c: c.get("cell", 0))
            return self._decide("scale_up", target.get("cell"), "load_high")

        if self.signal <= self.down_threshold:
            self._up_streak = 0
            self._down_streak += 1
            if self._down_streak < self.hold_ticks:
                return self._decide("hold", None, "down_streak_building")
            if len(active) <= self.min_cells:
                return self._decide("hold", None, "at_min_cells")
            projected = self.signal * len(active) / (len(active) - 1)
            if projected > self.projected_max:
                return self._decide("hold", None, "survivors_too_hot")
            self._down_streak = 0
            self._cooldown = self.cooldown_ticks
            coldest = min(
                active, key=lambda c: (self.cell_load(c), c.get("cell", 0))
            )
            return self._decide("scale_down", coldest.get("cell"), "load_low")

        # mid-band: load is where we want it — both streaks reset, so a
        # signal oscillating across a threshold never accumulates one
        self._up_streak = self._down_streak = 0
        return self._decide("hold", None, "in_band")

    def _decide(
        self, action: str, cell: Any, reason: str, record: Optional[bool] = None
    ) -> dict:
        decision = {
            "action": action,
            "cell": cell,
            "reason": reason,
            "signal": None if self.signal is None else round(self.signal, 4),
            "tick": self.tick,
        }
        self.last_decision = decision
        if action == "hold":
            self.counters["holds"] += 1
        elif action == "scale_up":
            self.counters["scale_ups"] += 1
        elif action == "scale_down":
            self.counters["scale_downs"] += 1
        # the bounded decision history keeps TRANSITIONS (scales, the
        # first tick of a park, the unpark), not the parked steady state
        if record if record is not None else action in ("scale_up", "scale_down"):
            self.decisions.append(decision)
        return decision

    def status(self) -> dict:
        return {
            "parked": self.parked,
            "park_reason": self.park_reason,
            "signal": None if self.signal is None else round(self.signal, 4),
            "thresholds": {
                "up": self.up_threshold,
                "down": self.down_threshold,
                "projected_max": self.projected_max,
                "hold_ticks": self.hold_ticks,
                "cooldown_ticks": self.cooldown_ticks,
                "work_target": self.work_target,
            },
            "bounds": {"min_cells": self.min_cells, "max_cells": self.max_cells},
            "last_decision": self.last_decision,
            "decisions": list(self.decisions),
            "counters": dict(self.counters),
        }


class FleetControllerExtension(Extension):
    """The tick driver: samples the plane, feeds the core, actuates.

    Ordered after Metrics (1000) and CellIngress (950) so telemetry and
    the cell identity are lit, before the plane (900) so `on_configure`
    can still find it by walking the extension list either way.
    """

    priority = 920

    def __init__(
        self,
        interval_s: float = 0.5,
        warm_spares: int = 0,
        scale_up: Optional[Callable] = None,
        scale_down: Optional[Callable] = None,
        **tuning: Any,
    ) -> None:
        self.interval_s = max(float(interval_s), 0.01)
        self.warm_spares = max(int(warm_spares), 0)
        self._scale_up_override = scale_up
        self._scale_down_override = scale_down
        self._tuning = tuning
        self.controller: Optional[FleetController] = None
        # the plane either co-installs directly (harness, tests) or
        # lives behind a supervised wrapper whose runtime is built in a
        # worker thread AFTER listen — resolved lazily via the property
        self._plane_direct = None
        self._plane_host = None
        self._num_cells_from_plane = "num_cells" not in tuning
        self._spares_applied = False
        self.instance = None
        self._task: Optional[asyncio.Task] = None
        self._t0: Optional[float] = None
        # rate derivation off the plane's monotonic dispatch counters
        self._last_sample: "dict[int, float]" = {}
        self._last_sample_t: Optional[float] = None
        self._rate_ewma: "dict[int, float]" = {}
        # roster timeline: every membership change, stamped relative to
        # listen time — the bench artifact's scale story
        self.timeline: "deque[dict]" = deque(maxlen=256)
        self.actuation = {
            "activations": 0,
            "parks": 0,
            "docs_migrated": 0,
            "failures": 0,
        }
        # -- exposition (adopted by a co-installed Metrics registry) ------
        self.decisions_metric = Counter(
            "hocuspocus_fleet_autoscale_decisions_total",
            "Autoscaling decisions by action (scale_up/scale_down/park)",
        )
        self.active_cells_gauge = Gauge(
            "hocuspocus_fleet_autoscale_active_cells",
            "Cells currently in placement under the autoscaler",
            fn=lambda: float(len(self.active_cells())),
        )
        self.parked_gauge = Gauge(
            "hocuspocus_fleet_autoscale_parked",
            "1 while scaling is parked by the overload ladder",
            fn=lambda: 1.0
            if self.controller is not None and self.controller.parked
            else 0.0,
        )
        self.signal_gauge = Gauge(
            "hocuspocus_fleet_autoscale_signal",
            "Normalized fleet-load signal (1.0 = at target)",
            fn=lambda: float(
                (self.controller.signal or 0.0)
                if self.controller is not None
                else 0.0
            ),
        )
        self.migrations_metric = Counter(
            "hocuspocus_fleet_autoscale_migrations_total",
            "Docs migrated off cells by scale-down decisions",
        )

    # -- wiring ---------------------------------------------------------------

    @property
    def plane(self):
        if self._plane_direct is not None:
            return self._plane_direct
        if self._plane_host is not None:
            runtime = getattr(self._plane_host, "runtime", None)
            if (
                runtime is not None
                and hasattr(runtime, "cell_stats")
                and hasattr(runtime, "placement")
            ):
                self._adopt_plane(runtime)
                return runtime
        return None

    @plane.setter
    def plane(self, value) -> None:
        self._plane_direct = value

    def _adopt_plane(self, plane) -> None:
        """First resolution of a supervised runtime: size the core to
        the real fleet and apply any still-pending warm-spare parking
        (listen came and went while the supervisor was still booting)."""
        self._plane_direct = plane
        if self.controller is not None and self._num_cells_from_plane:
            total = max(len(plane.cells), 1)
            self.controller.num_cells = total
            if "max_cells" not in self._tuning:
                self.controller.max_cells = total
            else:
                self.controller.max_cells = min(
                    self.controller.max_cells, total
                )
        if self._t0 is not None and not self._spares_applied:
            self._park_warm_spares()
            self._note_roster("boot")

    def _park_warm_spares(self) -> None:
        """Boot-time warm spares: the last N cells start parked — BUILT
        (arena allocated, registry warm) but out of placement, so the
        fleet boots at its trough footprint."""
        if self._spares_applied:
            return
        self._spares_applied = True
        if self._plane_direct is None or not self.warm_spares:
            return
        total = len(self._plane_direct.cells)
        floor = self.controller.min_cells if self.controller else 1
        spares = min(self.warm_spares, max(total - floor, 0))
        for index in range(total - spares, total):
            self._plane_direct.placement.mark_down(index)
        if spares:
            get_flight_recorder().record(
                RING, "warm_spares_parked", count=spares, total=total
            )

    async def on_configure(self, data: Payload) -> None:
        self.instance = data.instance
        extensions = getattr(data.instance, "_extensions", None) or getattr(
            data.instance.configuration, "extensions", []
        )
        for extension in extensions:
            if hasattr(extension, "cell_stats") and hasattr(
                extension, "placement"
            ):
                self._plane_direct = extension
                break
        else:
            for extension in extensions:
                # the supervised face (tpu/supervisor.py) builds its
                # runtime asynchronously — remember the host, resolve
                # the plane lazily once the supervisor is READY
                if getattr(extension, "supervisor", None) is not None:
                    self._plane_host = extension
                    break
        num_cells = (
            len(self._plane_direct.cells)
            if self._plane_direct is not None
            else 1
        )
        self._tuning.setdefault("num_cells", num_cells)
        self.controller = FleetController(**self._tuning)
        # metric adoption: same registry-walk pattern as the replica and
        # edge families — whichever co-installed extension exposes one
        for extension in extensions:
            registry = getattr(extension, "registry", None)
            if registry is not None and callable(
                getattr(registry, "register", None)
            ):
                for metric in self.metrics():
                    try:
                        registry.register(metric)
                    except ValueError:
                        pass
                break
        get_fleet_view().attach_autoscale(self.status)

    async def on_listen(self, data: Payload) -> None:
        self._t0 = time.monotonic()
        # reading .plane may adopt an already-READY supervised runtime,
        # which parks the spares and notes the boot itself
        if self.plane is not None and not self._spares_applied:
            self._park_warm_spares()
            self._note_roster("boot")
        # a still-booting supervised runtime is handled by _adopt_plane
        # once it resolves
        self._task = asyncio.ensure_future(self._run())

    async def on_destroy(self, data: Payload) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        view = get_fleet_view()
        if view.autoscale_status is self.status:
            view.attach_autoscale(None)

    def metrics(self) -> tuple:
        return (
            self.decisions_metric,
            self.active_cells_gauge,
            self.parked_gauge,
            self.signal_gauge,
            self.migrations_metric,
        )

    # -- sampling -------------------------------------------------------------

    def active_cells(self) -> "list[int]":
        if self.plane is None:
            return []
        return sorted(self.plane.placement.healthy)

    def sample_cells(self) -> "list[dict]":
        """Digest-shaped stats with work-unit RATES. The plane's
        `dispatched_total` is monotonic and migration-invariant (unlike
        the per-slot counters, which hydration credits wholesale), so
        the diff is pure fresh dispatch work; a low-RTT-style EWMA
        smooths tick-boundary noise."""
        stats = self.plane.cell_stats()
        now = time.monotonic()
        dt = (
            None
            if self._last_sample_t is None
            else max(now - self._last_sample_t, 1e-6)
        )
        for entry in stats:
            index = entry["cell"]
            plane = self.plane.cells[index].plane
            total = float(getattr(plane, "dispatched_total", 0.0))
            last = self._last_sample.get(index)
            rate = 0.0
            if dt is not None and last is not None:
                rate = max(total - last, 0.0) / dt
            smoothed = self._rate_ewma.get(index)
            smoothed = rate if smoothed is None else 0.5 * smoothed + 0.5 * rate
            self._rate_ewma[index] = smoothed
            self._last_sample[index] = total
            entry["work_rate"] = round(smoothed, 2)
        self._last_sample_t = now
        return stats

    # -- tick loop -------------------------------------------------------------

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.tick_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                self.actuation["failures"] += 1

    async def tick_once(self, cells: "Optional[list[dict]]" = None) -> dict:
        """One full control cycle; tests inject digest-shaped `cells`
        to drive the loop without wall-clock sampling."""
        from ..server.overload import RUNG_NAMES, get_overload_controller

        overload = get_overload_controller()
        allowed = overload.scaling_allowed()
        park_reason = (
            None if allowed else f"brownout:{RUNG_NAMES[overload.rung]}"
        )
        if cells is None:
            if self.plane is None:
                return {"action": "hold", "reason": "no_plane"}
            cells = self.sample_cells()
        decision = self.controller.observe(
            cells, scaling_allowed=allowed, park_reason=park_reason
        )
        await self._apply(decision)
        return decision

    async def _apply(self, decision: dict) -> None:
        action = decision["action"]
        if action in ("scale_up", "scale_down"):
            self.decisions_metric.inc(action=action)
            get_flight_recorder().record(
                RING,
                action,
                cell=decision["cell"],
                signal=decision["signal"],
                reason=decision["reason"],
            )
        elif action in ("park", "unpark") and decision is (
            self.controller.decisions[-1] if self.controller.decisions else None
        ):
            # transition tick only (steady parked ticks aren't recorded)
            self.decisions_metric.inc(action=action)
            get_flight_recorder().record(
                RING, action, reason=decision["reason"]
            )
        if action == "scale_up":
            await self._do_scale_up(decision["cell"])
        elif action == "scale_down":
            await self._do_scale_down(decision["cell"])

    async def _do_scale_up(self, index: Any) -> None:
        if self._scale_up_override is not None:
            await self._scale_up_override(index)
        elif self.plane is not None:
            await self.plane.activate_cell(index, self.instance)
        self.actuation["activations"] += 1
        self._note_roster("scale_up")

    async def _do_scale_down(self, index: Any) -> None:
        if self._scale_down_override is not None:
            await self._scale_down_override(index)
            self.actuation["parks"] += 1
        elif self.plane is not None:
            result = await self.plane.park_cell(index)
            moved = int(result.get("migrated", 0))
            self.actuation["parks"] += 1
            self.actuation["docs_migrated"] += moved
            if moved:
                self.migrations_metric.inc(moved)
        self._note_roster("scale_down")

    def _note_roster(self, action: str) -> None:
        entry = {
            "t_s": 0.0
            if self._t0 is None
            else round(time.monotonic() - self._t0, 3),
            "action": action,
            "active": self.active_cells(),
        }
        self.timeline.append(entry)
        get_flight_recorder().record(
            RING, "roster", action=action, active=entry["active"]
        )

    # -- status (the /debug/fleet `autoscale` section) -------------------------

    def status(self) -> dict:
        payload = {
            "enabled": True,
            "interval_s": self.interval_s,
            "roster": {
                "active": self.active_cells(),
                "total": len(self.plane.cells) if self.plane is not None else 0,
            },
            "timeline": list(self.timeline),
            "actuation": dict(self.actuation),
        }
        if self.controller is not None:
            payload.update(self.controller.status())
        return payload
