"""Cross-host cell admission: host-qualified roster + clock-gated joins.

The relay envelope protocol is already host-agnostic — a cell announces
itself on the control channel and edges route to its cell channel by id,
wherever the process lives. What was missing for cross-host fleets is
POLICY, not dataplane:

* **Identity.** A cell id gains an optional ``host/`` qualifier
  (``host-b/cell-0``). Rendezvous scoring in `CellRouter` and
  `DevicePlacement` hashes the full string, so a qualified id is a
  first-class placement target with zero routing changes.

* **Admission** (`AdmissionGate`, lives on each edge). A local-host
  cell is admitted on its first CELL_UP exactly as before. A FOREIGN
  cell stays **pending** — announced, probed, but *not routable* —
  until its per-peer `ClockOffsetEstimator` (observability/fleet.py)
  has resolved: enough PING/PONG samples at a bounded RTT. The gate
  deliberately judges resolution *quality* (sample count + RTT bound),
  never offset *magnitude*: `perf_counter` origins differ arbitrarily
  across processes, so a huge offset is normal while an unresolved or
  wide-RTT estimate means cross-tier latency attribution (and the
  staleness math in FleetView) would be garbage for that peer.

* **Membership epochs** (`PeerRoster`, mirrored on each cell). Edges
  already version routing through `CellRouter.epoch`; cells had no
  equivalent, which is why `/debug/fleet` could only flag epoch skew
  for the edge role. Each cell now folds control-channel lifecycle
  transitions (CELL_UP of a new peer, CELL_DRAINING, CELL_DOWN) into a
  monotonic roster epoch published in its digest — cells that watched
  the same control stream agree, and a cell that missed a transition
  diverges, which is exactly the skew worth flagging.

Admission never blocks convergence: a pending cell's announcements are
idempotent heartbeats, and once admitted the router's epoch bump heals
any in-flight routes through the existing stale-route/Step1-resync
machinery.
"""

from __future__ import annotations

import time
from typing import Optional

HOST_SEPARATOR = "/"


def cell_host(cell_id: str) -> Optional[str]:
    """The host qualifier of a cell id, or None for a bare (legacy,
    implicitly local) id."""
    if HOST_SEPARATOR in cell_id:
        return cell_id.split(HOST_SEPARATOR, 1)[0]
    return None


def qualify_cell_id(host_id: Optional[str], cell_id: str) -> str:
    """Qualify a bare cell id with its host. Already-qualified ids and
    hostless deployments pass through unchanged."""
    if not host_id or HOST_SEPARATOR in cell_id:
        return cell_id
    return f"{host_id}{HOST_SEPARATOR}{cell_id}"


class AdmissionGate:
    """Edge-side admission policy for announced cells.

    ``evaluate`` is pure (estimator in, verdict out); the pending table
    plus counters around it are what the gateway wires into its CELL_UP
    dispatch and `/debug/fleet` status.
    """

    def __init__(
        self,
        local_host: Optional[str] = None,
        min_samples: int = 2,
        max_rtt_s: float = 0.5,
    ) -> None:
        self.local_host = local_host
        self.min_samples = max(int(min_samples), 1)
        self.max_rtt_s = float(max_rtt_s)
        # cell id -> {"since": monotonic, "reason": last hold reason}
        self.pending: "dict[str, dict]" = {}
        self.counters = {
            "admitted_local": 0,
            "admitted_foreign": 0,
            "held_pending": 0,
            "pending_expired": 0,
        }

    def is_foreign(self, cell_id: str) -> bool:
        host = cell_host(cell_id)
        return host is not None and host != self.local_host

    def evaluate(self, cell_id: str, estimator=None) -> "tuple[bool, str]":
        """(admit, reason). Local cells always admit; foreign cells
        need a RESOLVED clock-offset estimate (samples + RTT bound)."""
        if not self.is_foreign(cell_id):
            return True, "local"
        if estimator is None or estimator.samples < self.min_samples:
            samples = 0 if estimator is None else estimator.samples
            return False, f"clock_unresolved:{samples}/{self.min_samples}"
        rtt = estimator.rtt_s
        if rtt is None or rtt > self.max_rtt_s:
            shown = "none" if rtt is None else f"{rtt:.3f}s"
            return False, f"rtt_unbounded:{shown}"
        return True, "clock_resolved"

    def hold(self, cell_id: str, reason: str) -> bool:
        """Record a held cell; True when it is NEWLY pending."""
        now = time.monotonic()
        entry = self.pending.get(cell_id)
        if entry is None:
            self.pending[cell_id] = {
                "since": now,
                "last_seen": now,
                "reason": reason,
            }
            self.counters["held_pending"] += 1
            return True
        entry["reason"] = reason
        # liveness, not patience: every re-hold (CELL_UP heartbeat or a
        # PONG re-evaluation) proves the peer is alive — expiry must
        # only fire when the announcements STOP
        entry["last_seen"] = now
        return False

    def admit(self, cell_id: str) -> bool:
        """Record an admission; True when the cell had been pending
        (i.e. this is a foreign join completing, not a heartbeat)."""
        was_pending = self.pending.pop(cell_id, None) is not None
        if was_pending and self.is_foreign(cell_id):
            self.counters["admitted_foreign"] += 1
        return was_pending

    def note_local(self, newly_routable: bool) -> None:
        """First-time local admissions, counted by the caller off the
        router's membership-change signal (heartbeats are no-ops)."""
        if newly_routable:
            self.counters["admitted_local"] += 1

    def expire(self, timeout_s: float) -> "list[str]":
        """Drop pending cells that stopped announcing (same liveness
        contract as the router's heartbeat sweep)."""
        now = time.monotonic()
        expired = [
            cell_id
            for cell_id, entry in self.pending.items()
            if now - entry["last_seen"] > timeout_s
        ]
        for cell_id in expired:
            self.pending.pop(cell_id, None)
            self.counters["pending_expired"] += 1
        return expired

    def status(self) -> dict:
        return {
            "local_host": self.local_host,
            "min_samples": self.min_samples,
            "max_rtt_s": self.max_rtt_s,
            "pending": {
                cell_id: entry["reason"]
                for cell_id, entry in sorted(self.pending.items())
            },
            "counters": dict(self.counters),
        }


class PeerRoster:
    """A cell's mirror of fleet membership off the control channel.

    Cells don't route (edges own that), but they DO need a versioned
    view of who is in the fleet so `/debug/fleet` can compare roster
    epochs cell-vs-cell — a cell whose epoch diverges from its peers
    missed (or double-saw) a membership transition. `note` is fed from
    the cell's control-channel dispatch, INCLUDING its own announce
    echo: every subscriber of the same stream then counts the same
    transitions and lands on the same epoch.
    """

    __slots__ = ("peers", "epoch")

    def __init__(self) -> None:
        self.peers: "dict[str, str]" = {}
        self.epoch = 0

    def note(self, cell_id: str, state: str) -> bool:
        """Fold one lifecycle observation; True (and an epoch bump) on
        a real transition, False for heartbeat no-ops."""
        if state == "down":
            if self.peers.pop(cell_id, None) is None:
                return False
            self.epoch += 1
            return True
        if self.peers.get(cell_id) == state:
            return False
        self.peers[cell_id] = state
        self.epoch += 1
        return True

    def table(self) -> dict:
        return {
            "epoch": self.epoch,
            "peers": dict(sorted(self.peers.items())),
        }
