"""Redis fan-out extension — the horizontal-scaling backbone.

Capability parity with reference `extension-redis/src/Redis.ts`:
one pub/sub channel per document named `{prefix}:{documentName}`, frames
prefixed `[1-byte idLen][identifier][payload]` for self-filtering, a
distributed store lock electing a single storer (SET NX PX + compare-
and-delete release), join protocol publishing SyncStep1 + QueryAwareness
on document load, and delayed unsubscribe on disconnect.
"""

from __future__ import annotations

import asyncio
import random
import uuid
from typing import Any, Callable, Optional

from ..net.resp import ClusterSubscriber, RedisClient, RedisClusterClient, RedisSubscriber
from ..protocol.message import IncomingMessage, OutgoingMessage
from ..aio import spawn_tracked
from ..server import REDIS_ORIGIN, logger
from ..server.message_receiver import MessageReceiver
from ..server.types import Extension, Payload


class LockContention(Exception):
    """Another instance holds the store lock. Silent: halts the store
    chain without logging an error (reference throws an empty Error)."""

    def __init__(self) -> None:
        super().__init__("")


class _HeldLock:
    __slots__ = ("token", "count", "extend_handle", "extends")

    def __init__(self, token: str) -> None:
        self.token = token
        self.count = 1
        self.extend_handle: Optional[asyncio.TimerHandle] = None
        self.extends = 0


class Redis(Extension):
    # Higher priority so onStoreDocument can intercept the chain before
    # database extensions store the document.
    priority = 1000

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        prefix: str = "hocuspocus",
        identifier: Optional[str] = None,
        lock_timeout: int = 1000,
        disconnect_delay: int = 1000,
        nodes: Optional[list] = None,
        create_client: Optional[Callable[[], Any]] = None,
        create_subscriber: Optional[Callable[[Callable[[bytes, bytes], None]], Any]] = None,
        lock_retry_count: int = 10,
        lock_retry_delay: int = 100,
        lock_auto_extend: bool = True,
        lock_max_extends: int = 20,
    ) -> None:
        """Production seams beyond host/port (reference
        `extension-redis/src/Redis.ts:19-50,96-140`): `nodes` switches to
        a slot-routed cluster client; `create_client`/`create_subscriber`
        inject arbitrary client objects (any `RedisCommands`-shaped /
        subscriber-shaped implementation); the store lock retries with
        jittered delay and auto-extends at ttl/2 while a slow store runs.
        """
        self.host = host
        self.port = port
        self.prefix = prefix
        self.identifier = identifier or f"host-{uuid.uuid4()}"
        self.lock_timeout = lock_timeout
        self.disconnect_delay = disconnect_delay
        self.lock_retry_count = lock_retry_count
        self.lock_retry_delay = lock_retry_delay
        self.lock_auto_extend = lock_auto_extend
        self.lock_max_extends = lock_max_extends

        self.redis_transaction_origin = REDIS_ORIGIN
        if create_client is not None:
            self.pub = create_client()
        elif nodes:
            self.pub = RedisClusterClient(nodes)
        else:
            self.pub = RedisClient(host, port)
        if create_subscriber is not None:
            self.sub = create_subscriber(self._handle_incoming_message)
        elif nodes:
            self.sub = ClusterSubscriber(nodes, on_message=self._handle_incoming_message)
        else:
            self.sub = RedisSubscriber(host, port, on_message=self._handle_incoming_message)
        # resync on self-healed resubscribe: frames published while this
        # instance's subscriber was down/reconnecting are gone forever
        # (pub/sub is at-most-once) — publishing our SyncStep1 per loaded
        # doc makes peers send back whatever we missed (and vice versa)
        if hasattr(self.sub, "on_reconnect"):
            self.sub.on_reconnect = self._resync_after_reconnect
        self.instance = None
        # plane-served docs: last anti-entropy SyncStep1 publish per
        # doc, plus trailing timers so a QUIESCENT doc still gets one
        # final exchange after its last suppressed change (a dropped
        # window frame must heal even with no further edits)
        self._last_anti_entropy: dict[str, float] = {}
        self._anti_entropy_handles: dict[str, object] = {}
        self.plane_anti_entropy_seconds = 2.0
        # strong refs for fire-and-forget apply/publish tasks: the loop
        # only weakly references tasks, and under fan-out load a GC'd
        # unreferenced task silently drops the apply or the reply
        # publish (see hocuspocus_tpu/aio.py)
        self._tasks: set = set()
        self.locks: dict[str, _HeldLock] = {}  # lock key -> held state
        self._pending_disconnects: dict[str, asyncio.TimerHandle] = {}
        self._pending_after_store: dict[str, asyncio.TimerHandle] = {}
        identifier_bytes = self.identifier.encode()
        self.message_prefix = bytes([len(identifier_bytes)]) + identifier_bytes

    # -- keys / framing ----------------------------------------------------

    def get_key(self, document_name: str) -> str:
        return f"{self.prefix}:{document_name}"

    def lock_key(self, document_name: str) -> str:
        return f"{self.get_key(document_name)}:lock"

    def encode_message(self, message: bytes) -> bytes:
        return self.message_prefix + message

    def decode_message(self, data: bytes) -> tuple[str, bytes]:
        identifier_length = data[0]
        identifier = data[1 : identifier_length + 1].decode()
        return identifier, data[identifier_length + 1 :]

    # -- hooks -------------------------------------------------------------

    async def on_configure(self, data: Payload) -> None:
        self.instance = data.instance

    async def after_load_document(self, data: Payload) -> None:
        await self.sub.subscribe(self.get_key(data.document_name))
        await self.publish_first_sync_step(data.document_name, data.document)
        await self.request_awareness_from_other_instances(data.document_name)

    async def publish_first_sync_step(self, document_name: str, document) -> None:
        sync_message = (
            OutgoingMessage(document_name)
            .create_sync_message()
            .write_first_sync_step_for(document)
        )
        await self.pub.publish(
            self.get_key(document_name), self.encode_message(sync_message.to_bytes())
        )

    async def _resync_after_reconnect(self) -> None:
        """Subscriber self-healed after an outage: pull missed state.

        Publishing SyncStep1 (our state vector) per loaded doc makes
        every peer reply Step2 with what we lack + their own Step1, so
        both directions close the at-most-once gap. Awareness states
        are re-requested the same way. Best-effort: a doc that fails
        here heals on its next change exchange."""
        if self.instance is None:
            return
        for name, document in list(self.instance.documents.items()):
            try:
                await self.publish_first_sync_step(name, document)
                await self.request_awareness_from_other_instances(name)
            except Exception:
                logger.log_error(f"[redis] post-reconnect resync failed for {name!r}")

    async def request_awareness_from_other_instances(self, document_name: str) -> None:
        message = OutgoingMessage(document_name).write_query_awareness()
        await self.pub.publish(
            self.get_key(document_name), self.encode_message(message.to_bytes())
        )

    async def on_store_document(self, data: Payload) -> None:
        """Acquire the distributed store lock; losing after all retries
        means another instance stores — halt the chain silently."""
        resource = self.lock_key(data.document_name)
        held = self.locks.get(resource)
        if held is not None:
            # concurrent store of the same doc on this instance (the
            # saveMutex makes this rare): reenter instead of clobbering
            # the token and orphaning the first holder's release
            held.count += 1
            return
        token = str(uuid.uuid4())
        for attempt in range(self.lock_retry_count + 1):
            if await self.pub.acquire_lock(resource, token, self.lock_timeout):
                held = _HeldLock(token)
                self.locks[resource] = held
                if self.lock_auto_extend:
                    self._schedule_lock_extend(resource, held)
                return
            if attempt < self.lock_retry_count:
                delay = self.lock_retry_delay * (0.5 + random.random())
                await asyncio.sleep(delay / 1000)
        raise LockContention()

    def _schedule_lock_extend(self, resource: str, held: _HeldLock) -> None:
        """Keep a held lock alive while a slow store runs (ttl/2 cadence;
        the reference's redlock extends the same way)."""

        def extend() -> None:
            if self.locks.get(resource) is not held:
                return
            # bounded: a leaked lock (process wedged mid-store) must
            # eventually expire so other instances can store again
            held.extends += 1
            if held.extends > self.lock_max_extends:
                return

            async def run() -> None:
                try:
                    still_held = await self.pub.extend_lock(
                        resource, held.token, self.lock_timeout
                    )
                except Exception:
                    return  # redis gone: the lock will expire on its own
                if still_held and self.locks.get(resource) is held:
                    self._schedule_lock_extend(resource, held)

            spawn_tracked(self._tasks, run())

        held.extend_handle = asyncio.get_event_loop().call_later(
            self.lock_timeout / 2000, extend
        )

    async def _release_store_lock(self, document_name: str) -> None:
        resource = self.lock_key(document_name)
        held = self.locks.get(resource)
        if held is not None:
            held.count -= 1
            if held.count <= 0:
                self.locks.pop(resource, None)
                if held.extend_handle is not None:
                    held.extend_handle.cancel()
                try:
                    await self.pub.release_lock(resource, held.token)
                except Exception:
                    pass  # lock expires on its own

    async def on_store_document_failed(self, data: Payload) -> None:
        """A later store hook failed: release our lock so other instances
        can store (after_store_document is skipped on chain failure)."""
        await self._release_store_lock(data.document_name)

    async def after_store_document(self, data: Payload) -> None:
        await self._release_store_lock(data.document_name)
        await self._direct_connection_grace(data)

    async def _direct_connection_grace(self, data: Payload) -> None:
        # Direct-connection stores need a grace period so sync messages
        # reach the subscription before disconnect tears it down.
        if data.socket_id == "server":
            document_name = data.document_name
            pending = self._pending_after_store.pop(document_name, None)
            if pending is not None:
                pending.cancel()
            waiter: asyncio.Future = asyncio.get_event_loop().create_future()

            def resolve() -> None:
                self._pending_after_store.pop(document_name, None)
                if not waiter.done():
                    waiter.set_result(None)

            self._pending_after_store[document_name] = asyncio.get_event_loop().call_later(
                self.disconnect_delay / 1000, resolve
            )
            await waiter

    async def on_awareness_update(self, data: Payload) -> None:
        changed_clients = data.added + data.updated + data.removed
        message = OutgoingMessage(data.document_name).create_awareness_update_message(
            data.awareness, changed_clients
        )
        await self.pub.publish(
            self.get_key(data.document_name), self.encode_message(message.to_bytes())
        )

    def _handle_incoming_message(self, channel: bytes, data: bytes) -> None:
        identifier, message_data = self.decode_message(data)
        if identifier == self.identifier:
            return
        message = IncomingMessage(message_data)
        document_name = message.read_var_string()
        message.write_var_string(document_name)
        if self.instance is None:
            return
        document = self.instance.documents.get(document_name)
        if document is None:
            return

        def reply(response: bytes) -> None:
            spawn_tracked(
                self._tasks,
                self.pub.publish(
                    self.get_key(document.name), self.encode_message(response)
                ),
            )

        receiver = MessageReceiver(message, self.redis_transaction_origin)
        spawn_tracked(self._tasks, receiver.apply(document, None, reply))

    async def on_plane_broadcast(self, data: Payload) -> None:
        """Cross-instance fan-out of a serve-mode plane window: publish
        the merged update frame itself — peers apply it directly. One
        coalesced message per doc-window instead of the per-op
        SyncStep1/Step2 round trips (which remain, rate-limited, as
        anti-entropy below and as the join protocol)."""
        from ..protocol.frames import build_update_frame

        await self.pub.publish(
            self.get_key(data.document_name),
            self.encode_message(build_update_frame(data.document_name, data.update)),
        )

    async def on_change(self, data: Payload) -> None:
        if data.transaction_origin == self.redis_transaction_origin:
            return
        document = data.document
        source = getattr(document, "broadcast_source", None)
        capturing = source is not None and (
            not hasattr(source, "is_capturing")
            or source.is_capturing(data.document_name)
        )
        if capturing:
            # plane-served: steady propagation rides the window frames
            # (on_plane_broadcast); keep a LOW-RATE SyncStep1 exchange
            # per doc as anti-entropy so a dropped pub/sub message heals
            # instead of desyncing the peer forever
            name = data.document_name
            now = asyncio.get_event_loop().time()
            last = self._last_anti_entropy.get(name, 0.0)
            if now - last < self.plane_anti_entropy_seconds:
                # TRAILING edge: the final change before quiescence must
                # still trigger one exchange after the window closes
                if name not in self._anti_entropy_handles:
                    def fire(n=name):
                        self._anti_entropy_handles.pop(n, None)
                        doc_now = (
                            self.instance.documents.get(n) if self.instance else None
                        )
                        if doc_now is not None:
                            self._last_anti_entropy[n] = asyncio.get_event_loop().time()
                            spawn_tracked(
                                self._tasks, self.publish_first_sync_step(n, doc_now)
                            )

                    self._anti_entropy_handles[name] = asyncio.get_event_loop().call_later(
                        self.plane_anti_entropy_seconds, fire
                    )
                return
            self._last_anti_entropy[name] = now
            # a pending trailing-edge timer would fire a second SyncStep1
            # right after this fresh one, busting the rate limit
            handle = self._anti_entropy_handles.pop(name, None)
            if handle is not None:
                handle.cancel()
        await self.publish_first_sync_step(data.document_name, data.document)

    async def on_disconnect(self, data: Payload) -> None:
        document_name = data.document_name
        pending = self._pending_disconnects.pop(document_name, None)
        if pending is not None:
            pending.cancel()

        def disconnect() -> None:
            self._pending_disconnects.pop(document_name, None)
            self._last_anti_entropy.pop(document_name, None)
            handle = self._anti_entropy_handles.pop(document_name, None)
            if handle is not None:
                handle.cancel()
            document = self.instance.documents.get(document_name) if self.instance else None
            if document is not None and document.get_connections_count() > 0:
                return
            spawn_tracked(self._tasks, self.sub.unsubscribe(self.get_key(document_name)))
            if document is not None:
                spawn_tracked(self._tasks, self.instance.unload_document(document))

        # Delay to allow last-minute syncs to arrive on the subscription.
        self._pending_disconnects[document_name] = asyncio.get_event_loop().call_later(
            self.disconnect_delay / 1000, disconnect
        )

    async def before_broadcast_stateless(self, data: Payload) -> None:
        message = OutgoingMessage(data.document_name).write_broadcast_stateless(data.payload)
        await self.pub.publish(
            self.get_key(data.document_name), self.encode_message(message.to_bytes())
        )

    async def on_destroy(self, data: Payload) -> None:
        for handle in list(self._pending_disconnects.values()):
            handle.cancel()
        for handle in list(self._anti_entropy_handles.values()):
            handle.cancel()
        self._anti_entropy_handles.clear()
        for handle in list(self._pending_after_store.values()):
            handle.cancel()
        for held in list(self.locks.values()):
            if held.extend_handle is not None:
                held.extend_handle.cancel()
        self.pub.close()
        self.sub.close()
