"""Redis fan-out extension — the horizontal-scaling backbone.

Capability parity with reference `extension-redis/src/Redis.ts`:
one pub/sub channel per document named `{prefix}:{documentName}`, frames
prefixed `[1-byte idLen][identifier][payload]` for self-filtering, a
distributed store lock electing a single storer (SET NX PX + compare-
and-delete release), join protocol publishing SyncStep1 + QueryAwareness
on document load, and delayed unsubscribe on disconnect.

Beyond parity, the REPLICATION FAST PATH (docs/guides/
horizontal-scaling.md) makes the cross-instance cost O(ticks x
channels) instead of O(updates x instances):

- **Outbound: per-tick publish coalescing.** Local updates ride the
  broadcast tick (`server/fanout.py` hands the tick's local-origin
  updates — and the already-built wire frame when the whole tick is
  local — to this extension's publish lane); plane window broadcasts
  (`on_plane_broadcast`) enqueue into the same lane. One merged
  Y-update frame per (doc, tick), awareness piggybacked, everything
  shipped through the pipelined client's single write+drain per tick.
- **Inbound: batched apply.** Incoming frames land in a bounded
  per-doc inbox drained once per tick: contiguous update frames merge
  into ONE `apply_update` (one local fan-out tick) per doc per drain;
  overflow drops are healed by an anti-entropy SyncStep1 exchange —
  never silent loss.
- **Anti-entropy.** Pub/sub is at-most-once, so direct update frames
  can vanish; a rate-limited SyncStep1 exchange per doc (immediate
  past the window, trailing edge within it) bounds any divergence
  window for both CPU-doc and plane-served replication.
"""

from __future__ import annotations

import asyncio
import random
import uuid
from collections import deque
from typing import Any, Callable, Optional

from ..crdt import apply_update
from ..net.resp import (
    ClusterSubscriber,
    PipelinedRedisClient,
    RedisClient,
    RedisClusterClient,
    RedisSubscriber,
)
from ..observability.wire import get_wire_telemetry
from ..protocol.frames import (
    build_update_frame,
    parse_frame_header,
    parse_frame_headers_batch,
)
from ..protocol.message import IncomingMessage, MessageType, OutgoingMessage
from ..protocol.sync import MESSAGE_YJS_UPDATE, coalesce_updates
from ..aio import spawn_tracked
from ..crdt.encoding import Decoder
from ..server import REDIS_ORIGIN, logger
from ..server.message_receiver import MessageReceiver
from ..server.types import Extension, Payload


class LockContention(Exception):
    """Another instance holds the store lock. Silent: halts the store
    chain without logging an error (reference throws an empty Error)."""

    def __init__(self) -> None:
        super().__init__("")


class _HeldLock:
    __slots__ = ("token", "count", "extend_handle", "extends")

    def __init__(self, token: str) -> None:
        self.token = token
        self.count = 1
        self.extend_handle: Optional[asyncio.TimerHandle] = None
        self.extends = 0


class Redis(Extension):
    # Higher priority so onStoreDocument can intercept the chain before
    # database extensions store the document.
    priority = 1000

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        prefix: str = "hocuspocus",
        identifier: Optional[str] = None,
        lock_timeout: int = 1000,
        disconnect_delay: int = 1000,
        nodes: Optional[list] = None,
        create_client: Optional[Callable[[], Any]] = None,
        create_subscriber: Optional[Callable[[Callable[[bytes, bytes], None]], Any]] = None,
        lock_retry_count: int = 10,
        lock_retry_delay: int = 100,
        lock_auto_extend: bool = True,
        lock_max_extends: int = 20,
        pipeline: bool = True,
        coalesce: bool = True,
        inbox_batch: bool = True,
        inbox_limit: int = 512,
    ) -> None:
        """Production seams beyond host/port (reference
        `extension-redis/src/Redis.ts:19-50,96-140`): `nodes` switches to
        a slot-routed cluster client; `create_client`/`create_subscriber`
        inject arbitrary client objects (any `RedisCommands`-shaped /
        subscriber-shaped implementation); the store lock retries with
        jittered delay and auto-extends at ttl/2 while a slow store runs.

        Replication fast path knobs: `pipeline` uses the fire-and-forget
        `PipelinedRedisClient` publish lane (single-node only; clusters
        and injected clients keep their own transport), `coalesce`
        merges outbound publishes per (doc, tick) via the broadcast
        fan-out seam, `inbox_batch`/`inbox_limit` batch inbound frame
        application through a bounded per-doc inbox (overflow heals via
        anti-entropy, never silently). All default ON; turning them off
        restores per-op publish/apply for differential testing.
        """
        self.host = host
        self.port = port
        self.prefix = prefix
        self.identifier = identifier or f"host-{uuid.uuid4()}"
        self.lock_timeout = lock_timeout
        self.disconnect_delay = disconnect_delay
        self.lock_retry_count = lock_retry_count
        self.lock_retry_delay = lock_retry_delay
        self.lock_auto_extend = lock_auto_extend
        self.lock_max_extends = lock_max_extends
        self.coalesce = coalesce
        self.inbox_batch = inbox_batch
        self.inbox_limit = inbox_limit

        self.redis_transaction_origin = REDIS_ORIGIN
        if create_client is not None:
            self.pub = create_client()
        elif nodes:
            self.pub = RedisClusterClient(nodes)
        elif pipeline:
            self.pub = PipelinedRedisClient(host, port)
        else:
            self.pub = RedisClient(host, port)
        if create_subscriber is not None:
            self.sub = create_subscriber(self._handle_incoming_message)
        elif nodes:
            self.sub = ClusterSubscriber(nodes, on_message=self._handle_incoming_message)
        else:
            self.sub = RedisSubscriber(host, port, on_message=self._handle_incoming_message)
        # resync on self-healed resubscribe: frames published while this
        # instance's subscriber was down/reconnecting are gone forever
        # (pub/sub is at-most-once) — publishing our SyncStep1 per loaded
        # doc makes peers send back whatever we missed (and vice versa)
        if hasattr(self.sub, "on_reconnect"):
            self.sub.on_reconnect = self._resync_after_reconnect
        # the OUTBOUND half of the same story: the pipelined publish
        # lane arms its resync hook whenever an outage forced it to
        # shed buffered publishes (byte cap / overflow / unreachable
        # server) and fires it once on the next successful reconnect —
        # the join-batch exchange below pulls back exactly the window
        # the sheds dropped
        if hasattr(self.pub, "on_resync"):
            self.pub.on_resync = self._resync_after_reconnect
        self.instance = None
        # plane-served docs: last anti-entropy SyncStep1 publish per
        # doc, plus trailing timers so a QUIESCENT doc still gets one
        # final exchange after its last suppressed change (a dropped
        # window frame must heal even with no further edits)
        self._last_anti_entropy: dict[str, float] = {}
        self._anti_entropy_handles: dict[str, object] = {}
        self.plane_anti_entropy_seconds = 2.0
        # strong refs for fire-and-forget apply/publish tasks: the loop
        # only weakly references tasks, and under fan-out load a GC'd
        # unreferenced task silently drops the apply or the reply
        # publish (see hocuspocus_tpu/aio.py)
        self._tasks: set = set()
        self.locks: dict[str, _HeldLock] = {}  # lock key -> held state
        self._pending_disconnects: dict[str, asyncio.TimerHandle] = {}
        self._pending_after_store: dict[str, asyncio.TimerHandle] = {}
        identifier_bytes = self.identifier.encode()
        self.message_prefix = bytes([len(identifier_bytes)]) + identifier_bytes
        # -- replication lane state -----------------------------------
        # outbound: doc -> {"updates": [bytes], "frame": reusable local
        # tick frame (valid only while it covers exactly "updates"),
        # "awareness": [frame bytes]} flushed once per event-loop tick
        self._pending_pub: dict[str, dict] = {}
        self._pub_scheduled = False
        # inbound: doc -> bounded deque of (msg_type, payload_offset,
        # raw frame); drained once per tick, serialized by _drain_lock
        self._inboxes: dict[str, deque] = {}
        # raw frames awaiting header parse: the subscriber callback only
        # stages — headers for the whole backlog are parsed in ONE
        # native batch call when the drain routes them (_route_staged)
        self._inbox_staging: list = []
        self._inbox_scheduled = False
        self._drain_lock = asyncio.Lock()
        self._overflowed: set[str] = set()
        # observability + bench accounting for the fast path
        self.replication_stats = {
            "updates_enqueued": 0,
            "update_frames_published": 0,
            "awareness_frames_published": 0,
            "frames_saved": 0,
            "frames_received": 0,
            "inbound_applies": 0,
            "inbound_merged_saved": 0,
            "inbox_overflows": 0,
        }
        get_wire_telemetry().track_redis_inbox(self)

    # -- keys / framing ----------------------------------------------------

    def get_key(self, document_name: str) -> str:
        return f"{self.prefix}:{document_name}"

    def lock_key(self, document_name: str) -> str:
        return f"{self.get_key(document_name)}:lock"

    def encode_message(self, message: bytes) -> bytes:
        return self.message_prefix + message

    def decode_message(self, data: bytes) -> tuple[str, bytes]:
        identifier_length = data[0]
        identifier = data[1 : identifier_length + 1].decode()
        return identifier, data[identifier_length + 1 :]

    # -- hooks -------------------------------------------------------------

    async def on_configure(self, data: Payload) -> None:
        self.instance = data.instance

    async def after_load_document(self, data: Payload) -> None:
        document_name = data.document_name
        await self.sub.subscribe(self.get_key(document_name))
        if self.coalesce:
            self._register_replication_seam(data.document)
        await self._publish_join_batch(document_name, data.document)

    async def _publish_join_batch(self, document_name: str, document) -> None:
        """The join/resync protocol: SyncStep1 + QueryAwareness leave as
        ONE pipelined batch (enqueue-only on the pipelined client, a
        single execute_many round trip otherwise) instead of two
        serialized publish RTTs."""
        step1 = (
            OutgoingMessage(document_name)
            .create_sync_message()
            .write_first_sync_step_for(document)
            .to_bytes()
        )
        query = OutgoingMessage(document_name).write_query_awareness().to_bytes()
        await self._publish_batch(document_name, [step1, query])

    def _register_replication_seam(self, document) -> None:
        """Point the document's broadcast tick at the publish lane: the
        tick's local-origin updates (and its awareness frame) replicate
        with the tick's own coalescing + encode."""
        fanout = getattr(document, "fanout", None)
        if fanout is None:
            return
        name = document.name

        def replicate_updates(frame, updates, _name=name):
            self._queue_replication(_name, updates, frame)

        def replicate_awareness(frame, _name=name):
            self._queue_awareness_frame(_name, frame)

        fanout.replicate_updates = replicate_updates
        fanout.replicate_awareness = replicate_awareness

    async def publish_first_sync_step(self, document_name: str, document) -> None:
        sync_message = (
            OutgoingMessage(document_name)
            .create_sync_message()
            .write_first_sync_step_for(document)
        )
        await self._publish(document_name, sync_message.to_bytes())

    # -- the publish lane --------------------------------------------------

    async def _publish(self, document_name: str, payload: bytes) -> None:
        """Publish one framed message; enqueue-only on the pipelined
        client (the ack is consumed by its reply reader), awaited
        round-trip otherwise."""
        channel = self.get_key(document_name)
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            # zero-copy: prefix + frame ride as segments; the pipelined
            # lane joins them straight into the socket write
            nowait(channel, (self.message_prefix, payload))
        else:
            await self.pub.publish(channel, self.encode_message(payload))

    def _publish_nowait(self, document_name: str, payload: bytes) -> None:
        """Sync-context publish: enqueue on the pipelined client, else a
        tracked fire-and-forget task."""
        channel = self.get_key(document_name)
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            # zero-copy segment publish (see _publish)
            nowait(channel, (self.message_prefix, payload))
        else:
            spawn_tracked(
                self._tasks, self.pub.publish(channel, self.encode_message(payload))
            )

    async def _publish_batch(self, document_name: str, payloads: list) -> None:
        """Ship several messages for one doc in ONE round trip."""
        channel = self.get_key(document_name)
        nowait = getattr(self.pub, "publish_nowait", None)
        if nowait is not None:
            for payload in payloads:
                # zero-copy segment publish (see _publish)
                nowait(channel, (self.message_prefix, payload))
            return
        execute_many = getattr(self.pub, "execute_many", None)
        if execute_many is not None:
            await execute_many(
                [
                    ("PUBLISH", channel, self.encode_message(payload))
                    for payload in payloads
                ]
            )
            return
        for payload in payloads:
            await self.pub.publish(channel, self.encode_message(payload))

    def _queue_replication(
        self, document_name: str, updates: list, frame: Optional[bytes] = None
    ) -> None:
        """Enqueue local update payloads for the per-tick replication
        flush. `frame` is the local tick's already-built wire frame,
        reusable only while it covers exactly this entry's updates."""
        entry = self._pending_pub.setdefault(
            document_name, {"updates": [], "frame": None, "awareness": []}
        )
        if entry["updates"]:
            entry["frame"] = None  # frame no longer covers the entry
        else:
            entry["frame"] = frame
        entry["updates"].extend(updates)
        self.replication_stats["updates_enqueued"] += len(updates)
        self._schedule_pub_flush()

    def _queue_awareness_frame(self, document_name: str, frame: bytes) -> None:
        entry = self._pending_pub.setdefault(
            document_name, {"updates": [], "frame": None, "awareness": []}
        )
        entry["awareness"].append(frame)
        self._schedule_pub_flush()

    def _schedule_pub_flush(self) -> None:
        if self._pub_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._flush_publishes()  # no loop (direct/test use)
            return
        self._pub_scheduled = True
        loop.call_soon(self._flush_publishes)

    def _flush_publishes(self) -> None:
        """One replication flush: per doc, merge the tick's updates into
        ONE frame (reusing the local tick frame when handed one; falling
        back to per-update frames on merge failure so nothing is lost),
        publish awareness piggybacked — all enqueued into the pipelined
        outbox, which ships the whole tick in one write+drain."""
        self._pub_scheduled = False
        pending = self._pending_pub
        if not pending:
            return
        self._pending_pub = {}
        stats = self.replication_stats
        wire = get_wire_telemetry()
        for name, entry in pending.items():
            updates = entry["updates"]
            frame = entry["frame"]
            if updates:
                saved = 0
                if frame is None:
                    merged = coalesce_updates(updates)
                    if merged is None:
                        # merge failure: per-update frames, no loss
                        for update in updates:
                            self._publish_nowait(
                                name, build_update_frame(name, update)
                            )
                        stats["update_frames_published"] += len(updates)
                    else:
                        self._publish_nowait(name, build_update_frame(name, merged))
                        stats["update_frames_published"] += 1
                        saved = len(updates) - 1
                else:
                    # encode-once across the boundary: the local tick's
                    # frame bytes ship as-is
                    self._publish_nowait(name, frame)
                    stats["update_frames_published"] += 1
                    saved = len(updates) - 1
                if saved:
                    stats["frames_saved"] += saved
                    if wire.enabled:
                        wire.record_redis_frames_saved(saved, direction="publish")
            for awareness_frame in entry["awareness"]:
                self._publish_nowait(name, awareness_frame)
                stats["awareness_frames_published"] += 1

    async def _resync_after_reconnect(self) -> None:
        """Subscriber self-healed after an outage: pull missed state.

        Publishing SyncStep1 (our state vector) per loaded doc makes
        every peer reply Step2 with what we lack + their own Step1, so
        both directions close the at-most-once gap. Awareness states
        are re-requested the same way. Best-effort: a doc that fails
        here heals on its next change exchange."""
        if self.instance is None:
            return
        for name, document in list(self.instance.documents.items()):
            try:
                await self._publish_join_batch(name, document)
            except Exception:
                logger.log_error(f"[redis] post-reconnect resync failed for {name!r}")

    async def on_store_document(self, data: Payload) -> None:
        """Acquire the distributed store lock; losing after all retries
        means another instance stores — halt the chain silently."""
        resource = self.lock_key(data.document_name)
        held = self.locks.get(resource)
        if held is not None:
            # concurrent store of the same doc on this instance (the
            # saveMutex makes this rare): reenter instead of clobbering
            # the token and orphaning the first holder's release
            held.count += 1
            return
        token = str(uuid.uuid4())
        for attempt in range(self.lock_retry_count + 1):
            if await self.pub.acquire_lock(resource, token, self.lock_timeout):
                held = _HeldLock(token)
                self.locks[resource] = held
                if self.lock_auto_extend:
                    self._schedule_lock_extend(resource, held)
                return
            if attempt < self.lock_retry_count:
                delay = self.lock_retry_delay * (0.5 + random.random())
                await asyncio.sleep(delay / 1000)
        raise LockContention()

    def _schedule_lock_extend(self, resource: str, held: _HeldLock) -> None:
        """Keep a held lock alive while a slow store runs (ttl/2 cadence;
        the reference's redlock extends the same way)."""

        def extend() -> None:
            if self.locks.get(resource) is not held:
                return
            # bounded: a leaked lock (process wedged mid-store) must
            # eventually expire so other instances can store again
            held.extends += 1
            if held.extends > self.lock_max_extends:
                return

            async def run() -> None:
                try:
                    still_held = await self.pub.extend_lock(
                        resource, held.token, self.lock_timeout
                    )
                except Exception:
                    return  # redis gone: the lock will expire on its own
                if still_held and self.locks.get(resource) is held:
                    self._schedule_lock_extend(resource, held)

            spawn_tracked(self._tasks, run())

        held.extend_handle = asyncio.get_event_loop().call_later(
            self.lock_timeout / 2000, extend
        )

    async def _release_store_lock(self, document_name: str) -> None:
        resource = self.lock_key(document_name)
        held = self.locks.get(resource)
        if held is not None:
            held.count -= 1
            if held.count <= 0:
                self.locks.pop(resource, None)
                if held.extend_handle is not None:
                    held.extend_handle.cancel()
                try:
                    await self.pub.release_lock(resource, held.token)
                except Exception:
                    pass  # lock expires on its own

    async def on_store_document_failed(self, data: Payload) -> None:
        """A later store hook failed: release our lock so other instances
        can store (after_store_document is skipped on chain failure)."""
        await self._release_store_lock(data.document_name)

    async def after_store_document(self, data: Payload) -> None:
        await self._release_store_lock(data.document_name)
        await self._direct_connection_grace(data)

    async def _direct_connection_grace(self, data: Payload) -> None:
        # Direct-connection stores need a grace period so sync messages
        # reach the subscription before disconnect tears it down.
        if data.socket_id == "server":
            document_name = data.document_name
            pending = self._pending_after_store.pop(document_name, None)
            if pending is not None:
                pending.cancel()
            waiter: asyncio.Future = asyncio.get_event_loop().create_future()

            def resolve() -> None:
                self._pending_after_store.pop(document_name, None)
                if not waiter.done():
                    waiter.set_result(None)

            self._pending_after_store[document_name] = asyncio.get_event_loop().call_later(
                self.disconnect_delay / 1000, resolve
            )
            await waiter

    async def on_awareness_update(self, data: Payload) -> None:
        document = data.document if hasattr(data, "document") else None
        fanout = getattr(document, "fanout", None)
        if (
            self.coalesce
            and fanout is not None
            and fanout.replicate_awareness is not None
        ):
            # piggybacked on the broadcast tick: the fan-out engine's
            # per-tick awareness frame replicates via the publish lane
            # (one encode, one publish per doc-tick) — publishing here
            # too would double every awareness frame
            return
        changed_clients = data.added + data.updated + data.removed
        message = OutgoingMessage(data.document_name).create_awareness_update_message(
            data.awareness, changed_clients
        )
        await self._publish(data.document_name, message.to_bytes())

    def _handle_incoming_message(self, channel: bytes, data: bytes) -> None:
        identifier, message_data = self.decode_message(data)
        if identifier == self.identifier:
            return
        if self.instance is None:
            return
        if not self.inbox_batch:
            message = IncomingMessage(message_data)
            document_name = message.read_var_string()
            message.write_var_string(document_name)
            document = self.instance.documents.get(document_name)
            if document is None:
                return
            receiver = MessageReceiver(message, self.redis_transaction_origin)
            spawn_tracked(
                self._tasks,
                receiver.apply(document, None, self._make_reply(document.name)),
            )
            return
        # stage only: the header parse for the whole backlog happens in
        # ONE native batch call when the drain routes it (_route_staged)
        self._inbox_staging.append(message_data)
        self._schedule_inbox_drain()

    def _route_staged(self) -> None:
        """Route staged raw frames into per-doc inboxes. Headers for the
        whole backlog are parsed in one native batch call (malformed
        frames yield None slots and are dropped — nothing safe to
        enqueue)."""
        staged = self._inbox_staging
        if not staged or self.instance is None:
            return
        self._inbox_staging = []
        headers = parse_frame_headers_batch(staged, skip_malformed=True)
        documents = self.instance.documents
        stats = self.replication_stats
        wire = get_wire_telemetry()
        for raw, header in zip(staged, headers):
            if header is None:
                continue  # malformed frame
            document_name, message_type, offset = header
            if document_name not in documents:
                continue
            inbox = self._inboxes.setdefault(document_name, deque())
            stats["frames_received"] += 1
            if len(inbox) >= self.inbox_limit:
                # bounded inbox: the frame is DROPPED, but never
                # silently — the drain publishes an anti-entropy
                # SyncStep1 for the doc, and the resulting state
                # exchange carries everything the dropped frames did
                # (sync is state-based)
                self._overflowed.add(document_name)
                stats["inbox_overflows"] += 1
                if wire.enabled:
                    wire.record_redis_inbox_overflow()
                continue
            inbox.append((message_type, offset, raw))

    def _make_reply(self, document_name: str) -> Callable[[bytes], None]:
        def reply(response: bytes) -> None:
            self._publish_nowait(document_name, response)

        return reply

    def inbox_depth(self) -> int:
        """Queued inbound frames (the wire-telemetry depth gauge),
        staged-but-unrouted frames included."""
        return len(self._inbox_staging) + sum(
            len(inbox) for inbox in self._inboxes.values()
        )

    def _schedule_inbox_drain(self) -> None:
        if self._inbox_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # subscriber callbacks only fire inside a loop
        self._inbox_scheduled = True
        loop.call_soon(self._start_inbox_drain)

    def _start_inbox_drain(self) -> None:
        self._inbox_scheduled = False
        # route BEFORE the drain task (which serializes on _drain_lock):
        # frames keep flowing into the bounded inboxes — and overflow is
        # counted — even while a slow drain holds the lock
        self._route_staged()
        if not self._inboxes and not self._overflowed:
            return
        spawn_tracked(self._tasks, self._drain_inboxes())

    async def _drain_inboxes(self) -> None:
        """One inbound tick: per doc, decode all queued frames, merge
        contiguous update frames into ONE apply_update (one local
        fan-out tick), apply everything else in arrival order through
        the normal receiver. Serialized: two drains must not interleave
        one doc's frames."""
        async with self._drain_lock:
            while self._inbox_staging or self._inboxes or self._overflowed:
                self._route_staged()
                inboxes = self._inboxes
                overflowed = self._overflowed
                self._inboxes = {}
                self._overflowed = set()
                wire = get_wire_telemetry()
                for document_name, frames in inboxes.items():
                    document = (
                        self.instance.documents.get(document_name)
                        if self.instance is not None
                        else None
                    )
                    if document is None:
                        continue  # unloaded while queued
                    if wire.enabled:
                        wire.record_redis_inbox_drain(len(frames))
                    try:
                        await self._apply_doc_frames(document, frames)
                    except Exception:
                        logger.log_error(
                            f"[redis] inbound drain failed for {document_name!r}"
                        )
                for document_name in overflowed:
                    # anti-entropy healing for dropped frames
                    document = (
                        self.instance.documents.get(document_name)
                        if self.instance is not None
                        else None
                    )
                    if document is None:
                        continue
                    try:
                        await self._publish_join_batch(document_name, document)
                    except Exception:
                        logger.log_error(
                            f"[redis] overflow resync failed for {document_name!r}"
                        )

    @staticmethod
    def _extract_update(message_type: int, offset: int, raw: bytes) -> Optional[bytes]:
        """The update payload of a Sync/SyncReply UPDATE frame, else
        None (anything with reply or hook semantics keeps the receiver
        path)."""
        if message_type not in (MessageType.Sync, MessageType.SyncReply):
            return None
        try:
            decoder = Decoder(raw)
            decoder.pos = offset
            if decoder.read_var_uint() != MESSAGE_YJS_UPDATE:
                return None
            return decoder.read_var_uint8_array()
        except Exception:
            return None

    async def _apply_doc_frames(self, document, frames) -> None:
        stats = self.replication_stats
        pending_updates: list = []

        def flush_updates() -> None:
            if not pending_updates:
                return
            updates = list(pending_updates)
            pending_updates.clear()
            merged = coalesce_updates(updates) if len(updates) > 1 else updates[0]
            if merged is not None:
                try:
                    apply_update(document, merged, self.redis_transaction_origin)
                    stats["inbound_applies"] += 1
                    saved = len(updates) - 1
                    if saved:
                        stats["inbound_merged_saved"] += saved
                        wire = get_wire_telemetry()
                        if wire.enabled:
                            wire.record_redis_frames_saved(saved, direction="apply")
                    return
                except Exception:
                    pass  # fall through to per-update application
            for update in updates:
                try:
                    apply_update(document, update, self.redis_transaction_origin)
                    stats["inbound_applies"] += 1
                except Exception:
                    logger.log_error(
                        f"[redis] inbound update apply failed for {document.name!r}"
                    )

        for message_type, offset, raw in frames:
            update = self._extract_update(message_type, offset, raw)
            if update is not None:
                pending_updates.append(update)
                continue
            # order matters: apply buffered updates before a frame with
            # handshake/reply semantics (Step1/Step2/awareness/...)
            flush_updates()
            message = IncomingMessage(raw)
            document_name = message.read_var_string()
            message.write_var_string(document_name)
            receiver = MessageReceiver(message, self.redis_transaction_origin)
            try:
                await receiver.apply(document, None, self._make_reply(document.name))
            except Exception:
                logger.log_error(
                    f"[redis] inbound frame apply failed for {document.name!r}"
                )
        flush_updates()

    async def on_plane_broadcast(self, data: Payload) -> None:
        """Cross-instance fan-out of a serve-mode plane window: publish
        the merged update frame itself — peers apply it directly. One
        coalesced message per doc-window instead of the per-op
        SyncStep1/Step2 round trips (which remain, rate-limited, as
        anti-entropy below and as the join protocol). With coalescing
        on, the window rides the per-tick publish lane — several
        windows landing in one event-loop tick merge into one frame,
        and the publish shares the pipelined flush with every other
        channel's tick traffic."""
        if self.coalesce:
            self._queue_replication(data.document_name, [data.update])
            return
        await self._publish(
            data.document_name, build_update_frame(data.document_name, data.update)
        )

    async def on_change(self, data: Payload) -> None:
        if data.transaction_origin == self.redis_transaction_origin:
            return
        document = data.document
        source = getattr(document, "broadcast_source", None)
        capturing = source is not None and (
            not hasattr(source, "is_capturing")
            or source.is_capturing(data.document_name)
        )
        fanout = getattr(document, "fanout", None)
        coalescing = (
            self.coalesce
            and fanout is not None
            and fanout.replicate_updates is not None
        )
        if capturing or coalescing:
            # steady propagation rides the coalesced update frames (the
            # plane's window broadcasts / the CPU tick's replication
            # seam); keep a LOW-RATE SyncStep1 exchange per doc as
            # anti-entropy so a dropped pub/sub message heals instead
            # of desyncing the peer forever
            name = data.document_name
            now = asyncio.get_event_loop().time()
            last = self._last_anti_entropy.get(name, 0.0)
            if now - last < self.plane_anti_entropy_seconds:
                # TRAILING edge: the final change before quiescence must
                # still trigger one exchange after the window closes
                if name not in self._anti_entropy_handles:
                    def fire(n=name):
                        self._anti_entropy_handles.pop(n, None)
                        doc_now = (
                            self.instance.documents.get(n) if self.instance else None
                        )
                        if doc_now is not None:
                            self._last_anti_entropy[n] = asyncio.get_event_loop().time()
                            spawn_tracked(
                                self._tasks, self.publish_first_sync_step(n, doc_now)
                            )

                    self._anti_entropy_handles[name] = asyncio.get_event_loop().call_later(
                        self.plane_anti_entropy_seconds, fire
                    )
                return
            self._last_anti_entropy[name] = now
            # a pending trailing-edge timer would fire a second SyncStep1
            # right after this fresh one, busting the rate limit
            handle = self._anti_entropy_handles.pop(name, None)
            if handle is not None:
                handle.cancel()
        await self.publish_first_sync_step(data.document_name, data.document)

    async def on_disconnect(self, data: Payload) -> None:
        document_name = data.document_name
        pending = self._pending_disconnects.pop(document_name, None)
        if pending is not None:
            pending.cancel()

        def disconnect() -> None:
            self._pending_disconnects.pop(document_name, None)
            self._last_anti_entropy.pop(document_name, None)
            handle = self._anti_entropy_handles.pop(document_name, None)
            if handle is not None:
                handle.cancel()
            document = self.instance.documents.get(document_name) if self.instance else None
            if document is not None and document.get_connections_count() > 0:
                return
            spawn_tracked(self._tasks, self.sub.unsubscribe(self.get_key(document_name)))
            if document is not None:
                spawn_tracked(self._tasks, self.instance.unload_document(document))

        # Delay to allow last-minute syncs to arrive on the subscription.
        self._pending_disconnects[document_name] = asyncio.get_event_loop().call_later(
            self.disconnect_delay / 1000, disconnect
        )

    async def before_broadcast_stateless(self, data: Payload) -> None:
        message = OutgoingMessage(data.document_name).write_broadcast_stateless(data.payload)
        await self._publish(data.document_name, message.to_bytes())

    async def on_destroy(self, data: Payload) -> None:
        for handle in list(self._pending_disconnects.values()):
            handle.cancel()
        for handle in list(self._anti_entropy_handles.values()):
            handle.cancel()
        self._anti_entropy_handles.clear()
        for handle in list(self._pending_after_store.values()):
            handle.cancel()
        for held in list(self.locks.values()):
            if held.extend_handle is not None:
                held.extend_handle.cancel()
        # ship what the lane already holds: enqueue pending frames, then
        # give the publish machinery one BOUNDED chance to drain before
        # close() sheds whatever is left (pub/sub is at-most-once and
        # peers heal via anti-entropy, so a timeout here loses nothing
        # that the protocol can't recover)
        try:
            self._flush_publishes()
            waitables = [task for task in self._tasks if not task.done()]
            flush_task = getattr(self.pub, "_flush_task", None)
            if flush_task is not None and not flush_task.done():
                waitables.append(flush_task)
            if waitables:
                await asyncio.wait_for(
                    asyncio.gather(*waitables, return_exceptions=True), timeout=1.0
                )
        except Exception:
            pass
        self._pending_pub.clear()
        self._inbox_staging.clear()
        self._inboxes.clear()
        self._overflowed.clear()
        self.pub.close()
        self.sub.close()
