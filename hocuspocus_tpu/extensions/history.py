"""Version history — snapshot checkpoints with preview and restore.

Beyond the reference's surface (the reference ecosystem ships document
versioning as a paid Tiptap add-on built on the same yjs snapshot
machinery this extension uses): each loaded document gets a
GC-disabled archive replica fed by its update stream, checkpoints are
minted on demand (or on every store), and clients drive everything
over the existing stateless channel — no new wire messages.

Client -> server (JSON over a Stateless message; an optional "rid"
request id is echoed verbatim in every reply/error and in the
broadcasts the request triggers, so clients can correlate exactly):
    {"action": "history.checkpoint", "label": "before cleanup"?, "rid"?}
    {"action": "history.list", "rid"?}
    {"action": "history.preview", "id": 3, "rid"?}
    {"action": "history.restore", "id": 3, "rid"?}

Server -> client:
    {"event": "history.checkpointed", "id", "label", "ts"}   (broadcast)
    {"event": "history.versions", "versions": [{id,label,ts}]}
    {"event": "history.preview", "id", "update": "<base64>"}  (reconstruct
        with Doc() + apply_update on the client)
    {"event": "history.restored", "id"}                       (broadcast)
    {"event": "history.error", "error"}

Restore rewrites the LIVE document's root types to the checkpointed
content as ordinary edits (delete + reinsert in one transaction), so it
propagates to every client and remains undoable. Text roots keep their
formatting via delta re-application; map/array roots restore to their
JSON content; XML trees restore via deep prelim clones (elements keep
attributes and children, text keeps its formatted delta). Y-type
embeds inside text remain preview-only (one type instance cannot
belong to two docs).
"""

from __future__ import annotations

import base64
import copy
import json
import time
from typing import Any, Optional

from ..crdt import Doc, apply_update, create_doc_from_snapshot, encode_state_as_update, snapshot
from ..crdt.content import ContentFormat, ContentString, ContentType
from ..crdt.types.base import AbstractType
from ..crdt.types.ymap import YMap
from ..crdt.types.ytext import YText
from ..crdt.types.yarray import YArray
from ..crdt.update import Snapshot
from ..server.types import Extension, Payload


class _DocHistory:
    __slots__ = ("archive", "versions", "next_id", "listener", "document", "pud")

    def __init__(self) -> None:
        self.archive = Doc(gc=False)
        self.versions: list[dict] = []
        self.next_id = 1
        self.listener = None
        # the LIVE doc the listener is attached to: the unload hook's
        # payload carries only the name (the doc is already torn down)
        self.document = None
        # lazily-created PermanentUserData over the archive (one per
        # doc — each instance registers observers on the users arrays)
        self.pud = None


class History(Extension):
    """In-memory version history. `max_versions` caps retained
    checkpoints per document (oldest dropped); `checkpoint_on_store`
    also mints one whenever the store hooks run (debounced saves)."""

    def __init__(self, max_versions: int = 50, checkpoint_on_store: bool = False) -> None:
        self.max_versions = max_versions
        self.checkpoint_on_store = checkpoint_on_store
        self._docs: dict[str, _DocHistory] = {}

    # -- lifecycle ---------------------------------------------------------

    async def after_load_document(self, data: Payload) -> None:
        name = data.document_name
        if name in self._docs:
            return
        hist = _DocHistory()
        apply_update(hist.archive, encode_state_as_update(data.document), "history")

        def on_update(update: bytes, _origin: Any, *_rest: Any) -> None:
            apply_update(hist.archive, update, "history")

        hist.listener = on_update
        hist.document = data.document
        data.document.on("update", on_update)
        self._docs[name] = hist

    async def after_unload_document(self, data: Payload) -> None:
        # the unload payload carries only the NAME (the doc is already
        # torn down) — detach from the reference captured at load
        hist = self._docs.pop(data.document_name, None)
        if hist is not None and hist.listener is not None and hist.document is not None:
            try:
                hist.document.off("update", hist.listener)
            except Exception:
                pass  # the doc is being destroyed either way

    async def after_store_document(self, data: Payload) -> None:
        if self.checkpoint_on_store:
            version = self._checkpoint(data.document_name, label="store")
            document = data.get("document")
            if version is not None and document is not None:
                # store-minted versions announce themselves exactly like
                # the stateless checkpoint action does — without this,
                # clients only discovered them by polling history.list.
                # origin tags the broadcast as server-initiated so the
                # HistoryClient's rid-less fallback never mistakes it
                # for the reply to a pending checkpoint request
                document.broadcast_stateless(
                    json.dumps(
                        {"event": "history.checkpointed", "origin": "store", **version}
                    )
                )

    # -- the stateless protocol --------------------------------------------

    async def on_stateless(self, data: Payload) -> None:
        try:
            request = json.loads(data.payload)
        except (TypeError, ValueError):
            return
        action = request.get("action", "") if isinstance(request, dict) else ""
        if not action.startswith("history."):
            return
        name = data.document_name
        document = data.document
        send = data.connection.send_stateless
        # request-id echo: clients may attach a "rid"; every reply,
        # error and initiator-triggered broadcast carries it back so
        # the provider's HistoryClient resolves the EXACT pending
        # request instead of correlating by event kind + send order
        rid = request.get("rid")

        def reply(payload: dict) -> None:
            if rid is not None:
                payload = {**payload, "rid": rid}
            send(json.dumps(payload))

        def broadcast(payload: dict) -> None:
            if rid is not None:
                payload = {**payload, "rid": rid}
            document.broadcast_stateless(json.dumps(payload))

        if action in ("history.checkpoint", "history.restore") and getattr(
            data.connection, "read_only", False
        ):
            # the sync path refuses read-only updates; a restore that
            # rewrites every root (or minting checkpoints) must not be
            # a side door around that permission
            reply({"event": "history.error", "error": "read-only connection"})
            return

        if action == "history.checkpoint":
            version = self._checkpoint(name, request.get("label"))
            if version is None:
                reply({"event": "history.error", "error": "no history for document"})
                return
            broadcast({"event": "history.checkpointed", **version})
        elif action == "history.list":
            versions = [
                {"id": v["id"], "label": v["label"], "ts": v["ts"]}
                for v in self._versions(name)
            ]
            reply({"event": "history.versions", "versions": versions})
        elif action == "history.preview":
            restored = self._restore_doc(name, request.get("id"))
            if restored is None:
                reply({"event": "history.error", "error": "unknown version"})
                return
            update = base64.b64encode(encode_state_as_update(restored)).decode()
            reply(
                {"event": "history.preview", "id": request.get("id"), "update": update}
            )
        elif action == "history.diff":
            # attributed diff of a TEXT root between a version and now
            # (or between two versions): ychange added/removed runs,
            # with author names when a PermanentUserData registry is
            # replicated in the doc (root "users")
            hist = self._docs.get(name)
            if hist is None:
                reply({"event": "history.error", "error": "no history for document"})
                return
            base = self._find_version(name, request.get("id"))
            if base is None:
                reply({"event": "history.error", "error": "unknown version"})
                return
            if request.get("until") is not None:
                until = self._find_version(name, request.get("until"))
                if until is None:
                    reply({"event": "history.error", "error": "unknown 'until' version"})
                    return
            else:
                # "until now" needs a CONCRETE snapshot: removed-run
                # marking compares visibility against it (a None
                # snapshot renders plain current text, yjs semantics)
                until = snapshot(hist.archive)
            root = request.get("root", "default")
            target = hist.archive.share.get(root)
            if target is None or _classify_root(target) != "text":
                # never get_text() an unvalidated client-supplied name:
                # it would CREATE a missing root or raise retyping an
                # existing non-text one (e.g. the "users" registry)
                reply(
                    {"event": "history.error", "error": f"root {root!r} is not a text root"}
                )
                return
            compute = self._ychange_resolver(hist)
            delta = hist.archive.get_text(root).to_delta(
                until, base, compute_ychange=compute
            )
            for op in delta:
                if isinstance(op.get("insert"), AbstractType):
                    # embedded Y types are not JSON: ship their snapshot
                    op["insert"] = op["insert"].to_json()
            reply(
                {
                    "event": "history.diff",
                    "id": request.get("id"),
                    "until": request.get("until"),
                    "root": root,
                    "delta": delta,
                }
            )
        elif action == "history.restore":
            restored = self._restore_doc(name, request.get("id"))
            if restored is None:
                reply({"event": "history.error", "error": "unknown version"})
                return
            try:
                _rewrite_live_doc(document, restored)
            except _UnsupportedRestore as error:
                reply({"event": "history.error", "error": str(error)})
                return
            broadcast({"event": "history.restored", "id": request.get("id")})
        else:
            reply({"event": "history.error", "error": f"unknown action {action!r}"})

    # -- internals ---------------------------------------------------------

    def _versions(self, name: str) -> list[dict]:
        hist = self._docs.get(name)
        return hist.versions if hist is not None else []

    def _checkpoint(self, name: str, label: Optional[str] = None) -> Optional[dict]:
        hist = self._docs.get(name)
        if hist is None:
            return None
        snap = snapshot(hist.archive)
        version = {
            "id": hist.next_id,
            "label": label or f"version {hist.next_id}",
            "ts": time.time(),
            "snapshot": base64.b64encode(snap.encode()).decode(),
        }
        hist.next_id += 1
        hist.versions.append(version)
        if len(hist.versions) > self.max_versions:
            hist.versions.pop(0)
        return {k: version[k] for k in ("id", "label", "ts")}

    def _find_version(self, name: str, version_id) -> Optional[Snapshot]:
        hist = self._docs.get(name)
        if hist is None:
            return None
        version = next((v for v in hist.versions if v["id"] == version_id), None)
        if version is None:
            return None
        return Snapshot.decode(base64.b64decode(version["snapshot"]))

    def _restore_doc(self, name: str, version_id) -> Optional[Doc]:
        snap = self._find_version(name, version_id)
        if snap is None:
            return None
        return create_doc_from_snapshot(self._docs[name].archive, snap)

    def _ychange_resolver(self, hist: _DocHistory):
        """compute_ychange backed by the doc's replicated user registry
        (root "users", PermanentUserData layout); plain marks when the
        doc has none."""
        if "users" not in hist.archive.share:
            return None
        if hist.pud is None:
            from ..crdt import PermanentUserData

            hist.pud = PermanentUserData(hist.archive)

        def compute(kind: str, struct_id) -> dict:
            user = (
                hist.pud.get_user_by_deleted_id(struct_id)
                if kind == "removed"
                else hist.pud.get_user_by_client_id(struct_id.client)
            )
            out = {"type": kind}
            if user is not None:
                out["user"] = user
            return out

        return compute


class _UnsupportedRestore(Exception):
    pass


def _concrete_kind(ytype) -> Optional[str]:
    """The root's kind when its Python type already pins it; None for
    generic AbstractType roots (created by remote integrates before any
    typed access)."""
    from ..crdt.types.yxml import YXmlFragment

    # order matters: YXmlFragment before the others (YXmlElement is a
    # fragment; YXmlText/YXmlHook subclass YText/YMap and classify as
    # text/map, matching how the rewrite path addresses them)
    if isinstance(ytype, YXmlFragment):
        return "xml"
    if isinstance(ytype, YText):
        return "text"
    if isinstance(ytype, YMap):
        return "map"
    if isinstance(ytype, YArray):
        return "array"
    return None


def _classify_root(ytype, live=None) -> str:
    """Best-effort root-type classification: roots created by remote
    integrates are GENERIC AbstractType instances until typed access.

    `live`: the live document's root of the same name, if any. An
    all-tombstoned sequence carries no content to sniff (a gc-enabled
    restored doc collapses deleted typed content to GC ranges), so the
    live root's concrete type is the only trustworthy signal there —
    defaulting to 'text' mistyped emptied array/map roots and made
    restore raise mid-transaction (ADVICE.md)."""
    kind = _concrete_kind(ytype)
    if kind is not None:
        return kind
    if ytype._map and ytype._start is None:
        return "map"
    item = ytype._start
    while item is not None:
        if isinstance(item.content, (ContentString, ContentFormat)):
            return "text"
        if isinstance(item.content, ContentType):
            return "xml"
        if not item.deleted:
            return "array"
        item = item.right
    if live is not None:
        live_kind = _concrete_kind(live)
        if live_kind is not None:
            return live_kind
        # server-side roots are usually generic too (typed access only
        # ever happened client-side): sniff the live root's CONTENT —
        # it holds the post-checkpoint state the tombstoned target lost
        return _classify_root(live)
    return "text" if not ytype._map else "map"


def _clone_xml_node(node):
    """Deep-copy a restored-doc XML node into a FRESH prelim node the
    live doc can integrate (one type instance cannot belong to two
    docs). Elements keep attributes and children; text keeps its
    formatted delta."""
    from ..crdt.types.yxml import YXmlElement, YXmlText

    if isinstance(node, YXmlText):
        fresh = YXmlText()
        delta = node.to_delta()
        for op in delta:
            if isinstance(op.get("insert"), AbstractType):
                raise _UnsupportedRestore("XML text embeds a Y type: preview-only")
        if delta:
            fresh.apply_delta(delta)
        return fresh
    if isinstance(node, YXmlElement):
        fresh = YXmlElement(node.node_name)
        for key, value in node.get_attributes().items():
            if isinstance(value, AbstractType):
                raise _UnsupportedRestore(
                    "XML attribute holds a Y type: preview-only"
                )
            fresh.set_attribute(key, value)
        kids = [_clone_xml_node(child) for child in node.to_array()]
        if kids:
            fresh.push(kids)
        return fresh
    if isinstance(node, AbstractType):
        raise _UnsupportedRestore(
            f"unsupported XML child {type(node).__name__}: preview-only"
        )
    # plain values (strings, numbers, json) are legal fragment children
    return copy.deepcopy(node)


def _rewrite_live_doc(document, restored: Doc) -> None:
    """Make the live doc render the restored version, as ordinary edits
    (one transaction -> one broadcastable update; undoable)."""
    names = set(document.share.keys()) | set(restored.share.keys())
    plan: list = []
    # validate EVERYTHING before mutating: a mid-transaction refusal
    # would leave the live doc half-rewritten
    for name in sorted(names):
        target = restored.share.get(name)
        live = document.share.get(name)
        if target is not None:
            kind = _classify_root(target, live)
        else:
            kind = _classify_root(live)
        # the run() below addresses the LIVE root through typed getters
        # (get_text/get_map/...), which raise mid-transaction on a
        # differently-typed root — refuse BEFORE mutating instead
        live_kind = _concrete_kind(live) if live is not None else None
        if live_kind is not None and kind != live_kind:
            raise _UnsupportedRestore(
                f"root {name!r} is {live_kind} in the live document but "
                f"{kind} in the checkpoint"
            )
        payload = None
        if kind == "text" and target is not None:
            payload = restored.get_text(name).to_delta()
            for op in payload:
                if isinstance(op.get("insert"), AbstractType):
                    # a nested Y type from the RESTORED doc must not be
                    # re-integrated into the live doc (one instance
                    # cannot belong to two docs)
                    raise _UnsupportedRestore(
                        f"text root {name!r} embeds a Y type: preview-only"
                    )
        elif kind == "xml" and target is not None:
            payload = [
                _clone_xml_node(child)
                for child in restored.get_xml_fragment(name).to_array()
            ]
        plan.append((name, kind, target, payload))

    def run(_transaction) -> None:
        for name, kind, target, payload in plan:
            if kind == "text":
                live = document.get_text(name)
                live.delete(0, len(live))
                if payload:
                    live.apply_delta(payload)
            elif kind == "xml":
                live = document.get_xml_fragment(name)
                if len(live):
                    live.delete(0, len(live))
                if payload:
                    live.push(payload)
            elif kind == "map":
                live = document.get_map(name)
                old = restored.get_map(name).to_json() if target is not None else {}
                for key in list(live.keys()):
                    if key not in old:
                        live.delete(key)
                for key, value in old.items():
                    live.set(key, value)
            elif kind == "array":
                live = document.get_array(name)
                live.delete(0, len(live))
                old = restored.get_array(name).to_json() if target is not None else []
                if old:
                    live.insert(0, old)

    document.transact(run, origin="history.restore")
